"""Headline benchmark: LLM decode throughput per chip.

Measures steady-state decode tokens/sec of the serving engine's fused
decode+sample chunk (the same `lax.scan` executable the continuous-batching
engine dispatches, clearml_serving_tpu/llm/engine.py) on a Llama-3.2-1B-shaped
decoder in bf16 with random weights (throughput is weight-value-independent).
Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N}

vs_baseline is the ratio against the BASELINE.md north-star target of
1500 tok/s/chip (Llama-8B class on v5e); the 1B model is the round-1 flagship —
later rounds move the bench to a quantized 8B.

NOTE on timing: some remote-TPU platforms (tunneled/axon) treat
block_until_ready as a no-op — completion is only observable via a host
readback, so every timed section here ends with np.asarray of a value that
data-depends on the full computation.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.sampling import SamplingParams, sample_tokens

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    import os

    if on_tpu:
        # overridable for larger-model runs: BENCH_PRESET=llama3-8b
        # BENCH_QUANTIZE=int8 BENCH_SCAN_LAYERS=1 BENCH_BATCH=8
        cfg = {
            "preset": os.environ.get("BENCH_PRESET", "llama3-1b"),
            "dtype": "bfloat16",
            "scan_layers": os.environ.get("BENCH_SCAN_LAYERS", "").lower()
            in ("1", "true", "yes"),
        }
        batch = int(os.environ.get("BENCH_BATCH", 16))
        seq_len, chunk, rounds = 1024, 25, 4
    else:  # CPU smoke mode so the bench is runnable anywhere
        cfg = {"preset": "llama-tiny", "dtype": "float32"}
        batch, seq_len, chunk, rounds = 4, 128, 5, 2

    from clearml_serving_tpu.engines.jax_engine import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    quantize = os.environ.get("BENCH_QUANTIZE")
    if quantize == "int8":
        # int8 tree built directly (never materializes full-precision 8B);
        # the model's weight accessor dequantizes per layer inside the scan
        from clearml_serving_tpu.ops.quant import random_quantized_llama

        bundle, params = random_quantized_llama(cfg, seed=0)
    else:
        bundle = models.build_model("llama", cfg)
        params = bundle.init(jax.random.PRNGKey(0))
    cache = bundle.init_cache(batch, seq_len)
    # mid-sequence state: decode cost grows with cache occupancy; measure at
    # half-full for a steady-state figure
    cache["length"] = jnp.full((batch,), seq_len // 2, jnp.int32)

    sampling = SamplingParams(
        temperature=jnp.zeros((batch,), jnp.float32),
        top_k=jnp.zeros((batch,), jnp.int32),
        top_p=jnp.ones((batch,), jnp.float32),
    )

    def decode_chunk(params, tokens, cache, rng):
        def body(carry, step_rng):
            tokens, cache = carry
            logits, cache = bundle.decode(params, tokens, cache)
            sampled = sample_tokens(logits.astype(jnp.float32), sampling, step_rng)
            return (sampled, cache), sampled

        (tokens, cache), _ = jax.lax.scan(
            body, (tokens, cache), jax.random.split(rng, chunk)
        )
        return tokens, cache

    step = jax.jit(decode_chunk, donate_argnums=(2,))
    tokens = jnp.zeros((batch,), jnp.int32)
    rng = jax.random.PRNGKey(1)

    # warmup (compile + first execution), synced via readback
    tokens, cache = step(params, tokens, cache, rng)
    np.asarray(tokens)

    t0 = time.perf_counter()
    for _ in range(rounds):
        tokens, cache = step(params, tokens, cache, rng)
    np.asarray(tokens)  # data-dependent readback = true completion
    dt = time.perf_counter() - t0

    tok_per_sec = batch * chunk * rounds / dt
    print(
        json.dumps(
            {
                "metric": "llm_decode_throughput_{}{}_b{}".format(
                    cfg.get("preset", "llama"),
                    "-int8" if quantize == "int8" else "",
                    batch,
                ),
                "value": round(tok_per_sec, 2),
                "unit": "tok/s/chip",
                "vs_baseline": round(tok_per_sec / 1500.0, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
