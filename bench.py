"""Headline benchmark: LLM decode throughput per chip.

Measures steady-state decode tokens/sec of the serving engine's fused
decode+sample chunk (the same `lax.scan` executable the continuous-batching
engine dispatches, clearml_serving_tpu/llm/engine.py) on a Llama-3-8B-shaped
decoder (int8 weights, scan_layers) with random weights (throughput is
weight-value-independent).  Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N,
     "platform": "tpu"|"cpu", ...}

vs_baseline is the ratio against the BASELINE.md north-star target of
1500 tok/s/chip (Llama-8B class on v5e).

Robustness contract (the driver must ALWAYS capture a JSON line):
- The TPU backend on this image is a tunnel that can HANG (not error) on
  first device enumeration, so the parent process never touches the default
  backend.  It probes platform health in a subprocess with a timeout, runs
  the TPU measurement in a second subprocess with a timeout, and on any
  failure falls back to an in-process CPU smoke run (backend forced to CPU
  via jax.config.update, in-process).

Backend identity (hard-won, round 3): the TPU is tunneled through an
**experimental PJRT platform named "axon"** (see /root/.axon_site/
sitecustomize.py) and the driver environment sets ``JAX_PLATFORMS=axon``.
JAX never auto-selects an experimental platform, so stripping JAX_PLATFORMS
from a child's env makes jax.devices() return CPU even when the tunnel is
healthy — which is why rounds 1-2 never captured a TPU line.  Children that
want the TPU must INHERIT ``JAX_PLATFORMS=axon``; the probe/worker accept
platform "axon" (device_kind says TPU) as TPU.  A ``JAX_PLATFORMS`` value
naming only cpu is still stripped from children: with it present at
interpreter startup the sitecustomize PJRT registration has been observed to
hang while the tunnel is down.

NOTE on timing: some remote-TPU platforms (tunneled/axon) treat
block_until_ready as a no-op — completion is only observable via a host
readback, so every timed section here ends with np.asarray of a value that
data-depends on the full computation.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_TOK_S = 1500.0  # BASELINE.md: Llama-3-8B class, tok/s/chip on v5e

# re-exported for the probe subprocess snippet (python -c "import bench; ...")
from clearml_serving_tpu.utils.tpu import is_tpu_device  # noqa: E402

PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
# Budget for the TPU worker (cold 8B compile included — scan_layers keeps it
# to ~one layer's compile). Kept under typical driver kill-timeouts so the
# CPU fallback line still lands if the TPU attempt drags: a captured smoke
# line beats an rc=124 with no output.
TPU_TIMEOUT = float(os.environ.get("BENCH_TPU_TIMEOUT", 600))


def _measure(cfg, batch, seq_len, chunk, rounds, quantize):
    """Run the decode-throughput measurement on the current jax backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from clearml_serving_tpu import models
    from clearml_serving_tpu.engines.jax_engine import (
        enable_persistent_compilation_cache,
    )
    from clearml_serving_tpu.llm.sampling import SamplingParams, sample_tokens

    enable_persistent_compilation_cache()
    if quantize in ("int8", "int4"):
        # quantized tree built directly (never materializes full-precision
        # 8B); the model's weight accessor dequantizes per layer in the scan
        from clearml_serving_tpu.ops.quant import random_quantized_llama

        bundle, params = random_quantized_llama(
            cfg, seed=0, bits=4 if quantize == "int4" else 8
        )
    else:
        bundle = models.build_model("llama", cfg)
        params = bundle.init(jax.random.PRNGKey(0))
    cache = bundle.init_cache(batch, seq_len)
    # mid-sequence state: decode cost grows with cache occupancy; measure at
    # half-full for a steady-state figure
    cache["length"] = jnp.full((batch,), seq_len // 2, jnp.int32)

    sampling = SamplingParams(
        temperature=jnp.zeros((batch,), jnp.float32),
        top_k=jnp.zeros((batch,), jnp.int32),
        top_p=jnp.ones((batch,), jnp.float32),
    )

    def decode_chunk(params, tokens, cache, rng):
        def body(carry, step_rng):
            tokens, cache = carry
            logits, cache = bundle.decode(params, tokens, cache)
            sampled = sample_tokens(logits.astype(jnp.float32), sampling, step_rng)
            return (sampled, cache), sampled

        (tokens, cache), _ = jax.lax.scan(
            body, (tokens, cache), jax.random.split(rng, chunk)
        )
        return tokens, cache

    # TTFT: one prompt prefill + first greedy token, batch 1 (the
    # BASELINE.md target is p50 TTFT < 200 ms at prompt ~512)
    p_len = min(512, seq_len)
    ptokens = jnp.zeros((1, p_len), jnp.int32)
    pcache = bundle.init_cache(1, seq_len)
    prefill = jax.jit(bundle.prefill)
    plogits, _ = prefill(params, ptokens, jnp.asarray([p_len], jnp.int32), pcache)
    np.asarray(jnp.argmax(plogits))  # compile prefill AND argmax, readback-synced
    t0 = time.perf_counter()
    plogits, _ = prefill(params, ptokens, jnp.asarray([p_len], jnp.int32), pcache)
    first = jnp.argmax(plogits)
    np.asarray(first)
    ttft_ms = (time.perf_counter() - t0) * 1e3
    del pcache, plogits

    step = jax.jit(decode_chunk, donate_argnums=(2,))
    tokens = jnp.zeros((batch,), jnp.int32)
    rng = jax.random.PRNGKey(1)

    # warmup (compile + first execution), synced via readback
    tokens, cache = step(params, tokens, cache, rng)
    np.asarray(tokens)

    t0 = time.perf_counter()
    for _ in range(rounds):
        tokens, cache = step(params, tokens, cache, rng)
    np.asarray(tokens)  # data-dependent readback = true completion
    dt = time.perf_counter() - t0
    return batch * chunk * rounds / dt, ttft_ms


def _emit(metric, value, platform, **extra):
    # vs_baseline is only meaningful for the 8B-class TPU run; a tiny-model
    # CPU smoke number compared against the 1500 tok/s TPU target would be
    # nonsense, so report 0.0 there (the note field explains why).
    vs = round(value / TARGET_TOK_S, 4) if platform == "tpu" else 0.0
    line = {
        "metric": metric,
        "value": round(value, 2),
        "unit": "tok/s/chip",
        "vs_baseline": vs,
        "platform": platform,
    }
    line.update(extra)
    print(json.dumps(line))


def _tpu_worker() -> None:
    """Runs in a subprocess with JAX_PLATFORMS=axon inherited (the tunnel)."""
    import jax

    dev = jax.devices()[0]
    if not is_tpu_device(dev):
        raise SystemExit(
            "worker backend is {}/{} — not a TPU".format(
                dev.platform, dev.device_kind
            )
        )
    cfg = {
        "preset": os.environ.get("BENCH_PRESET", "llama3-8b"),
        "dtype": "bfloat16",
        "scan_layers": os.environ.get("BENCH_SCAN_LAYERS", "1").lower()
        in ("1", "true", "yes"),
    }
    # defaults are the best measured v5e config (benchmarks/TPU_RESULTS.jsonl
    # 2026-07-29): b32 + int8 KV = 859 tok/s vs 477 at the old b8 default
    kv_quant = os.environ.get("BENCH_KV_QUANT", "int8")
    if kv_quant and kv_quant != "none":
        cfg["kv_quant"] = kv_quant
    quantize = os.environ.get("BENCH_QUANTIZE", "int8")
    batch = int(os.environ.get("BENCH_BATCH", 32))
    seq_len = int(os.environ.get("BENCH_SEQ", 1024))
    chunk = int(os.environ.get("BENCH_CHUNK", 25))
    rounds = int(os.environ.get("BENCH_ROUNDS", 4))
    tok_s, ttft_ms = _measure(cfg, batch, seq_len, chunk, rounds, quantize)
    extra = {
        "ttft_p{}_b1_ms".format(min(512, seq_len)): round(ttft_ms, 2),
        "ttft_target_ms": 200,  # BASELINE.md target is at prompt ~512
        "backend": "{}:{}".format(dev.platform, dev.device_kind),
    }
    _emit(
        "llm_decode_throughput_{}{}{}_b{}".format(
            cfg["preset"],
            "-{}".format(quantize) if quantize else "",
            "-kv{}".format(cfg["kv_quant"]) if cfg.get("kv_quant") else "",
            batch,
        ),
        tok_s,
        "tpu",
        **extra,
    )


def _cpu_smoke(note: str) -> None:
    """In-process CPU fallback; must always succeed and emit the JSON line."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    cfg = {"preset": "llama-tiny", "dtype": "float32"}
    tok_s, ttft_ms = _measure(cfg, batch=4, seq_len=128, chunk=5, rounds=2, quantize=None)
    _emit(
        "llm_decode_throughput_llama-tiny_b4_cpusmoke",
        tok_s,
        "cpu",
        note=note,
        ttft_p128_b1_ms=round(ttft_ms, 2),
    )


def _shared_prefix_smoke() -> None:
    """Shared-prefix TTFT scenario (``--shared-prefix``): N requests share a
    long system prompt; the radix prefix cache (llm/prefix_cache.py) should
    make every warm admission prefill ONLY its non-shared tail, so warm TTFT
    drops well below cold TTFT. Runs the real continuous-batching engine on
    the paged-KV backend (shared pages map by reference) on CPU — this is a
    mechanism check (cold vs warm ratio + hit rate), not a tok/s figure.

    Knobs: BENCH_PREFIX_LEN (system prompt tokens, default 1024),
    BENCH_PREFIX_REQS (requests, default 32), BENCH_PREFIX_TAIL (per-request
    unique tail tokens, default 16). Prints ONE JSON line."""
    import asyncio

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import numpy as np  # noqa: F401

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    sys_len = int(os.environ.get("BENCH_PREFIX_LEN", 1024))
    n_req = int(os.environ.get("BENCH_PREFIX_REQS", 32))
    tail_len = int(os.environ.get("BENCH_PREFIX_TAIL", 16))
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params,
        max_batch=4,
        max_seq_len=2048,
        prefill_buckets=[128, 256, 512, 1024, 1536, 2048],
        eos_token_id=None,
        decode_steps=2,
        cache_mode="paged",
        page_size=16,
        prefix_cache=4096,
        prefix_block=64,
    )

    def run_group(seed: int):
        """One cold + (n_req - 1) warm admissions of a fresh system prompt;
        returns per-request TTFT ms (sequential: TTFT must not include
        queueing behind another admission)."""
        system = [(i * 7 + seed) % 250 for i in range(sys_len)]

        async def one(idx: int) -> float:
            tail = [(idx * 13 + j * 3 + seed) % 250 for j in range(tail_len)]
            req = GenRequest(prompt_ids=system + tail, max_new_tokens=2)
            async for _ in engine.generate(req):
                pass
            return (req.first_token_at - req.submitted_at) * 1e3

        async def group():
            return [await one(i) for i in range(n_req)]

        return asyncio.run(group())

    # warmup group: compiles every trace both paths need (cold prefill
    # bucket, page gather, tail prefill_chunk) so the measured group times
    # execution, not XLA compilation
    run_group(seed=101)
    ttfts = run_group(seed=3)
    stats = engine._prefix.stats()
    engine.stop()
    cold = ttfts[0]
    warm = sorted(ttfts[1:])
    warm_p50 = warm[len(warm) // 2] if warm else 0.0
    hits = stats["hits"]
    misses = stats["misses"]
    line = {
        "metric": "llm_shared_prefix_ttft_cpusmoke",
        "value": round(warm_p50, 2),
        "unit": "ms",
        "platform": "cpu",
        "cold_ttft_ms": round(cold, 2),
        "warm_ttft_p50_ms": round(warm_p50, 2),
        "warm_ttft_max_ms": round(warm[-1], 2) if warm else 0.0,
        "cold_warm_speedup": round(cold / warm_p50, 2) if warm_p50 else 0.0,
        "cache_hit_rate": round(hits / (hits + misses), 4) if hits + misses else 0.0,
        "prefix_len": sys_len,
        "requests": n_req,
        # prefill compute actually performed (tokens through the model):
        # cold pays the whole prompt, warm only the non-shared tail window
        "prefill_tokens_cold": sys_len + tail_len,
        "prefill_tokens_warm": sys_len + tail_len - (
            stats["hit_tokens"] // max(1, hits)
        ),
        "note": "paged radix prefix cache; warm admissions prefill only the tail",
    }
    print(json.dumps(line))


def run_pipeline_ab(
    cfg: dict,
    *,
    batch: int = 4,
    decode_steps: int = 8,
    new_tokens: int = 96,
    prompt_len: int = 12,
    max_seq_len: int = 256,
    quantize=None,
    cache_mode: str = "dense",
) -> dict:
    """Pipelined-decode A/B on the REAL continuous-batching engine: the same
    workload at TPUSERVE_PIPELINE_DEPTH=1 (serial dispatch->sync->emit) vs 2
    (double-buffered chunk dispatch with device-resident token chaining,
    docs/pipelined_decode.md). Greedy, fixed prompts, eos disabled — the
    token streams must be byte-identical across depths; the step time is
    decode wall / dispatched chunks at steady state. Returns the result row
    (shared by the ``--pipeline-ab`` CPU scenario and the TPU battery)."""
    import asyncio

    import jax
    import numpy as np  # noqa: F401

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    if quantize in ("int8", "int4"):
        from clearml_serving_tpu.ops.quant import random_quantized_llama

        bundle, params = random_quantized_llama(
            cfg, seed=0, bits=4 if quantize == "int4" else 8
        )
        quantize = None  # already applied to the tree
    else:
        bundle = models.build_model("llama", cfg)
        params = bundle.init(jax.random.PRNGKey(0))

    prompts = [
        [(7 * i + 3 + j) % 250 + 1 for j in range(prompt_len)]
        for i in range(batch)
    ]

    def measure(depth: int):
        engine = LLMEngineCore(
            bundle, params,
            max_batch=batch,
            max_seq_len=max_seq_len,
            prefill_buckets=[max(16, prompt_len)],
            eos_token_id=None,        # run to max_new_tokens: fixed work
            decode_steps=decode_steps,
            cache_mode=cache_mode,
            pipeline_depth=depth,
        )

        async def one(ids):
            req = GenRequest(
                prompt_ids=ids, max_new_tokens=new_tokens, temperature=0.0
            )
            return [t async for t in engine.generate(req)]

        async def group():
            outs = await asyncio.gather(*(one(p) for p in prompts))
            await engine.wait_drained()
            return outs

        # warmup: compile every trace (prefill bucket + decode chunk), then
        # measure a steady-state group. Step time divides by the DISPATCH
        # count actually issued (ragged admissions can add a partial chunk;
        # charging it to one depth only would skew the A/B).
        asyncio.run(group())
        seq0 = engine._dispatch_seq
        t0 = time.perf_counter()
        outs = asyncio.run(group())
        wall = time.perf_counter() - t0
        chunks = engine._dispatch_seq - seq0
        engine.stop()
        return outs, wall, max(1, chunks)

    outs1, wall1, chunks1 = measure(1)
    outs2, wall2, chunks2 = measure(2)
    toks = batch * new_tokens
    step1_ms = wall1 / chunks1 * 1e3
    step2_ms = wall2 / chunks2 * 1e3
    cpus = os.cpu_count() or 1
    return {
        "metric": "llm_pipelined_decode_ab",
        "value": round((1.0 - step2_ms / step1_ms) * 100.0, 2),
        "unit": "% step-time reduction (depth 2 vs 1)",
        "step_ms_depth1": round(step1_ms, 3),
        "step_ms_depth2": round(step2_ms, 3),
        "chunks_depth1": chunks1,
        "chunks_depth2": chunks2,
        "tok_s_depth1": round(toks / wall1, 2),
        "tok_s_depth2": round(toks / wall2, 2),
        "speedup": round(wall1 / wall2, 4),
        "identical_tokens": outs1 == outs2,
        # on mismatch: per-request (len1, len2, first-diff-index) triples —
        # enough to tell a lost/duplicated token from a value divergence
        "mismatch_detail": (
            None
            if outs1 == outs2
            else [
                (
                    len(a),
                    len(b),
                    next(
                        (i for i, (x, y) in enumerate(zip(a, b)) if x != y),
                        min(len(a), len(b)),
                    ),
                )
                for a, b in zip(outs1, outs2)
                if a != b
            ]
        ),
        "batch": batch,
        "decode_steps": decode_steps,
        "new_tokens": new_tokens,
        "cache": cache_mode,
        "cpus": cpus,
        # pipelining hides chunk N's host-side retire (readback + emission)
        # behind chunk N+1's device compute. A single-core host has nothing
        # to hide behind — every cycle is already useful work — so the A/B
        # there measures pipeline overhead (~0), not the overlap win.
        "note": (
            "single-core host: overlap win not observable; expect >=10% "
            "only with >=2 cores or a real accelerator"
            if cpus == 1
            else "depths overlap retire host work with device compute"
        ),
    }


def run_ragged_ab(
    cfg: dict,
    *,
    batch: int = 4,
    decode_steps: int = 2,
    new_tokens: int = 48,
    decode_prompt_len: int = 12,
    admit_prompt_len: int = 160,
    step_token_budget: int = 32,
    chunk: int = 8,
    max_seq_len: int = 512,
    cache_mode: str = "paged",
    page_size: int = 16,
    repeats: int = 3,
) -> dict:
    """Ragged-vs-two-dispatch A/B on the REAL engine
    (docs/ragged_attention.md): ``batch-1`` short-prompt requests decode
    continuously; once every stream is flowing, ONE long-prompt request is
    admitted. The legacy arm runs the historical two-dispatch scheduler
    (chunked prefill paced by the prefill gate); the ragged arm runs the
    token-budget scheduler, whose mixed launches carry the admission as
    chunk rows BESIDE the decode rows.

    Headline: ``decode_stall_ms`` — the worst inter-token gap any live
    decode stream sees inside the admission window (submit .. first token
    of the admitted request). Two-dispatch serializes the admission's
    prefill dispatches against decode chunks on one device queue, so the
    gap grows with the prompt; ragged bounds it near one mixed-step time.
    Also reports the admitted request's TTFT, per-arm TTFT p50/p99 across
    all requests, token-weighted batch occupancy, tok/s, and stream
    byte-identity across the arms (greedy; both arms chunk EVERY prompt —
    full prefill differs from chunked numerically under kv_quant)."""
    import asyncio

    import numpy as np  # noqa: F401

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    decode_prompts = [
        [(7 * i + 3 + j) % 250 + 1 for j in range(decode_prompt_len)]
        for i in range(batch - 1)
    ]
    admit_prompt = [(11 * j + 5) % 250 + 1 for j in range(admit_prompt_len)]
    buckets = sorted({
        max(16, decode_prompt_len),
        min(max_seq_len, 1 << (admit_prompt_len - 1).bit_length()),
    })

    def measure(mode: str):
        extra = (
            dict(chunked_prefill_size=chunk)
            if mode == "two_dispatch"
            else dict(scheduler="ragged", step_token_budget=step_token_budget)
        )
        engine = LLMEngineCore(
            bundle, params,
            max_batch=batch,
            max_seq_len=max_seq_len,
            prefill_buckets=buckets,
            eos_token_id=None,      # fixed work per stream
            decode_steps=decode_steps,
            cache_mode=cache_mode,
            page_size=page_size,
            **extra,
        )
        stamps: dict = {}
        occupancy: list = []

        async def one(key, ids, n):
            req = GenRequest(
                prompt_ids=list(ids), max_new_tokens=n, temperature=0.0
            )
            out = []
            stamps[key] = {"submit": time.perf_counter(), "tokens": []}
            async for tok in engine.generate(req):
                stamps[key]["tokens"].append(time.perf_counter())
                occupancy.append(engine.active_slots)
                out.append(tok)
            return out

        async def group():
            decode_tasks = [
                asyncio.create_task(one(i, p, new_tokens))
                for i, p in enumerate(decode_prompts)
            ]
            # wait until every decode stream is live before admitting
            while not all(
                len(stamps.get(i, {}).get("tokens", ())) >= 2
                for i in range(len(decode_prompts))
            ):
                await asyncio.sleep(0.002)
            t_admit = time.perf_counter()
            long_out = await one("admit", admit_prompt, new_tokens // 2)
            outs = [await t for t in decode_tasks]
            await engine.wait_drained()
            return outs + [long_out], t_admit

        # warmup group: compile every trace (prefill buckets, ragged step
        # variants, decode chunk) so the measured windows time scheduling,
        # not XLA compiles. Then ``repeats`` measured groups — the stall /
        # TTFT metrics take the MEDIAN across groups so one scheduler hiccup
        # on a noisy host cannot write the headline.
        asyncio.run(group())
        stalls, admit_ttfts, ttft_lists, tok_rates, occs = [], [], [], [], []
        outs = None
        for _ in range(max(1, repeats)):
            stamps.clear()
            occupancy.clear()
            t0 = time.perf_counter()
            outs, t_admit = asyncio.run(group())
            wall = time.perf_counter() - t0
            t_first_long = stamps["admit"]["tokens"][0]
            # worst inter-token gap any decode stream saw inside the
            # admission window (including the wait from the window's edges
            # to the neighboring emissions)
            stall = 0.0
            for i in range(len(decode_prompts)):
                ts = stamps[i]["tokens"]
                if not ts:
                    continue
                points = [t_admit] + [
                    t for t in ts if t_admit <= t <= t_first_long
                ] + [min(t_first_long, ts[-1])]
                for a, b in zip(points, points[1:]):
                    if b > a:
                        stall = max(stall, b - a)
            stalls.append(stall)
            admit_ttfts.append(t_first_long - stamps["admit"]["submit"])
            ttft_lists.append(sorted(
                s["tokens"][0] - s["submit"]
                for s in stamps.values()
                if s["tokens"]
            ))
            tok_rates.append(sum(len(o) for o in outs) / wall)
            occs.append(sum(occupancy) / max(1, len(occupancy)))
        engine.stop()

        def med(xs):
            return sorted(xs)[len(xs) // 2]

        ttfts = ttft_lists[stalls.index(med(stalls))]

        def pct(p):
            return ttfts[min(len(ttfts) - 1, int(p * (len(ttfts) - 1)))]

        return {
            "outs": outs,
            "decode_stall_ms": round(med(stalls) * 1e3, 3),
            "admit_ttft_ms": round(med(admit_ttfts) * 1e3, 3),
            "ttft_p50_ms": round(pct(0.50) * 1e3, 3),
            "ttft_p99_ms": round(pct(0.99) * 1e3, 3),
            "occupancy": round(med(occs), 3),
            "tok_s": round(med(tok_rates), 2),
        }

    legacy = measure("two_dispatch")
    ragged = measure("ragged")
    identical = legacy.pop("outs") == ragged.pop("outs")
    return {
        "metric": "llm_ragged_scheduler_ab",
        # headline: how much of the admission-window decode stall the
        # ragged scheduler removes
        "value": round(
            (1.0 - (
                ragged["decode_stall_ms"]
                / max(1e-9, legacy["decode_stall_ms"])
            )) * 100.0,
            2,
        ),
        "unit": "% decode-stall reduction during admission (ragged vs "
                "two-dispatch)",
        "two_dispatch": legacy,
        "ragged": ragged,
        "identical_tokens": identical,
        "batch": batch,
        "decode_steps": decode_steps,
        "new_tokens": new_tokens,
        "admit_prompt_len": admit_prompt_len,
        "step_token_budget": step_token_budget,
        "chunked_prefill_size": chunk,
        "cache": cache_mode,
        "cpus": os.cpu_count() or 1,
        "note": (
            "two-dispatch admission prefill runs in a worker thread but "
            "shares the device (and on CPU, the core) with decode chunks; "
            "ragged carries it as chunk rows of the decode launch itself"
        ),
    }


def run_ragged_decode_steps_ab(
    cfg: dict,
    *,
    q: int = 4,
    new_tokens: int = 96,
    decode_prompt_len: int = 12,
    admit_prompt_len: int = 24,
    step_token_budget: int = 48,
    max_seq_len: int = 256,
    cache_mode: str = "paged",
    page_size: int = 16,
) -> dict:
    """Multi-step ragged decode-row A/B (docs/ragged_attention.md, ISSUE
    13): one long greedy decode stream rides the ragged scheduler's mixed
    launches while a continuous trickle of short admissions keeps the loop
    in ragged phases — the steady decode-while-admitting state where q=1
    rows pay ONE dispatch per token. The arms differ only in
    ``ragged_decode_steps`` (1 vs ``q``); the headline is
    dispatches-per-decode-token (ragged launches / decode tokens advanced
    by ragged launches) with the stream's tok/s beside it, and the streams
    must be byte-identical across arms (greedy)."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    stream_prompt = [(7 * j + 3) % 250 + 1 for j in range(decode_prompt_len)]
    admit_prompt = [(11 * j + 5) % 250 + 1 for j in range(admit_prompt_len)]
    buckets = sorted({
        max(16, decode_prompt_len),
        max(16, 1 << (admit_prompt_len - 1).bit_length()),
    })

    def measure(steps: int):
        engine = LLMEngineCore(
            bundle, params,
            max_batch=3, max_seq_len=max_seq_len, prefill_buckets=buckets,
            eos_token_id=None, decode_steps=max(4, q),
            ragged_decode_steps=steps, scheduler="ragged",
            step_token_budget=step_token_budget,
            cache_mode=cache_mode, page_size=page_size,
        )

        async def group():
            out: list = []
            done = asyncio.Event()

            async def stream():
                req = GenRequest(
                    prompt_ids=list(stream_prompt),
                    max_new_tokens=new_tokens, temperature=0.0,
                )
                async for tok in engine.generate(req):
                    out.append(tok)
                done.set()

            async def feeder():
                while not done.is_set():
                    req = GenRequest(
                        prompt_ids=list(admit_prompt),
                        max_new_tokens=1, temperature=0.0,
                    )
                    async for _ in engine.generate(req):
                        pass

            await asyncio.gather(stream(), feeder())
            await engine.wait_drained()
            return out

        asyncio.run(group())            # warmup pass: compiles every trace
        base = dict(engine.counters)
        t0 = time.perf_counter()
        out = asyncio.run(group())
        wall = time.perf_counter() - t0
        launches = engine.counters["ragged_steps"] - base["ragged_steps"]
        dec_tokens = (
            engine.counters["ragged_decode_tokens"]
            - base["ragged_decode_tokens"]
        )
        snap = engine.lifecycle_stats()["ragged"]["tokens_per_launch"]
        engine.stop()
        return {
            "out": out,
            "tok_s": round(len(out) / wall, 2),
            "ragged_launches": launches,
            "ragged_decode_tokens": dec_tokens,
            "dispatches_per_decode_token": round(
                launches / max(1, dec_tokens), 3
            ),
            "tokens_per_launch_mean": round(
                snap["sum_ms"] / max(1, snap["count"]), 2
            ),
        }

    one = measure(1)
    multi = measure(q)
    identical = one.pop("out") == multi.pop("out")
    return {
        "metric": "llm_ragged_decode_steps_ab",
        # headline: dispatch-bubble amortization — how many launches each
        # decode token costs at q vs 1
        "value": multi["dispatches_per_decode_token"],
        "unit": "ragged launches per decode token at q={}".format(q),
        "q1": one,
        "q{}".format(q): multi,
        "decode_steps": q,
        "identical_tokens": identical,
        "new_tokens": new_tokens,
        "step_token_budget": step_token_budget,
        "cache": cache_mode,
        "cpus": os.cpu_count() or 1,
    }


def run_spec_row_ab(
    cfg: dict,
    *,
    spec_k: int = 3,
    spec_ngram: int = 2,
    batch: int = 3,
    new_tokens: int = 64,
    step_token_budget: int = 16,
    max_seq_len: int = 256,
    cache_mode: str = "paged",
    page_size: int = 16,
) -> dict:
    """Spec-as-row vs legacy serial spec (docs/ragged_attention.md, ISSUE
    13): the same repetitive-prompt greedy workload (n-gram-friendly, so
    drafts accept) on the two-dispatch scheduler's serial draft-verify
    scan vs the ragged scheduler's in-launch q=k+1 verify rows. Streams
    must be byte-identical; reports tok/s per arm and the ragged arm's
    measured per-launch acceptance.

    Read the CPU tok/s comparison with care: off-TPU the ragged pass is
    the XLA reference, which computes the FULL budget-padded token axis
    every launch (the Pallas kernel skips q-blocks no row owns), and the
    legacy scan amortizes its ONE dispatch over decode_steps draft-verify
    rounds while spec-as-row verifies one window per launch — on a 1-core
    CPU, where a dispatch costs ~nothing and compute is everything, the
    serial scan wins tok/s by construction. What spec-as-row buys is what
    the scan structurally cannot do: verify rows ride MIXED launches
    beside decode windows and admission chunks (no pipeline drain, no
    whole-batch stall while one request speculates), which is the
    tunnel-dispatch-bound TPU regime's win; the CPU arm certifies stream
    identity and acceptance parity, not throughput."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    prompts = [
        ([(5 * i + 3) % 29 + 1] * 3 + [(3 * i + 7) % 29 + 1] * 2) * 4
        for i in range(batch)
    ]

    def measure(mode: str):
        extra = (
            dict(chunked_prefill_size=8)
            if mode == "two_dispatch"
            else dict(scheduler="ragged", step_token_budget=step_token_budget)
        )
        engine = LLMEngineCore(
            bundle, params,
            max_batch=batch, max_seq_len=max_seq_len,
            prefill_buckets=[32], eos_token_id=None, decode_steps=4,
            speculation="ngram", spec_k=spec_k, spec_ngram=spec_ngram,
            cache_mode=cache_mode, page_size=page_size, **extra,
        )

        async def group():
            async def one(ids):
                req = GenRequest(
                    prompt_ids=list(ids), max_new_tokens=new_tokens,
                    temperature=0.0,
                )
                return [t async for t in engine.generate(req)]

            outs = await asyncio.gather(*(one(p) for p in prompts))
            await engine.wait_drained()
            return outs

        asyncio.run(group())            # warmup pass
        t0 = time.perf_counter()
        outs = asyncio.run(group())
        wall = time.perf_counter() - t0
        row = {
            "outs": outs,
            "tok_s": round(sum(len(o) for o in outs) / wall, 2),
        }
        if mode == "ragged":
            s = engine.lifecycle_stats()["ragged"]
            row["spec_verify_rows"] = s["step_rows"]["spec_verify"]
            snap = s["spec_acceptance"]
            row["acceptance_mean"] = round(
                snap["sum_ms"] / max(1, snap["count"]), 3
            )
        engine.stop()
        return row

    legacy = measure("two_dispatch")
    ragged = measure("ragged")
    identical = legacy.pop("outs") == ragged.pop("outs")
    return {
        "metric": "llm_spec_row_ab",
        "value": round(
            (ragged["tok_s"] / max(1e-9, legacy["tok_s"]) - 1.0) * 100.0, 2
        ),
        "unit": "% tok/s, spec-as-row vs legacy serial spec scan",
        "legacy_spec": legacy,
        "spec_as_row": ragged,
        "identical_tokens": identical,
        "spec_k": spec_k,
        "batch": batch,
        "cache": cache_mode,
        "cpus": os.cpu_count() or 1,
    }


def run_spec_tree_ab(
    cfg: dict,
    *,
    spec_k: int = 4,
    spec_ngram: int = 1,
    spec_branch: int = 2,
    batch: int = 3,
    new_tokens: int = 64,
    step_token_budget: int = 20,
    max_seq_len: int = 256,
    cache_mode: str = "paged",
    page_size: int = 16,
) -> dict:
    """Draft-tree vs draft-chain verify rows at EQUAL verify budget
    (docs/spec_decode_trees.md, ISSUE 20): three arms of the same greedy
    workload on the ragged scheduler — no speculation, the n-gram CHAIN
    proposer at k, and the n-gram FOREST proposer at the same k with up
    to ``spec_branch`` root branches. Every verify row costs k+1 query
    positions in both spec arms; the forest only re-shapes WHICH drafts
    fill them. The headline is accepted decode tokens per ragged launch
    (ragged_decode_tokens / ragged_steps over the measured pass): the
    acceptance-rate gap closes exactly insofar as the tree arm commits
    more tokens from the same launch budget. Streams must be
    byte-identical across all three arms (greedy acceptance is
    exact-match; speculation may never change output).

    The workload is ambiguity-rich by construction: each prompt repeats
    an n-gram context with TWO distinct continuations, so the
    most-recent-match chain draft is sometimes wrong while an older
    match carries the answer — the regime the forest's depth-1 siblings
    exist for (``spec_ngram`` defaults to 1, where generated streams
    keep re-visiting ambiguous contexts). On unambiguous history the
    forest dedups to the chain drafts and the arms tie; do not expect a
    gap on a clean cycling tail."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    # probed against llama-tiny's greedy continuations: each prompt's
    # generated stream re-visits single-token contexts with more than one
    # continuation in history (replaying both proposers offline over the
    # streams shows the forest strictly ahead), without collapsing into a
    # period-1 tail where both arms saturate and tie
    seeds = [
        [4, 288, 161, 312, 4, 288, 312, 161, 4, 288, 161, 312, 4, 288],
        [5, 9, 3, 11, 5, 9, 3, 11, 5, 9, 3, 11, 5, 9],
        [12, 4, 8, 21, 12, 4, 8, 21, 12, 4, 8, 21, 12, 4],
    ]
    prompts = [list(seeds[i % len(seeds)]) for i in range(batch)]

    arm_kwargs = {
        "none": {},
        "chain": dict(speculation="ngram", spec_k=spec_k,
                      spec_ngram=spec_ngram),
        "tree": dict(speculation="ngram", spec_k=spec_k,
                     spec_ngram=spec_ngram, spec_tree=True,
                     spec_branch=spec_branch),
    }

    def measure(mode: str):
        from clearml_serving_tpu.llm import compile_sentry

        if compile_sentry.enabled():
            # the sentry is process-wide: drop the previous arm's fence so
            # this arm's warmup compiles count as warmup, not serving
            compile_sentry.get().reset()
        engine = LLMEngineCore(
            bundle, params,
            max_batch=batch, max_seq_len=max_seq_len, prefill_buckets=[16],
            eos_token_id=None, decode_steps=4, scheduler="ragged",
            step_token_budget=step_token_budget,
            cache_mode=cache_mode, page_size=page_size, **arm_kwargs[mode],
        )

        async def group():
            async def one(ids):
                req = GenRequest(
                    prompt_ids=list(ids), max_new_tokens=new_tokens,
                    temperature=0.0,
                )
                return [t async for t in engine.generate(req)]

            outs = await asyncio.gather(*(one(p) for p in prompts))
            await engine.wait_drained()
            return outs

        async def warm():
            # registry sweep (pins the per-arm compile surface, including
            # the tree-arg kernel variant for the tree arm); the fence is
            # set manually AFTER the trace pass below, so the MEASURED
            # pass is what the strict sentry certifies compile-free
            from clearml_serving_tpu.llm.warmup import run_warmup

            return await run_warmup(engine, full=True, fence=False)

        asyncio.run(warm())
        asyncio.run(group())            # warmup pass: compiles every trace
        if engine._compile_sentry is not None:
            engine._compile_sentry.fence()
        base = dict(engine.counters)
        t0 = time.perf_counter()
        outs = asyncio.run(group())
        wall = time.perf_counter() - t0
        launches = engine.counters["ragged_steps"] - base["ragged_steps"]
        dec_tokens = (
            engine.counters["ragged_decode_tokens"]
            - base["ragged_decode_tokens"]
        )
        s = engine.lifecycle_stats()["ragged"]
        row = {
            "outs": outs,
            "tok_s": round(sum(len(o) for o in outs) / wall, 2),
            "ragged_launches": launches,
            "ragged_decode_tokens": dec_tokens,
        }
        if mode != "none":
            # pure-decode steps in the no-spec arm bypass the ragged
            # mixed-launch path, so per-launch accounting only compares
            # the two spec arms (whose verify rows always ride launches)
            row["accepted_tokens_per_launch"] = round(
                dec_tokens / max(1, launches), 3
            )
            row["dispatches_per_decode_token"] = round(
                launches / max(1, dec_tokens), 3
            )
            row["spec_verify_rows"] = s["step_rows"]["spec_verify"]
            snap = s["spec_acceptance"]
            row["acceptance_mean"] = round(
                snap["sum_ms"] / max(1, snap["count"]), 3
            )
            prop = s["spec_proposer"]
            row["proposer"] = {
                k: prop[k] for k in ("name", "proposed", "hit", "branched")
                if k in prop
            }
        if mode == "tree":
            snap = s["spec_tree_depth"]
            row["accept_depth_mean"] = round(
                snap["sum_ms"] / max(1, snap["count"]), 3
            )
            row["tree_fallbacks"] = s["spec_tree_fallbacks"]
        # per-arm certification (the slo_loadtest pattern): the sanitizer
        # is per-engine; the compile sentry is process-wide but reset at
        # the top of the arm, so "serve" counts exactly the compiles the
        # measured pass triggered past this arm's fence. In strict mode a
        # violation raises mid-run — completing at all is the certificate.
        sanitizer = engine._sanitizer
        san = (
            sanitizer.stats() if sanitizer is not None
            else {"checks": 0, "failures": -1}
        )
        sentry = engine._compile_sentry
        sen = (
            sentry.stats_brief() if sentry is not None
            else {"mode": "off", "serve": -1, "fenced": False}
        )
        row["certs"] = {
            "sanitizer_checks": san.get("checks", 0),
            "sanitizer_violations": san.get("failures", 0),
            "post_warmup_compiles": sen.get("serve", -1),
            "compile_sentry_mode": sen.get("mode", "off"),
        }
        engine.stop()
        return row

    none = measure("none")
    chain = measure("chain")
    tree = measure("tree")
    identical = (
        none.pop("outs") == chain.pop("outs") == tree.pop("outs")
    )
    # process-wide sentries (ownership ledger, sharding sentry) read ONCE
    # after all three arms — their counts span the whole run, and strict
    # mode already failed the run on the first violation
    from clearml_serving_tpu.llm import lifecycle_ledger, sharding_sentry

    ledger = lifecycle_ledger.arm() if lifecycle_ledger.enabled() else None
    led = (
        ledger.stats() if ledger is not None
        else {"strict": False, "leaks": -1, "double_releases": -1}
    )
    shard = sharding_sentry.arm() if sharding_sentry.enabled() else None
    shd = (
        shard.stats_brief() if shard is not None
        else {"strict": False, "implicit_transfers": -1,
              "unplanned_reshards": -1}
    )
    arm_certs = [none["certs"], chain["certs"], tree["certs"]]
    certs = {
        "sanitizer_checks": sum(c["sanitizer_checks"] for c in arm_certs),
        "sanitizer_violations": (
            -1 if any(c["sanitizer_violations"] < 0 for c in arm_certs)
            else sum(c["sanitizer_violations"] for c in arm_certs)
        ),
        "post_warmup_compiles": (
            -1 if any(c["post_warmup_compiles"] < 0 for c in arm_certs)
            else sum(c["post_warmup_compiles"] for c in arm_certs)
        ),
        "compile_sentry_mode": arm_certs[0]["compile_sentry_mode"],
        "leaks": (
            led.get("leaks", -1) + led.get("double_releases", 0)
            if led.get("leaks", -1) >= 0 else -1
        ),
        "ledger_mode": (
            "strict" if led.get("strict")
            else ("count" if ledger is not None else "off")
        ),
        "implicit_transfers": shd.get("implicit_transfers", -1),
        "unplanned_reshards": shd.get("unplanned_reshards", -1),
        "shard_sentry_mode": (
            "strict" if shd.get("strict")
            else ("count" if shard is not None else "off")
        ),
    }
    return {
        "metric": "llm_spec_tree_ab",
        # headline: the acceptance-gap close — extra committed tokens per
        # launch the tree buys from the SAME k+1 verify budget
        "value": round(
            tree["accepted_tokens_per_launch"]
            - chain["accepted_tokens_per_launch"], 3
        ),
        "unit": "accepted decode tokens per ragged launch, tree minus "
                "chain at equal k+1 verify budget",
        "no_spec": none,
        "chain": chain,
        "tree": tree,
        "identical_tokens": identical,
        "certs": certs,
        "spec_k": spec_k,
        "spec_branch": spec_branch,
        "batch": batch,
        "cache": cache_mode,
        "cpus": os.cpu_count() or 1,
    }


def run_kv_tier_ab(
    cfg: dict,
    *,
    n_prefixes: int = 3,
    prefix_len: int = 768,
    tail_len: int = 12,
    new_tokens: int = 8,
    decode_tokens: int = 48,
    page_size: int = 16,
    prefix_block: int = 16,
    device_cache_pages: int = 48,
    host_pages: int = 160,
    max_seq_len: int = 832,
    num_pages: int = 192,
) -> dict:
    """Host-RAM KV tiering A/B on the real engine (docs/kv_tiering.md):
    a constrained-HBM trace whose shared-prefix WORKING SET exceeds the
    device-side prefix-cache budget. Two engines differ only in the host
    tier: the tiered arm demotes evicted runs to pinned host RAM and
    re-onlines them on a hit via async DMA; the untiered arm drops them
    (the pre-tier behavior) and every revisit of an evicted prefix pays a
    cold prefill.

    Reports warm-TTFT by serving tier {hbm hit, host hit, cold}, the
    promotion DMA overlap ratio (share of the copy hidden behind other
    device work, observed at retire reaps), tok/s of a decode stream
    running CONCURRENTLY with the warm sweep, and stream byte-identity: a
    demoted-then-promoted run must produce the same tokens as the
    always-resident warm hit, under the armed KV sanitizer."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    os.environ.setdefault("TPUSERVE_SANITIZE", "1")
    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    prompts = [
        [(13 * i + 5 * j) % 250 + 1 for j in range(prefix_len + tail_len)]
        for i in range(n_prefixes)
    ]
    decode_prompt = [(17 * j + 11) % 250 + 1 for j in range(tail_len)]
    blocks_per_prompt = (prefix_len + tail_len - 1) // prefix_block
    working_set_pages = n_prefixes * blocks_per_prompt * (
        prefix_block // page_size
    )

    def measure(tiered: bool):
        engine = LLMEngineCore(
            bundle, params,
            max_batch=2,
            max_seq_len=max_seq_len,
            # the cold bucket covers the whole prompt; the warm tail rides
            # the small chunk bucket (prefix + one chunk must fit too)
            prefill_buckets=[16, 32, 64, prefix_len + 2 * prefix_block],
            eos_token_id=None,
            decode_steps=2,
            cache_mode="paged",
            page_size=page_size,
            num_pages=num_pages,
            prefix_cache=256,
            prefix_block=prefix_block,
            prefix_cache_pages=device_cache_pages,
            prefix_cache_host_pages=host_pages if tiered else None,
        )

        async def one(ids, n, stamps=None):
            req = GenRequest(
                prompt_ids=list(ids), max_new_tokens=n, temperature=0.0
            )
            out, t0 = [], time.perf_counter()
            async for tok in engine.generate(req):
                if stamps is not None:
                    stamps.append(time.perf_counter())
                elif not out:
                    stamps_first[0] = time.perf_counter() - t0
                out.append(tok)
            return out

        stamps_first = [0.0]

        async def one_drained(ids, n):
            out = await one(ids, n)
            # each sequential request runs in its own asyncio.run: the
            # engine loop must drain before that event loop closes
            await engine.wait_drained()
            return out

        def timed(ids, n=new_tokens):
            """(stream, ttft_s, tier) — tier classified from the cache's
            hit counters around the request."""
            s0 = engine._prefix.stats()
            stream = asyncio.run(one_drained(ids, n))
            s1 = engine._prefix.stats()
            if s1["hits_by_tier"]["host"] > s0["hits_by_tier"]["host"]:
                tier = "host"
            elif s1["hits_by_tier"]["hbm"] > s0["hits_by_tier"]["hbm"]:
                tier = "hbm"
            else:
                tier = "cold"
            return stream, stamps_first[0], tier

        # warmup: compile every shape off the clock (prefill buckets, the
        # radix-hit gather + tail chunk, decode chunk, promotion scatter)
        warm_ids = [(3 * j + 7) % 250 + 1 for j in range(prefix_len + tail_len)]
        timed(warm_ids)
        timed(warm_ids)
        if tiered:
            engine._prefix.spill(0)
            timed(warm_ids)  # host-hit shapes (promotion scatter) compile
        # cold pass: working set exceeds the device budget, so the tiered
        # arm demotes older runs as it goes and the untiered arm drops them
        cold_ttfts, cold_streams = [], []
        for ids in prompts:
            stream, ttft, _tier = timed(ids)
            cold_ttfts.append(ttft)
            cold_streams.append(stream)
        # byte-identity pair on the LAST prefix (still resident): resident
        # warm hit vs demoted-then-promoted warm hit
        resident_stream, resident_ttft, resident_tier = timed(prompts[-1])
        identical = True
        if tiered:
            engine._prefix.spill(0)
            promoted_stream, _t, promoted_tier = timed(prompts[-1])
            identical = (
                promoted_stream == resident_stream
                and promoted_tier == "host"
                and resident_tier == "hbm"
            )
        # warm sweep over the whole working set with a CONCURRENT decode
        # stream (does the promotion DMA steal from live decodes?)
        sweep: dict = {"ttft": {"hbm": [], "host": [], "cold": []},
                       "hits": {"hbm": 0, "host": 0, "cold": 0}}
        decode_stamps: list = []

        async def sweep_group():
            decode_task = asyncio.create_task(
                one(decode_prompt, decode_tokens, stamps=decode_stamps)
            )
            while len(decode_stamps) < 2:
                await asyncio.sleep(0.002)
            for ids in prompts:
                s0 = engine._prefix.stats()
                t0 = time.perf_counter()
                req = GenRequest(
                    prompt_ids=list(ids), max_new_tokens=new_tokens,
                    temperature=0.0,
                )
                first = None
                async for _tok in engine.generate(req):
                    if first is None:
                        first = time.perf_counter() - t0
                s1 = engine._prefix.stats()
                if s1["hits_by_tier"]["host"] > s0["hits_by_tier"]["host"]:
                    tier = "host"
                elif s1["hits_by_tier"]["hbm"] > s0["hits_by_tier"]["hbm"]:
                    tier = "hbm"
                else:
                    tier = "cold"
                sweep["ttft"][tier].append(first)
                sweep["hits"][tier] += 1
            await decode_task
            await engine.wait_drained()

        asyncio.run(sweep_group())
        decode_tok_s = (
            (len(decode_stamps) - 1)
            / max(1e-9, decode_stamps[-1] - decode_stamps[0])
        )
        tier_stats = (engine.lifecycle_stats() or {}).get("kv_tier") or {}
        sanitizer = (
            engine._sanitizer.stats()
            if engine._sanitizer is not None
            else {"checks": 0, "failures": 0}
        )
        engine.stop()

        def med(xs):
            xs = sorted(xs)
            return round(xs[len(xs) // 2] * 1e3, 3) if xs else None

        return {
            "cold_streams": cold_streams,
            "identical": identical,
            "ttft_ms": {
                "cold": med(cold_ttfts),
                "hbm": med(sweep["ttft"]["hbm"]),
                "host": med(sweep["ttft"]["host"]),
                "warm_cold": med(sweep["ttft"]["cold"]),
            },
            "warm_hits": dict(sweep["hits"]),
            "decode_tok_s": round(decode_tok_s, 2),
            "promo_overlap_ratio": tier_stats.get("promo_overlap_ratio"),
            "demotions": tier_stats.get("demotions", 0),
            "promotions": tier_stats.get("promotions", 0),
            "sanitizer_checks": sanitizer["checks"],
            "sanitizer_violations": sanitizer["failures"],
        }

    tiered = measure(True)
    untiered = measure(False)
    identical = (
        tiered.pop("identical")
        and tiered["cold_streams"] == untiered["cold_streams"]
    )
    untiered.pop("identical", None)
    tiered.pop("cold_streams")
    untiered.pop("cold_streams")
    cold = tiered["ttft_ms"]["cold"]
    host = tiered["ttft_ms"]["host"]
    return {
        "metric": "llm_kv_tier_ab",
        # headline: how many cold-prefill TTFTs one host-tier warm hit saves
        "value": round(cold / host, 2) if (cold and host) else None,
        "unit": "x cold-prefill TTFT over host-tier warm TTFT",
        "tiered": tiered,
        "untiered": untiered,
        "identical_streams": identical,
        "n_prefixes": n_prefixes,
        "prefix_len": prefix_len,
        "working_set_pages": working_set_pages,
        "device_cache_pages": device_cache_pages,
        "host_pages": host_pages,
        "page_size": page_size,
        "cpus": os.cpu_count() or 1,
        "note": (
            "working set > device prefix-cache budget by construction: the "
            "untiered arm re-prefills evicted prefixes cold; the tiered arm "
            "serves them from host RAM with the promotion DMA overlapped "
            "with the tail prefill (overlap observed at retire reaps)"
        ),
    }


def run_paged_quant_ab(
    cfg: dict,
    *,
    batch: int = 4,
    decode_steps: int = 8,
    new_tokens: int = 64,
    prompt_len: int = 24,
    max_seq_len: int = 256,
    quantize=None,
    drift_steps: int = 6,
    page_size: int = 32,
) -> dict:
    """bf16-vs-int8 PAGED KV A/B on the real continuous-batching engine
    (docs/paged_kv_quant.md): the same greedy workload on two engines that
    differ ONLY in ``kv_quant`` — identical weights, page budget, and page
    size. Reports steady-state step ms, tok/s, pool bytes by kind (the
    capacity win: >= 1.8x total-pool reduction expected at D >= 64), and
    the max logit drift between the two KV representations measured on the
    raw paged decode path. On CPU the Pallas int8 kernel additionally runs
    in interpret=True mode against the XLA int8 reference (parity
    maxdiff)."""
    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    base_cfg = {k: v for k, v in cfg.items() if k != "kv_quant"}
    if quantize in ("int8", "int4"):
        from clearml_serving_tpu.ops.quant import random_quantized_llama

        # random_quantized_llama builds the tree with scan_layers=True
        # (stacked [L, ...] layers); the A/B bundles must match that layout
        base_cfg = dict(base_cfg, scan_layers=True)
        _, params = random_quantized_llama(
            base_cfg, seed=0, bits=4 if quantize == "int4" else 8
        )
    else:
        params = models.build_model("llama", base_cfg).init(
            jax.random.PRNGKey(0)
        )
    bundles = {
        "bf16": models.build_model("llama", base_cfg),
        "int8": models.build_model("llama", dict(base_cfg, kv_quant="int8")),
    }
    prompts = [
        [(7 * i + 3 + j) % 250 + 1 for j in range(prompt_len)]
        for i in range(batch)
    ]

    def measure(bundle):
        engine = LLMEngineCore(
            bundle, params,
            max_batch=batch,
            max_seq_len=max_seq_len,
            prefill_buckets=[max(16, prompt_len)],
            eos_token_id=None,
            decode_steps=decode_steps,
            cache_mode="paged",
            # default 32-token pages: the int8 Pallas path needs
            # page_size % 32 == 0 on TPU (docs/paged_kv_quant.md), and the
            # A/B must compare both representations on the SAME layout
            page_size=page_size,
        )

        async def one(ids):
            req = GenRequest(
                prompt_ids=ids, max_new_tokens=new_tokens, temperature=0.0
            )
            return [t async for t in engine.generate(req)]

        async def group():
            outs = await asyncio.gather(*(one(p) for p in prompts))
            await engine.wait_drained()
            return outs

        asyncio.run(group())  # warmup: compile prefill + decode chunk
        # best-of-N timed groups: single-group walls on a shared CPU jitter
        # by 20%+, which would dominate the A/B delta being measured
        wall, chunks, outs = None, 1, None
        for _ in range(3):
            seq0 = engine._dispatch_seq
            t0 = time.perf_counter()
            outs = asyncio.run(group())
            w = time.perf_counter() - t0
            c = max(1, engine._dispatch_seq - seq0)
            if wall is None or w / c < wall / chunks:
                wall, chunks = w, c
        pool_bytes = engine.paged_cache.pool_bytes()
        dtype = engine.paged_cache.pool_dtype
        pages = engine.paged_cache.pool.num_pages
        engine.stop()
        return outs, wall, chunks, pool_bytes, dtype, pages

    def max_logit_drift():
        """Raw paged decode path, greedy, both KV representations over the
        SAME token sequence: max |logits_bf16 - logits_int8| across steps
        (accuracy note for the docs; per-vector int8 is ~0.4% RMS)."""
        from clearml_serving_tpu.llm.kv_cache import PagedKVCache

        ids = prompts[0]
        tokens = jnp.asarray([ids], jnp.int32)
        lens = jnp.asarray([len(ids)], jnp.int32)
        caches, state = {}, {}
        for name, bundle in bundles.items():
            mini = bundle.init_cache(1, max(32, prompt_len + drift_steps))
            logits, mini = bundle.prefill(params, tokens, lens, mini)
            cache = PagedKVCache(
                bundle.n_layers, bundle.n_kv_heads, bundle.head_dim,
                num_pages=64, page_size=page_size, max_slots=1,
                dtype=base_cfg.get("dtype", "bfloat16"),
                kv_quant="int8" if name == "int8" else "",
            )
            scales = ()
            if name == "int8":
                scales = (
                    mini["k_scale"][:, 0, : len(ids)],
                    mini["v_scale"][:, 0, : len(ids)],
                )
            cache.write_prompt(
                0, mini["k"][:, 0, : len(ids)], mini["v"][:, 0, : len(ids)],
                len(ids), *scales,
            )
            caches[name] = cache
            state[name] = (jnp.argmax(logits, -1).astype(jnp.int32), logits)
        drift = float(
            jnp.max(jnp.abs(state["bf16"][1] - state["int8"][1]))
        )
        # chain the bf16 greedy tokens through BOTH paths so drift isolates
        # the KV representation, not diverging token histories
        nxt = state["bf16"][0]
        length = len(ids)
        for _ in range(drift_steps):
            step_logits = {}
            for name, bundle in bundles.items():
                cache = caches[name]
                cache.pool.extend(0, 1)
                ((wp, wo),) = cache.pool.token_coords(0, length, 1)
                table = jnp.asarray(cache.pool.page_table(64))
                args = (
                    params, nxt, cache.k, cache.v, table,
                    jnp.asarray([length], jnp.int32),
                    jnp.asarray([wp], jnp.int32), jnp.asarray([wo], jnp.int32),
                )
                if name == "int8":
                    out = bundle.decode_paged(
                        *args, k_scales=cache.k_scale, v_scales=cache.v_scale
                    )
                    cache.k, cache.v = out[1], out[2]
                    cache.k_scale, cache.v_scale = out[3], out[4]
                else:
                    out = bundle.decode_paged(*args)
                    cache.k, cache.v = out[1], out[2]
                step_logits[name] = out[0]
            drift = max(
                drift,
                float(jnp.max(jnp.abs(step_logits["bf16"] - step_logits["int8"]))),
            )
            length += 1
            nxt = jnp.argmax(step_logits["bf16"], -1).astype(jnp.int32)
        return drift

    outs_b, wall_b, chunks_b, bytes_b, dtype_b, pages_b = measure(bundles["bf16"])
    outs_q, wall_q, chunks_q, bytes_q, dtype_q, pages_q = measure(bundles["int8"])
    step_b = wall_b / chunks_b * 1e3
    step_q = wall_q / chunks_q * 1e3
    toks = batch * new_tokens
    total_b = bytes_b["kv"] + bytes_b["scale"]
    total_q = bytes_q["kv"] + bytes_q["scale"]
    row = {
        "metric": "llm_paged_kv_quant_ab",
        "value": round(total_b / total_q, 4),
        "unit": "x pool-bytes reduction (bf16 -> int8+scales)",
        "pool_bytes_bf16": total_b,
        "pool_bytes_int8": bytes_q["kv"],
        "pool_bytes_int8_scales": bytes_q["scale"],
        "pool_dtype": [dtype_b, dtype_q],
        "num_pages": pages_q,
        "equal_page_budget": pages_b == pages_q,
        "step_ms_bf16": round(step_b, 3),
        "step_ms_int8": round(step_q, 3),
        "step_time_ratio": round(step_q / step_b, 4),
        "tok_s_bf16": round(toks / wall_b, 2),
        "tok_s_int8": round(toks / wall_q, 2),
        "max_logit_drift": round(max_logit_drift(), 5),
        "identical_greedy_streams": outs_b == outs_q,
        "batch": batch,
        "decode_steps": decode_steps,
        "new_tokens": new_tokens,
        "note": (
            "int8 paged pools halve KV DMA bytes + pool HBM; streams may "
            "differ from bf16 by bounded quantization noise (drift above)"
        ),
    }
    import jax as _jax

    if _jax.devices()[0].platform != "tpu":
        # CPU smoke: exercise the Pallas int8 kernel in interpret mode
        # against the XLA int8 reference (the hardware path's parity gate)
        from clearml_serving_tpu.ops.paged_attention import (
            paged_attention, paged_attention_xla,
        )

        rng = np.random.default_rng(0)
        hkv, g, d, n, p, pp = 2, 2, 128, 9, 16, 4
        q = jnp.asarray(rng.normal(size=(2, hkv, g, d)).astype(np.float32))
        kf = rng.normal(size=(hkv, n, p, d)).astype(np.float32)
        absmax = np.abs(kf).max(-1)
        scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
        k8 = jnp.asarray(
            np.clip(np.round(kf / scale[..., None]), -127, 127).astype(np.int8)
        )
        ks = jnp.asarray(scale)
        table = jnp.asarray(
            rng.choice(np.arange(1, n), size=(2, pp), replace=False
                       ).astype(np.int32)
        )
        lengths = jnp.asarray([37, 64], jnp.int32)
        ref = paged_attention_xla(q, k8, k8, table, lengths, ks, ks)
        out = paged_attention(
            q, k8, k8, table, lengths, k_scale=ks, v_scale=ks, interpret=True
        )
        row["pallas_interpret_maxdiff"] = float(jnp.max(jnp.abs(ref - out)))
    return row


def _quantized_tree_bytes(params) -> dict:
    """Weight-tree byte accounting for the int4 A/B: total tree bytes, the
    bytes of the QUANTIZED projection leaves (values + scales — the subset
    the roofline's weight-read term streams every decode step; embeddings/
    norms stay full precision in every arm and would dilute the ratio), and
    the dense bf16-equivalent of that subset."""
    import jax

    def walk(tree, acc):
        if isinstance(tree, dict):
            if "_q4" in tree:
                acc["quant"] += tree["_q4"].nbytes + tree["_scale4"].nbytes
                # packed uint8 [K//2, N] -> bf16 [K, N] is 4x the bytes
                acc["dense_equiv"] += tree["_q4"].nbytes * 4
                return
            if "_q8" in tree:
                acc["quant"] += tree["_q8"].nbytes + tree["_scale"].nbytes
                acc["dense_equiv"] += tree["_q8"].nbytes * 2
                return
            for value in tree.values():
                walk(value, acc)
            return
        if isinstance(tree, (list, tuple)):
            for value in tree:
                walk(value, acc)

    acc = {"quant": 0, "dense_equiv": 0}
    walk(params, acc)
    total = int(sum(
        leaf.nbytes for leaf in jax.tree.leaves(params)
        if hasattr(leaf, "nbytes")
    ))
    return {"tree": total, "quant_leaves": int(acc["quant"]),
            "dense_equiv": int(acc["dense_equiv"])}


def run_int4_ab(
    cfg: dict,
    *,
    batch: int = 4,
    decode_steps: int = 8,
    new_tokens: int = 64,
    prompt_len: int = 24,
    max_seq_len: int = 256,
    from_bf16: bool = True,
    drift_steps: int = 6,
) -> dict:
    """w4a16 A/B on the real continuous-batching engine (docs/w4a16.md):
    the same greedy workload on three engines that differ ONLY in the
    weight tree / matmul route —

      int4_fused  packed int4, decode matmuls through the Pallas fused
                  dequant-matmul (ops/fused_matmul.py; the production path)
      int4_xla    the same packed int4 tree with cfg int4_fused=False
                  (XLA inline-dequant reference route)
      int8        per-channel int8 (the PR-5-era weight format)

    Reports best-of-3 steady-state step ms + tok/s per arm, weight-tree
    bytes (tree / quantized-leaf / dense-equivalent — the HBM weight-read
    term), fused-vs-XLA stream byte-identity, max logit drift of int4 vs
    int8 on the raw decode path (``from_bf16`` arms quantize ONE shared
    bf16 init so the drift isolates the weight format; random trees skip
    it), and — off-TPU — the fused kernel's interpret-mode parity maxdiff
    against the XLA reference."""
    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
    from clearml_serving_tpu.ops.quant import (
        quantize_llama_params, random_quantized_llama,
    )

    base_cfg = {k: v for k, v in cfg.items() if k != "int4_fused"}
    base_cfg["scan_layers"] = True
    if from_bf16:
        p_bf16 = models.build_model("llama", base_cfg).init(
            jax.random.PRNGKey(0)
        )
        params4 = quantize_llama_params(p_bf16, bits=4)
        params8 = quantize_llama_params(p_bf16, bits=8)
    else:
        # 8B-scale: quantized trees built directly; full precision never
        # materializes (drift vs int8 is skipped — unrelated random trees)
        _, params4 = random_quantized_llama(base_cfg, seed=0, bits=4)
        _, params8 = random_quantized_llama(base_cfg, seed=0, bits=8)
    bundle_fused = models.build_model("llama", base_cfg)
    bundle_xla = models.build_model(
        "llama", dict(base_cfg, int4_fused=False)
    )
    arms = (
        ("int4_fused", bundle_fused, params4),
        ("int4_xla", bundle_xla, params4),
        ("int8", bundle_fused, params8),
    )
    prompts = [
        [(7 * i + 3 + j) % 250 + 1 for j in range(prompt_len)]
        for i in range(batch)
    ]

    def measure(bundle, params):
        engine = LLMEngineCore(
            bundle, params,
            max_batch=batch,
            max_seq_len=max_seq_len,
            prefill_buckets=[max(16, prompt_len)],
            eos_token_id=None,
            decode_steps=decode_steps,
        )

        async def one(ids):
            req = GenRequest(
                prompt_ids=ids, max_new_tokens=new_tokens, temperature=0.0
            )
            return [t async for t in engine.generate(req)]

        async def group():
            outs = await asyncio.gather(*(one(p) for p in prompts))
            await engine.wait_drained()
            return outs

        asyncio.run(group())  # warmup: compile prefill + decode chunk
        # best-of-N timed groups (shared-CPU wall jitter would drown the
        # delta; same protocol as run_paged_quant_ab)
        wall, chunks, outs = None, 1, None
        for _ in range(3):
            seq0 = engine._dispatch_seq
            t0 = time.perf_counter()
            outs = asyncio.run(group())
            w = time.perf_counter() - t0
            c = max(1, engine._dispatch_seq - seq0)
            if wall is None or w / c < wall / chunks:
                wall, chunks = w, c
        engine.stop()
        return outs, wall, chunks

    def max_logit_drift():
        """Raw dense decode, int4 vs int8 trees quantized from the SAME
        bf16 init, chained on the int8 arm's greedy tokens — the drift
        isolates the weight format, not diverging histories."""
        ids = prompts[0]
        tokens = jnp.asarray([ids], jnp.int32)
        lens = jnp.asarray([len(ids)], jnp.int32)
        caches, logits = {}, {}
        for name, p in (("int4", params4), ("int8", params8)):
            lg, caches[name] = bundle_fused.prefill(
                p, tokens, lens,
                bundle_fused.init_cache(1, prompt_len + drift_steps + 8),
            )
            logits[name] = lg
        drift = float(jnp.max(jnp.abs(logits["int4"] - logits["int8"])))
        nxt = jnp.argmax(logits["int8"], -1).astype(jnp.int32)
        for _ in range(drift_steps):
            step = {}
            for name, p in (("int4", params4), ("int8", params8)):
                step[name], caches[name] = bundle_fused.decode(
                    p, nxt, caches[name]
                )
            drift = max(
                drift,
                float(jnp.max(jnp.abs(step["int4"] - step["int8"]))),
            )
            nxt = jnp.argmax(step["int8"], -1).astype(jnp.int32)
        return drift

    results = {}
    for name, bundle, params in arms:
        outs, wall, chunks = measure(bundle, params)
        results[name] = {
            "outs": outs,
            "step_ms": wall / chunks * 1e3,
            "tok_s": batch * new_tokens / wall,
        }
    bytes4 = _quantized_tree_bytes(params4)
    bytes8 = _quantized_tree_bytes(params8)
    toks = batch * new_tokens
    row = {
        "metric": "llm_int4_weight_ab",
        "value": round(
            results["int4_xla"]["step_ms"] / results["int4_fused"]["step_ms"],
            4,
        ),
        "unit": "x step-time speedup (xla-dequant -> fused kernel)",
        "step_ms": {
            name: round(results[name]["step_ms"], 3) for name in results
        },
        "tok_s": {name: round(results[name]["tok_s"], 2) for name in results},
        "weight_bytes_int4": bytes4,
        "weight_bytes_int8": bytes8,
        "int4_vs_int8_quant_bytes": round(
            bytes4["quant_leaves"] / bytes8["quant_leaves"], 4
        ),
        "int4_vs_bf16_quant_bytes": round(
            bytes4["quant_leaves"] / bytes4["dense_equiv"], 4
        ),
        "identical_streams_fused_vs_xla": (
            results["int4_fused"]["outs"] == results["int4_xla"]["outs"]
        ),
        "batch": batch,
        "decode_steps": decode_steps,
        "new_tokens": new_tokens,
        "tokens_per_group": toks,
        "note": (
            "int4 group-quantized weights quarter the HBM weight-read "
            "term; the fused kernel makes the 4-bit read structural "
            "(docs/w4a16.md)"
        ),
    }
    if from_bf16:
        row["max_logit_drift_int4_vs_int8"] = round(max_logit_drift(), 5)
    if jax.devices()[0].platform != "tpu":
        # CPU smoke: the fused kernel itself in interpret mode against the
        # XLA dequant reference (the hardware path's parity gate), over a
        # few alignment-representative shapes
        from clearml_serving_tpu.ops.fused_matmul import (
            fused_int4_matmul, int4_matmul_xla,
        )
        from clearml_serving_tpu.ops.quant import quantize_int4

        rng = np.random.default_rng(0)
        maxdiff = 0.0
        for m, k, n, group in (
            (2, 128, 128, 128), (4, 256, 256, 128), (3, 256, 384, 64),
            (8, 512, 256, 128),
        ):
            w = jnp.asarray(
                (rng.normal(size=(k, n)) * k ** -0.5).astype(np.float32)
            )
            x = jnp.asarray(rng.normal(size=(m, k)).astype(np.float32))
            q, s = quantize_int4(w, group=group)
            ref = int4_matmul_xla(x, q, s, jnp.float32)
            out = fused_int4_matmul(x, q, s, dtype=jnp.float32,
                                    interpret=True)
            maxdiff = max(maxdiff, float(jnp.max(jnp.abs(ref - out))))
        row["pallas_interpret_maxdiff"] = maxdiff
    return row


def _int4_ab_smoke() -> None:
    """CPU smoke for ``--int4-ab`` (acceptance: int4 quantized-leaf bytes
    ~0.5x int8 / ~0.25x bf16-equivalent, fused-vs-XLA streams byte-identical
    — on CPU the wrapper routes to the identical XLA expression by
    construction — and interpret-mode kernel parity <= 1e-5). Runs on a
    widened llama-tiny (dim 256 -> K spans one, two, and four 128-row scale
    groups across the projection shapes). Updates benchmarks/INT4_AB_cpu.json.
    Knobs: BENCH_I4_BATCH / BENCH_I4_STEPS / BENCH_I4_TOKENS."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    row = run_int4_ab(
        {"preset": "llama-tiny", "dtype": "bfloat16", "dim": 256,
         "n_heads": 4, "n_kv_heads": 2, "ffn_dim": 512},
        batch=int(os.environ.get("BENCH_I4_BATCH", 2)),
        decode_steps=int(os.environ.get("BENCH_I4_STEPS", 4)),
        new_tokens=int(os.environ.get("BENCH_I4_TOKENS", 24)),
        prompt_len=12,
        max_seq_len=128,
    )
    row["metric"] += "_cpusmoke"
    row["platform"] = "cpu"
    artifact = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks",
        "INT4_AB_cpu.json",
    )
    with open(artifact, "w") as f:
        json.dump(row, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(row))


def _ragged_ab_smoke() -> None:
    """CPU smoke for ``--ragged-ab`` (acceptance: byte-identical streams
    across schedulers and a STRICTLY smaller decode stall during a
    concurrent long-prompt admission — the ISSUE-9 headline; plus the
    ISSUE-13 arms: the ``--decode-steps`` q=1-vs-q A/B with
    dispatches-per-decode-token < 0.5 at q, and spec-as-row vs the legacy
    serial spec scan with identical streams). Updates
    benchmarks/RAGGED_AB_cpu.json (asserted by tier-1). Knobs:
    BENCH_RAGGED_BATCH / BENCH_RAGGED_TOKENS / BENCH_RAGGED_BUDGET /
    BENCH_RAGGED_ADMIT / BENCH_RAGGED_CACHE, and ``--decode-steps N``
    (or BENCH_RAGGED_DECODE_STEPS) for the multi-step arm's window."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    q = int(os.environ.get("BENCH_RAGGED_DECODE_STEPS", 4))
    if "--decode-steps" in sys.argv:
        q = int(sys.argv[sys.argv.index("--decode-steps") + 1])
    cfg = {"preset": "llama-tiny", "dtype": "float32"}
    row = run_ragged_ab(
        cfg,
        batch=int(os.environ.get("BENCH_RAGGED_BATCH", 3)),
        new_tokens=int(os.environ.get("BENCH_RAGGED_TOKENS", 64)),
        step_token_budget=int(os.environ.get("BENCH_RAGGED_BUDGET", 24)),
        admit_prompt_len=int(os.environ.get("BENCH_RAGGED_ADMIT", 224)),
        cache_mode=os.environ.get("BENCH_RAGGED_CACHE", "paged"),
        max_seq_len=256,
    )
    row["metric"] += "_cpusmoke"
    row["platform"] = "cpu"
    row["decode_steps_ab"] = run_ragged_decode_steps_ab(cfg, q=q)
    row["spec_row_ab"] = run_spec_row_ab(cfg)
    artifact = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks",
        "RAGGED_AB_cpu.json",
    )
    with open(artifact, "w") as f:
        json.dump(row, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(row))


def _spec_tree_ab_smoke() -> None:
    """CPU smoke for ``--spec-tree-ab`` (acceptance: byte-identical greedy
    streams across the no-spec / chain / tree arms, and the tree arm's
    accepted-tokens-per-launch STRICTLY above the chain arm at the same
    k+1 verify budget — the ISSUE-20 headline). Updates
    benchmarks/SPEC_TREE_AB_cpu.json (asserted by tier-1). Knobs:
    BENCH_SPEC_TREE_K / BENCH_SPEC_TREE_BRANCH / BENCH_SPEC_TREE_BATCH /
    BENCH_SPEC_TREE_TOKENS / BENCH_SPEC_TREE_BUDGET."""
    # strict-sentry certification (the slo_loadtest pattern, forced not
    # defaulted): the committed artifact's certs block claims 0 sanitizer
    # violations / ledger leaks / post-warmup compiles / implicit
    # transfers, and strict mode FAILS the run on any of them — so the
    # artifact existing at all is the proof
    os.environ["TPUSERVE_SANITIZE"] = "1"
    os.environ["TPUSERVE_COMPILE_SENTRY"] = "strict"
    os.environ["TPUSERVE_LEDGER"] = "strict"
    os.environ["TPUSERVE_SHARD_SENTRY"] = "strict"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    row = run_spec_tree_ab(
        {"preset": "llama-tiny", "dtype": "float32"},
        spec_k=int(os.environ.get("BENCH_SPEC_TREE_K", 4)),
        spec_branch=int(os.environ.get("BENCH_SPEC_TREE_BRANCH", 2)),
        batch=int(os.environ.get("BENCH_SPEC_TREE_BATCH", 3)),
        new_tokens=int(os.environ.get("BENCH_SPEC_TREE_TOKENS", 64)),
        step_token_budget=int(os.environ.get("BENCH_SPEC_TREE_BUDGET", 20)),
    )
    row["metric"] += "_cpusmoke"
    row["platform"] = "cpu"
    artifact = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks",
        "SPEC_TREE_AB_cpu.json",
    )
    with open(artifact, "w") as f:
        json.dump(row, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(row))


def _kv_tier_ab_smoke() -> None:
    """CPU smoke for ``--kv-tier-ab`` (acceptance: byte-identical streams
    for a demoted-then-promoted run vs the always-resident warm hit under
    the armed sanitizer, and host-tier warm TTFT well under cold-prefill
    TTFT on a working set larger than the device prefix-cache budget).
    Updates benchmarks/KV_TIER_AB_cpu.json (asserted by tier-1). Knobs:
    BENCH_TIER_PREFIXES / BENCH_TIER_PREFIX_LEN / BENCH_TIER_HOST_PAGES /
    BENCH_TIER_DEVICE_PAGES."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    row = run_kv_tier_ab(
        # int8 KV: the tier holds int8 pages + scale rows (the 2x-cheaper
        # representation the design banks on)
        {"preset": "llama-tiny", "dtype": "float32", "kv_quant": "int8"},
        n_prefixes=int(os.environ.get("BENCH_TIER_PREFIXES", 3)),
        prefix_len=int(os.environ.get("BENCH_TIER_PREFIX_LEN", 768)),
        device_cache_pages=int(os.environ.get("BENCH_TIER_DEVICE_PAGES", 48)),
        host_pages=int(os.environ.get("BENCH_TIER_HOST_PAGES", 160)),
    )
    row["metric"] += "_cpusmoke"
    row["platform"] = "cpu"
    artifact = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks",
        "KV_TIER_AB_cpu.json",
    )
    with open(artifact, "w") as f:
        json.dump(row, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(row))


def _paged_quant_ab_smoke() -> None:
    """CPU smoke for ``--paged-quant-ab`` (acceptance: >= 1.8x pool-bytes
    reduction at equal page budget, no step-time regression, Pallas int8
    interpret parity). Runs at bf16 pools with head_dim 64 — the honest
    production layout; llama-tiny's D=16 would overstate the f32-scale
    overhead. Knobs: BENCH_PQ_BATCH / BENCH_PQ_STEPS / BENCH_PQ_TOKENS."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    row = run_paged_quant_ab(
        # llama-tiny widened to head_dim 64 (dim 256 / 4 heads), bf16 pools
        {"preset": "llama-tiny", "dtype": "bfloat16", "dim": 256,
         "n_heads": 4, "n_kv_heads": 2},
        batch=int(os.environ.get("BENCH_PQ_BATCH", 2)),
        decode_steps=int(os.environ.get("BENCH_PQ_STEPS", 4)),
        new_tokens=int(os.environ.get("BENCH_PQ_TOKENS", 24)),
        prompt_len=12,
        max_seq_len=128,
    )
    row["metric"] += "_cpusmoke"
    row["platform"] = "cpu"
    print(json.dumps(row))


def _pipeline_ab_smoke() -> None:
    """CPU smoke for ``--pipeline-ab`` (acceptance: >=10% steady-state step
    time reduction at depth 2 vs 1, byte-identical greedy streams). Knobs:
    BENCH_PIPE_BATCH / BENCH_PIPE_STEPS / BENCH_PIPE_TOKENS / BENCH_PIPE_CACHE."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    row = run_pipeline_ab(
        {"preset": "llama-tiny", "dtype": "float32"},
        batch=int(os.environ.get("BENCH_PIPE_BATCH", 4)),
        decode_steps=int(os.environ.get("BENCH_PIPE_STEPS", 8)),
        new_tokens=int(os.environ.get("BENCH_PIPE_TOKENS", 192)),
        cache_mode=os.environ.get("BENCH_PIPE_CACHE", "dense"),
    )
    row["metric"] += "_cpusmoke"
    row["platform"] = "cpu"
    print(json.dumps(row))


def _loadtest(smoke: bool, replicas: int = 0,
              disaggregated: bool = False) -> None:
    """``--loadtest [--smoke] [--replicas N] [--disaggregated]``:
    loadtest harnesses.

    Without ``--replicas``: the SLO-aware-scheduling loadtest — open-loop
    Poisson mixed-trace replay against the real engine with priority
    classes, the preemptible batch lane, the brownout controller, the
    armed KV sanitizer AND the strict compile sentry (the shared warmup
    registry llm/warmup.py runs first; any post-warmup XLA compile fails
    the run, and the committed headline asserts post_warmup_compiles == 0
    — benchmarks/slo_loadtest.py; docs/slo_scheduling.md;
    docs/static_analysis.md TPU6xx). Emits per-class p50/p99 TTFT +
    goodput vs offered-load curves and updates
    benchmarks/LOADTEST_cpu.json.

    With ``--replicas N`` (N >= 2): the replica-fleet router loadtest —
    1 vs N engine replicas behind the prefix-affine router on the
    repeated-conversation trace, plus the kill-one-replica chaos case
    (benchmarks/replica_loadtest.py; docs/replication.md). Headline:
    affine-hit rate, interactive p99 TTFT, aggregate goodput speedup,
    zero sanitizer/sentry violations, zero chaos 503s. Updates
    benchmarks/LOADTEST_replicas_cpu.json.

    With ``--replicas N --disaggregated``: the disaggregated
    prefill/decode loadtest — mono vs two-hybrid vs prefill/decode-split
    replicas with the KV transport shipping admissions' prefix KV
    (benchmarks/disagg_loadtest.py; docs/disaggregation.md). Headline:
    ship hit rate >= 0.9, byte-identical streams, zero sanitizer/sentry
    violations. Updates benchmarks/DISAGG_AB_cpu.json."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if disaggregated:
        from benchmarks import disagg_loadtest

        row = disagg_loadtest.run(smoke=smoke, replicas=replicas or 2)
    elif replicas and replicas > 1:
        from benchmarks import replica_loadtest

        row = replica_loadtest.run(smoke=smoke, replicas=replicas)
    else:
        from benchmarks import slo_loadtest

        row = slo_loadtest.run(smoke=smoke)
    print(json.dumps(row))


def _subprocess_env():
    """Env for child python processes that should reach the TPU.

    ``JAX_PLATFORMS=axon`` must be INHERITED (the tunnel registers as the
    experimental "axon" platform, which jax refuses to auto-select — see
    module docstring).  A cpu-only value is dropped: that combination has
    hung sitecustomize at interpreter startup while the tunnel is down."""
    env = dict(os.environ)
    plats = env.get("JAX_PLATFORMS", "")
    if plats and "axon" not in plats and "tpu" not in plats:
        env.pop("JAX_PLATFORMS", None)
    return env


_PROBE_SNIPPET = (
    "import jax, bench; "
    "print('TPU_OK' if bench.is_tpu_device(jax.devices()[0]) else 'NOT_TPU')"
)


def _probe_tpu() -> bool:
    """Check backend health in a throwaway subprocess (it can hang)."""
    env = _subprocess_env()
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True,
            text=True,
            timeout=PROBE_TIMEOUT,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "TPU_OK" in out.stdout


def main() -> None:
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        _cpu_smoke("forced cpu via BENCH_PLATFORM")
        return
    if not _probe_tpu():
        _cpu_smoke("tpu backend unavailable (probe failed/timed out)")
        return
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--tpu-worker"],
            capture_output=True,
            text=True,
            timeout=TPU_TIMEOUT,
            env=_subprocess_env(),
        )
    except subprocess.TimeoutExpired:
        _cpu_smoke("tpu bench timed out after {}s".format(TPU_TIMEOUT))
        return
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode == 0 and lines:
        print(lines[-1])
        return
    _cpu_smoke(
        "tpu bench failed rc={}: {}".format(
            out.returncode, (out.stderr or "").strip()[-300:]
        )
    )


if __name__ == "__main__":
    if "--tpu-worker" in sys.argv:
        # worker mode: let failures propagate as a nonzero exit so the parent
        # reports them via its dedicated "tpu bench failed rc=..." path
        _tpu_worker()
    elif "--shared-prefix" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "shared_prefix"
    ):
        _shared_prefix_smoke()
    elif "--pipeline-ab" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "pipeline_ab"
    ):
        _pipeline_ab_smoke()
    elif "--ragged-ab" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "ragged_ab"
    ):
        _ragged_ab_smoke()
    elif "--spec-tree-ab" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "spec_tree_ab"
    ):
        _spec_tree_ab_smoke()
    elif "--kv-tier-ab" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "kv_tier_ab"
    ):
        _kv_tier_ab_smoke()
    elif "--paged-quant-ab" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "paged_quant_ab"
    ):
        _paged_quant_ab_smoke()
    elif "--int4-ab" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "int4_ab"
    ):
        _int4_ab_smoke()
    elif "--loadtest" in sys.argv or (
        os.environ.get("BENCH_SCENARIO") == "loadtest"
    ):
        replicas = None
        if "--replicas" in sys.argv:
            try:
                replicas = int(sys.argv[sys.argv.index("--replicas") + 1])
            except (IndexError, ValueError):
                # fail loudly: silently running the default scale would
                # overwrite the committed artifact with numbers the
                # operator thinks are something else
                print("error: --replicas needs an integer argument",
                      file=sys.stderr)
                sys.exit(2)
        elif os.environ.get("BENCH_LOADTEST_REPLICAS"):
            try:
                replicas = int(os.environ["BENCH_LOADTEST_REPLICAS"])
            except ValueError:
                print("error: BENCH_LOADTEST_REPLICAS must be an integer",
                      file=sys.stderr)
                sys.exit(2)
        if replicas is not None and replicas < 2:
            # an EXPLICIT replica count below the harness minimum (0 and 1
            # included) must not silently fall through to the single-engine
            # SLO loadtest (and overwrite ITS artifact with numbers the
            # operator thinks are router output)
            print("error: --replicas needs >= 2 (the replica loadtest "
                  "always runs its own single-replica arm)", file=sys.stderr)
            sys.exit(2)
        disaggregated = "--disaggregated" in sys.argv or (
            os.environ.get("BENCH_LOADTEST_DISAGG", "") in ("1", "true")
        )
        if disaggregated and replicas is None:
            # the disaggregated harness needs a fleet; default to the
            # committed artifact's 2-replica shape rather than erroring
            replicas = 2
        _loadtest(
            "--smoke" in sys.argv
            or os.environ.get("BENCH_LOADTEST_SMOKE", "") in ("1", "true"),
            replicas=replicas or 0,
            disaggregated=disaggregated,
        )
    else:
        try:
            main()
        except Exception as exc:  # last-resort: the driver must get a JSON line
            try:
                _cpu_smoke("unexpected error: {!r}".format(exc))
            except Exception as exc2:
                _emit("llm_decode_throughput_error", 0.0, "none", note=repr(exc2))
