"""Disaggregated prefill/decode loadtest (docs/disaggregation.md).

Replays a repeated-conversation + batch trace against three arms —

1. ``mono``:   ONE replica doing both jobs (the byte-identity baseline),
2. ``hybrid``: two hybrid replicas behind the prefix-affine router (the
               PR-12 fleet: both still do both jobs),
3. ``disagg``: two replicas split ``prefill`` / ``decode`` with the KV
               transport shipping every admission's prefix between them —

and certifies the ISSUE-14 acceptance criteria on the committed artifact
(``benchmarks/DISAGG_AB_cpu.json``, asserted by
tests/test_loadtest_artifact.py in tier-1):

- ship hit rate >= 0.9 on the clean path: the decode replica's
  admissions find the shipped prefix resident and recompute NONE of the
  shipped KV (engine ``kv_ship`` counters, not harness bookkeeping);
- every arm's streams byte-identical to the mono arm's (greedy, int8
  paged KV, radix caching and shipping never change tokens);
- 0 KV-sanitizer violations, 0 post-warmup XLA compiles (STRICT compile
  sentry — completing at all is the zero-recompile certificate).

Measurement model, stated plainly: unlike the PR-12 router loadtest's
isolated-substream estimate, every arm here runs CO-SCHEDULED through
the live group (a disaggregated request's prefill and decode legs are
inherently sequential across replicas — there is no honest way to
isolate them). On this one-core container the goodput columns therefore
carry scheduler interference no real fleet has and are reported for
SHAPE only; the committed headline certifies correctness, ship hit
rate, and the zero-recompile/zero-leak certificates, not fleet
throughput. Chip-scale disaggregation curves ride the TPU battery on
the next healthy tunnel window.

    python bench.py --loadtest --replicas 2 --disaggregated --smoke
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

REPO = Path(__file__).resolve().parent.parent
ARTIFACT = REPO / "benchmarks" / "DISAGG_AB_cpu.json"

# artifact schema (asserted by tests/test_loadtest_artifact.py in tier-1)
SCHEMA_KEYS = {
    "metric", "platform", "smoke", "replicas", "engine", "trace", "arms",
    "headline",
}
ARM_KEYS = {
    "name", "replicas", "roles", "requests", "completed", "shed", "errors",
    "duration_s", "goodput_tok_s", "interactive_ttft_p50_ms",
    "interactive_ttft_p99_ms", "streams_identical_to_mono",
    "post_warmup_compiles", "warmup_requests", "sanitizer_checks",
    "sanitizer_violations", "kv_ship", "disaggregation",
}
HEADLINE_KEYS = {
    "ship_hit_rate", "ship_hit_bound", "ship_ok", "ship_legs",
    "ship_drops", "ship_warm_skips", "receive_reroutes",
    "streams_identical", "goodput_tok_s_mono", "goodput_tok_s_hybrid",
    "goodput_tok_s_disagg", "goodput_note", "post_warmup_compiles",
    "compile_sentry_mode", "sanitizer_checks", "sanitizer_violations",
}

# the trace: repeated conversations (each turn extends the last — the
# prefix workload shipping exists for) + batch one-shots
N_CONVERSATIONS = 10
N_TURNS = 4
CONV_BASE = 96           # tokens of history at turn 0
TURN_STEP = 16           # tokens appended per turn
CONV_MAX_NEW = 6
N_BATCH = 8
BATCH_WORKERS = 2
BATCH_PROMPT = 48
BATCH_MAX_NEW = 12


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def conv_prompt(conv: int, turn: int) -> List[int]:
    n = CONV_BASE + TURN_STEP * turn
    return [(conv * 67 + i * 13) % 239 + 1 for i in range(n)]


def batch_prompt(i: int) -> List[int]:
    return [(i * 101 + j * 17) % 239 + 1 for j in range(BATCH_PROMPT)]


def engine_cfg() -> Dict[str, Any]:
    """One replica's budget. int8 paged KV (the transport payload the
    tiering/demote path defined: int8 pages + f32 scale rows); page_size
    32 keeps the int8 kernel gate clean on TPU re-runs."""
    return dict(
        max_batch=4,
        max_seq_len=256,
        prefill_buckets=[32, 64, 96, 128, 160, 192],
        eos_token_id=None,          # fixed work per request
        decode_steps=1,
        cache_mode="paged",
        page_size=32,
        chunked_prefill_size=32,
        prefix_cache=384,
        prefix_block=32,
        num_pages=161,              # 160 usable (page 0 is the null page)
        prefix_cache_pages=96,      # whole trace working set stays resident
        max_pending=32,
        brownout=True,
        watchdog_interval=5.0,
        pipeline_depth=1 if (os.cpu_count() or 1) == 1 else None,
    )


def build_group(n_replicas: int, roles: Optional[List[str]]):
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import LLMEngineCore
    from clearml_serving_tpu.llm.replica import ReplicaGroup

    bundle = models.build_model(
        "llama",
        {"preset": "llama-tiny", "dtype": "float32", "kv_quant": "int8"},
    )
    params = bundle.init(jax.random.PRNGKey(0))
    cfg = engine_cfg()
    engines = [
        LLMEngineCore(bundle, params, replica="r{}".format(i), **cfg)
        for i in range(n_replicas)
    ]
    return ReplicaGroup(engines, warmup_mode="startup", roles=roles), cfg


async def _consume(group, request, rec: dict, records: List[dict]) -> None:
    from clearml_serving_tpu.errors import (
        EngineOverloadedError,
        RequestError,
    )

    try:
        toks: List[int] = []
        async for token in group.generate(request):
            toks.append(int(token))
        rec["status"] = "ok"
        rec["tokens"] = toks
        if request.first_token_at is not None:
            rec["ttft_ms"] = (
                request.first_token_at - request.submitted_at
            ) * 1e3
        rec["t_done"] = time.perf_counter()
    except EngineOverloadedError:
        rec["status"] = "shed"
    except RequestError as ex:
        rec["status"] = "error"
        rec["error"] = repr(ex)[:200]
    except asyncio.CancelledError:
        rec["status"] = "cancelled"
        raise
    except Exception as ex:  # noqa: BLE001 - harness must keep counting
        rec["status"] = "error"
        rec["error"] = repr(ex)[:200]
    finally:
        records.append(rec)


async def _run_trace(group, seed: int) -> dict:
    """Co-scheduled open sessions through the live group (module
    docstring defends the model): conversation sessions run turns in
    order with think times, batch workers run closed-loop."""
    from clearml_serving_tpu.llm.engine import GenRequest

    rng = random.Random(seed)
    records: List[dict] = []

    async def session(conv: int) -> None:
        await asyncio.sleep(0.02 * (conv % 5))
        for turn in range(N_TURNS):
            request = GenRequest(
                prompt_ids=conv_prompt(conv, turn),
                max_new_tokens=CONV_MAX_NEW, priority="interactive",
            )
            rec = {"cls": "interactive", "conv": conv, "turn": turn}
            await _consume(group, request, rec, records)
            await asyncio.sleep(rng.uniform(0.005, 0.03))

    async def batch_worker(wid: int) -> None:
        for i in range(wid, N_BATCH, BATCH_WORKERS):
            request = GenRequest(
                prompt_ids=batch_prompt(i), max_new_tokens=BATCH_MAX_NEW,
                priority="batch",
            )
            rec = {"cls": "batch", "idx": i}
            await _consume(group, request, rec, records)

    t0 = time.perf_counter()
    await asyncio.gather(
        *[session(c) for c in range(N_CONVERSATIONS)],
        *[batch_worker(w) for w in range(BATCH_WORKERS)],
    )
    await group.wait_drained()
    done_times = [r["t_done"] for r in records if "t_done" in r]
    duration = (max(done_times) if done_times else time.perf_counter()) - t0
    done = [r for r in records if r["status"] == "ok"]
    ttfts = [
        r["ttft_ms"] for r in done
        if r["cls"] == "interactive" and r.get("ttft_ms") is not None
    ]
    return {
        "records": records,
        "requests": len(records),
        "completed": len(done),
        "shed": sum(1 for r in records if r["status"] == "shed"),
        "errors": sum(
            1 for r in records if r["status"] not in ("ok", "shed")
        ),
        "duration_s": round(duration, 2),
        "goodput_tok_s": round(
            sum(len(r.get("tokens", [])) for r in done)
            / max(1e-6, duration), 2,
        ),
        "interactive_ttft_p50_ms": round(_percentile(ttfts, 0.5) or 0.0, 2),
        "interactive_ttft_p99_ms": round(_percentile(ttfts, 0.99) or 0.0, 2),
    }


def _sentry_serve_count() -> int:
    from clearml_serving_tpu.llm import compile_sentry

    if not compile_sentry.enabled():
        return -1
    return int(compile_sentry.get().stats_brief().get("serve", -1))


def _merge_ship(group) -> Optional[dict]:
    """Fleet-wide kv_ship counters: sums over replicas, with the hit rate
    re-derived from the summed hit/recompute counts."""
    blocks = [
        r.engine._kv_ship_snapshot() for r in group.replicas
    ]
    blocks = [b for b in blocks if b]
    if not blocks:
        return None
    out = {
        k: sum(b[k] for b in blocks)
        for k in ("ships", "ship_pages", "ship_drops", "receives",
                  "receive_pages", "receive_empty", "receive_failures",
                  "hits", "recomputes")
    }
    judged = out["hits"] + out["recomputes"]
    out["hit_rate"] = round(out["hits"] / judged, 4) if judged else None
    return out


async def _run_arm(name: str, n_replicas: int,
                   roles: Optional[List[str]],
                   expected: Optional[Dict[tuple, List[int]]]) -> dict:
    from clearml_serving_tpu.llm import compile_sentry

    group, cfg = build_group(n_replicas, roles)
    try:
        if compile_sentry.enabled():
            # fresh fence per arm: the next arm's engines re-warm their
            # own jit caches and those compiles must count as warmup
            compile_sentry.get().reset(
                strict=compile_sentry.strict_enabled()
            )
        warm = await group.warmup(full=True)
        trace = await _run_trace(group, seed=11 + n_replicas)
        identical = None
        streams = {}
        for rec in trace.pop("records"):
            if rec["status"] != "ok":
                continue
            key = (
                ("c", rec["conv"], rec["turn"])
                if rec["cls"] == "interactive"
                else ("b", rec["idx"])
            )
            streams[key] = rec["tokens"]
        if expected is not None:
            identical = bool(streams) and all(
                streams.get(k) == v for k, v in expected.items()
            )
        sanitizer_checks = 0
        sanitizer_failures = 0
        for replica in group.replicas:
            sanitizer = replica.engine._sanitizer
            if sanitizer is None:
                sanitizer_failures = -1
                continue
            s = sanitizer.stats()
            sanitizer_checks += s.get("checks", 0)
            sanitizer_failures += s.get("failures", 0)
        arm = dict(
            trace,
            name=name,
            replicas=n_replicas,
            roles=roles or ["hybrid"] * n_replicas,
            streams_identical_to_mono=identical,
            warmup_requests=warm["requests"],
            post_warmup_compiles=_sentry_serve_count(),
            sanitizer_checks=sanitizer_checks,
            sanitizer_violations=sanitizer_failures,
            kv_ship=_merge_ship(group),
            disaggregation=group._disagg_snapshot(),
        )
        return {"arm": arm, "streams": streams, "cfg": cfg}
    finally:
        group.stop()


async def _run_async(smoke: bool, replicas: int) -> dict:
    from clearml_serving_tpu.llm import compile_sentry

    mono = await _run_arm("mono", 1, None, None)
    hybrid = await _run_arm(
        "hybrid", replicas, None, mono["streams"]
    )
    roles = ["prefill"] * (replicas - 1) + ["decode"]
    disagg = await _run_arm(
        "disagg", replicas, roles, mono["streams"]
    )
    a1, a2, a3 = mono["arm"], hybrid["arm"], disagg["arm"]
    ship = a3["kv_ship"] or {}
    dis = a3["disaggregation"] or {}
    sentry_mode = (
        compile_sentry.get().stats_brief().get("mode", "off")
        if compile_sentry.enabled() else "off"
    )
    streams_identical = bool(
        a2["streams_identical_to_mono"] and a3["streams_identical_to_mono"]
    )
    return {
        "metric": "llm_disagg_loadtest" + ("_cpusmoke" if smoke else ""),
        "platform": "cpu",
        "smoke": smoke,
        "replicas": replicas,
        "engine": {
            k: v for k, v in disagg["cfg"].items() if k != "prefill_buckets"
        },
        "trace": {
            "conversations": N_CONVERSATIONS,
            "turns": N_TURNS,
            "conv_base_tokens": CONV_BASE,
            "turn_step_tokens": TURN_STEP,
            "conv_max_new": CONV_MAX_NEW,
            "batch_requests": N_BATCH,
            "batch_prompt_tokens": BATCH_PROMPT,
            "batch_max_new": BATCH_MAX_NEW,
        },
        "arms": [a1, a2, a3],
        "headline": {
            "ship_hit_rate": ship.get("hit_rate"),
            "ship_hit_bound": 0.9,
            "ship_ok": bool(
                ship.get("hit_rate") is not None
                and ship["hit_rate"] >= 0.9
            ),
            "ship_legs": dis.get("ship_legs", 0),
            "ship_drops": ship.get("ship_drops", 0),
            "ship_warm_skips": dis.get("ship_warm_skips", 0),
            "receive_reroutes": dis.get("receive_reroutes", 0),
            "streams_identical": streams_identical,
            "goodput_tok_s_mono": a1["goodput_tok_s"],
            "goodput_tok_s_hybrid": a2["goodput_tok_s"],
            "goodput_tok_s_disagg": a3["goodput_tok_s"],
            "goodput_note": (
                "co-scheduled on one core: goodput columns carry "
                "scheduler interference no real fleet has; this artifact "
                "certifies correctness + ship hit rate, not throughput"
            ),
            "post_warmup_compiles": max(
                a1["post_warmup_compiles"], a2["post_warmup_compiles"],
                a3["post_warmup_compiles"],
            ),
            "compile_sentry_mode": sentry_mode,
            "sanitizer_checks": a1["sanitizer_checks"]
            + a2["sanitizer_checks"] + a3["sanitizer_checks"],
            "sanitizer_violations": max(
                a1["sanitizer_violations"], a2["sanitizer_violations"],
                a3["sanitizer_violations"],
            ),
        },
    }


def run(smoke: bool = True, replicas: int = 2,
        write_artifact: bool = True) -> dict:
    """Entry point for ``bench.py --loadtest --replicas N
    --disaggregated``. Forces the CPU backend, arms the KV sanitizer AND
    the strict compile sentry BEFORE any engine exists (completing at all
    is the zero-recompile certificate), runs the three arms, optionally
    updates the committed artifact."""
    if replicas < 2:
        raise ValueError("the disaggregated loadtest needs --replicas >= 2")
    os.environ["TPUSERVE_SANITIZE"] = "1"
    # forced, not defaulted: a pre-exported "1" must not silently
    # downgrade the certification run to count-only mode
    os.environ["TPUSERVE_COMPILE_SENTRY"] = "strict"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from clearml_serving_tpu.engines.jax_engine import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    row = asyncio.run(_run_async(smoke, replicas))
    if write_artifact:
        ARTIFACT.write_text(json.dumps(row, indent=2) + "\n")
    return row


def main() -> None:
    import sys

    smoke = "--smoke" in sys.argv
    row = run(smoke=smoke)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
