"""Per-endpoint latency-percentile report (BASELINE.md: "req/s + p50/p99
TTFT per endpoint").

Boots a REAL router process with two endpoints — the sklearn iris example
(CPU hot loop, router-overhead bound) and a tiny continuous-batching LLM
endpoint (streaming chat, TTFT) — drives each through the loadtest harness
(examples/loadtest/loadtest.py, the reference's `ab -n .. -c ..` recipe),
and writes ``benchmarks/LOADTEST_<platform>.json`` with req/s + p50/p99
latency + p50/p99 TTFT per endpoint.

    python benchmarks/loadtest_report.py            # cpu (forced in-process)
    python benchmarks/loadtest_report.py --platform default   # real backend

CPU numbers measure the router/orchestration overhead path; the LLM tok/s
story lives in bench.py. Platform is recorded in the artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PORT = int(os.environ.get("LOADTEST_PORT", 18090))

BOOT = '''
import sys, os
sys.path.insert(0, {repo!r})
if {force_cpu}:
    import jax
    jax.config.update("jax_platforms", "cpu")
os.environ["TPUSERVE_STATE_ROOT"] = {state_root!r}
import jax as _jax  # record the REAL backend for the report artifact
_d = _jax.devices()[0]
with open(os.path.join({state_root!r}, "backend.txt"), "w") as _f:
    _f.write("{{}}:{{}}".format(_d.platform, _d.device_kind))
import joblib
from sklearn.datasets import load_iris
from sklearn.linear_model import LogisticRegression
x, y = load_iris(return_X_y=True)
joblib.dump(LogisticRegression(max_iter=200).fit(x, y),
            os.path.join({state_root!r}, "sk.pkl"))
from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor
p = ModelRequestProcessor(force_create=True)
rec = p.registry.register("iris", path=os.path.join({state_root!r}, "sk.pkl"),
                          framework="sklearn")
p.add_endpoint(
    ModelEndpoint(engine_type="sklearn", serving_url="test_model_sklearn",
                  model_id=rec.id),
    preprocess_code=os.path.join({repo!r}, "examples/sklearn/preprocess.py"),
)
p.add_endpoint(dict(engine_type="llm", serving_url="test_llm",
                    auxiliary_cfg={{"engine": {{"preset": {preset!r},
                                                "max_batch": 8,
                                                "max_seq_len": 256,
                                                "decode_steps": 8}}}}))
p.serialize()
os.environ["TPUSERVE_SERVICE_ID"] = p._service.id
from clearml_serving_tpu.serving.main import build_app, setup_processor
from aiohttp import web
web.run_app(build_app(setup_processor()), host="127.0.0.1", port={port})
'''


def _wait_healthy(timeout=180):
    import urllib.request

    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            with urllib.request.urlopen(
                "http://127.0.0.1:{}/health".format(PORT), timeout=2
            ) as r:
                if r.status == 200:
                    return True
        except Exception:
            time.sleep(1)
    return False


def _loadtest(url, payload, n, c):
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "examples/loadtest/loadtest.py"),
            url,
            "--payload",
            json.dumps(payload),
            "-n",
            str(n),
            "-c",
            str(c),
        ],
        capture_output=True,
        text=True,
        timeout=1200,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if not lines:
        return {"error": (out.stderr or "no output").strip()[-300:]}
    return json.loads(lines[-1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="cpu", choices=["cpu", "default"])
    ap.add_argument("--preset", default=None, help="llm preset override")
    ap.add_argument("-n", type=int, default=2000, help="requests per endpoint")
    ap.add_argument("-c", type=int, default=64, help="concurrency")
    args = ap.parse_args()
    force_cpu = args.platform == "cpu"
    preset = args.preset or ("llama-tiny" if force_cpu else "llama3-1b")

    import tempfile

    state_root = tempfile.mkdtemp(prefix="loadtest_state_")
    boot = BOOT.format(
        repo=str(REPO), state_root=state_root, port=PORT,
        force_cpu=force_cpu, preset=preset,
    )
    # env plumbing (hard-won, bench.py module docstring): the TPU registers
    # as the experimental "axon" platform which jax never auto-selects, so a
    # --platform default run must INHERIT JAX_PLATFORMS=axon or the router
    # silently lands on CPU. A cpu-forced run strips it instead — that value
    # in a child's env has hung sitecustomize while the tunnel is down (the
    # boot snippet forces cpu in-process).
    if force_cpu:
        env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    else:
        env = dict(os.environ)
    proc = subprocess.Popen(
        [sys.executable, "-c", boot],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        env=env,
    )
    try:
        if not _wait_healthy():
            proc.terminate()
            err = proc.stderr.read().decode()[-500:] if proc.stderr else ""
            print(json.dumps({"error": "router failed to boot", "stderr": err}))
            sys.exit(1)

        base = "http://127.0.0.1:{}".format(PORT)
        try:
            with open(os.path.join(state_root, "backend.txt")) as f:
                backend = f.read().strip()
        except OSError:
            backend = "unknown"
        report = {
            "platform": args.platform,
            "backend": backend,
            "llm_preset": preset,
            "n": args.n,
            "concurrency": args.c,
            "endpoints": {},
        }
        report["endpoints"]["sklearn_process"] = _loadtest(
            base + "/serve/test_model_sklearn",
            {"x0": 5.1, "x1": 3.5, "x2": 1.4, "x3": 0.2},
            args.n,
            args.c,
        )
        # streaming chat: TTFT percentiles; fewer requests (each generates
        # tokens), lower concurrency than max_batch*queue to keep it honest
        report["endpoints"]["llm_chat_stream"] = _loadtest(
            base + "/serve/openai/v1/chat/completions",
            {
                "model": "test_llm",
                "messages": [{"role": "user", "content": "hello there"}],
                "max_tokens": 16,
                "stream": True,
            },
            max(64, args.n // 10),
            min(16, args.c),
        )
        # LOADTEST_<platform>.json now belongs to the SLO loadtest harness
        # (benchmarks/slo_loadtest.py, `bench.py --loadtest`); this router-
        # overhead report keeps its own artifact under a _router_ name
        out_path = REPO / "benchmarks" / "LOADTEST_router_{}.json".format(
            args.platform
        )
        out_path.write_text(json.dumps(report, indent=2) + "\n")
        print(json.dumps(report))
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


if __name__ == "__main__":
    main()
