"""Microbench: Pallas paged-attention kernel vs XLA gather vs dense cache.

Answers the standing question from ops/paged_attention.py's header: does the
r2 multi-page double-buffered-DMA kernel beat the plain-XLA page gather (the
r1 kernel lost, 4.3 vs 3.1 ms)?  Shapes are the r1 measurement's except
d=128 (Llama-3's real head_dim — Mosaic cannot lane-align a d=64 page plane,
so d=64 takes the XLA fallback by construction): b=16 hkv=8 g=4 d=128,
16-token pages, 64 pages/seq, bf16 pools, sequences half-full (512 tokens
live of 1024 capacity).

Contenders:
- pallas[pb=N]   ops.paged_attention (r2 kernel), pages_per_block sweep
- xla_gather     ops.paged_attention_xla (the fallback the kernel must beat)
- dense          attention over a dense [B, Hkv, S, D] cache at the same
                 occupancy — the no-paging baseline (wastes HBM capacity,
                 not traffic, at this occupancy)

Timing: the axon tunnel no-ops block_until_ready, so every timed section
ends in a host readback that data-depends on the result (np.asarray).
Prints one JSON line per contender plus a "winner" summary line.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

# standalone runs (`python benchmarks/paged_bench.py`) need the repo root on
# sys.path to reach the clearml_serving_tpu package
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

B, HKV, G, D = 16, 8, 4, 128
PAGE = 16
PAGES_PER_SEQ = 64
LIVE_TOKENS = PAGE * PAGES_PER_SEQ // 2  # half-full steady state
ROUNDS = 50


def _time(fn, *args, rounds=ROUNDS):
    out = fn(*args)
    np.asarray(out)  # warmup + compile, readback-synced
    t0 = time.perf_counter()
    for _ in range(rounds):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / rounds * 1e3  # ms


def main() -> None:
    from clearml_serving_tpu.ops import paged_attention as pa

    from clearml_serving_tpu.utils.tpu import is_tpu_device

    dev = jax.devices()[0]
    platform = "tpu" if is_tpu_device(dev) else dev.platform
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 5)
    n_pages = B * PAGES_PER_SEQ + 1
    q = jax.random.normal(ks[0], (B, HKV, G, D), jnp.bfloat16)
    k_pool = jax.random.normal(ks[1], (HKV, n_pages, PAGE, D), jnp.bfloat16)
    v_pool = jax.random.normal(ks[2], (HKV, n_pages, PAGE, D), jnp.bfloat16)
    page_table = jnp.arange(1, B * PAGES_PER_SEQ + 1, dtype=jnp.int32).reshape(
        B, PAGES_PER_SEQ
    )
    lengths = jnp.full((B,), LIVE_TOKENS, jnp.int32)

    results = {}

    xla = jax.jit(pa.paged_attention_xla)
    results["xla_gather"] = _time(xla, q, k_pool, v_pool, page_table, lengths)

    if platform == "tpu":
        for pb in (4, 8, 16, 32):
            fn = jax.jit(
                lambda q, k, v, pt, ln, pb=pb: pa.paged_attention(
                    q, k, v, pt, ln, pages_per_block=pb
                )
            )
            try:
                results["pallas_pb{}".format(pb)] = _time(
                    fn, q, k_pool, v_pool, page_table, lengths
                )
            except Exception as ex:  # record, keep sweeping
                print(json.dumps({"contender": "pallas_pb{}".format(pb),
                                  "error": str(ex)[:200]}))

    # dense baseline: same live tokens in a dense cache (max capacity seq)
    seq_cap = PAGE * PAGES_PER_SEQ
    k_dense = jax.random.normal(ks[3], (B, HKV, seq_cap, D), jnp.bfloat16)
    v_dense = jax.random.normal(ks[4], (B, HKV, seq_cap, D), jnp.bfloat16)

    def dense_attn(q, k, v, lengths):
        # q [B,Hkv,G,D]; masked flash-free softmax over full capacity
        s = jnp.einsum("bhgd,bhsd->bhgs", q, k, preferred_element_type=jnp.float32)
        s = s / np.sqrt(D)
        mask = jnp.arange(seq_cap)[None, None, None, :] < lengths[:, None, None, None]
        s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum(
            "bhgs,bhsd->bhgd", p.astype(k.dtype), v, preferred_element_type=jnp.float32
        ).astype(q.dtype)

    results["dense_fullcap"] = _time(jax.jit(dense_attn), q, k_dense, v_dense, lengths)

    for name, ms in results.items():
        print(json.dumps({"contender": name, "ms": round(ms, 3),
                          "platform": platform}))
    best_pallas = min(
        (v for k, v in results.items() if k.startswith("pallas")), default=None
    )
    summary = {
        "metric": "paged_attention_decode_b16",
        "platform": platform,
        "xla_gather_ms": round(results["xla_gather"], 3),
        "dense_ms": round(results["dense_fullcap"], 3),
    }
    if best_pallas is not None:
        summary["best_pallas_ms"] = round(best_pallas, 3)
        summary["pallas_vs_gather"] = round(results["xla_gather"] / best_pallas, 3)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
