"""Microbench: Pallas paged-attention kernel vs XLA gather vs dense cache.

Two questions, two scenario families:

1. ``uniform`` (r1-r3 continuity): b=16 hkv=8 g=4 d=128, 16-token pages,
   sequences uniformly half-full (512 of 1024).  Answers "does the r2
   multi-page double-buffered-DMA kernel beat the plain-XLA page gather"
   (r3 on v5e: yes, 2.391 vs 2.744 ms).

2. ``ragged`` (VERDICT r3 #3): b=32/64 with a realistic serving length
   mix (128..4096 cycling) at 4096-token capacity.  This is where paging
   PAYS: a dense full-capacity cache must stream B*4096 positions of K/V
   through the MXU-adjacent bandwidth every decode step regardless of how
   short most sequences are, while paged contenders touch only live
   pages (~1/3 of capacity for this mix).  The summary also emits the
   HBM-capacity side of the argument: bytes a dense cache would pin vs
   the paged pool, and the max decode batch each fits in the same budget
   — the dense-fullcap configuration OOMs out of slots long before the
   paged pool does.

Contenders per scenario:
- pallas[pb=N]   ops.paged_attention (r2 kernel), pages_per_block sweep
- xla_gather     ops.paged_attention_xla (the fallback the kernel must beat)
- dense          attention over a dense [B, Hkv, cap, D] cache, the
                 no-paging baseline

Timing: the axon tunnel no-ops block_until_ready, so every timed section
ends in a host readback that data-depends on the result (np.asarray).
Prints one JSON line per contender plus a "winner" summary per scenario.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

# standalone runs (`python benchmarks/paged_bench.py`) need the repo root on
# sys.path to reach the clearml_serving_tpu package
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

HKV, G, D = 8, 4, 128
PAGE = 16
ROUNDS = 50

# realistic serving mix for the ragged scenarios (vLLM-style ragged decode
# batch: many short chats, a few long-context stragglers)
RAGGED_MIX = (128, 256, 512, 512, 1024, 2048, 4096, 256)


def _time(fn, *args, rounds=ROUNDS):
    out = fn(*args)
    np.asarray(out)  # warmup + compile, readback-synced
    t0 = time.perf_counter()
    for _ in range(rounds):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / rounds * 1e3  # ms


def _scenario(name, batch, seq_cap, lengths_list, platform, pa):
    """Time all contenders on one (batch, capacity, lengths) shape."""
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 5)
    pages_per_seq = seq_cap // PAGE
    lengths = np.asarray(lengths_list, np.int32)
    assert lengths.shape[0] == batch

    # paged pool sized by LIVE pages (+1 reserved null page 0 that padded
    # table entries point at) — that sizing IS paging's capacity win
    live_pages_per_seq = -(-lengths // PAGE)  # ceil
    n_pages = int(live_pages_per_seq.sum()) + 1
    q = jax.random.normal(ks[0], (batch, HKV, G, D), jnp.bfloat16)
    k_pool = jax.random.normal(ks[1], (HKV, n_pages, PAGE, D), jnp.bfloat16)
    v_pool = jax.random.normal(ks[2], (HKV, n_pages, PAGE, D), jnp.bfloat16)
    table = np.zeros((batch, pages_per_seq), np.int32)
    nxt = 1
    for b in range(batch):
        n = int(live_pages_per_seq[b])
        table[b, :n] = np.arange(nxt, nxt + n)
        nxt += n
    page_table = jnp.asarray(table)
    lengths_dev = jnp.asarray(lengths)

    results = {}
    xla = jax.jit(pa.paged_attention_xla)
    results["xla_gather"] = _time(xla, q, k_pool, v_pool, page_table, lengths_dev)

    if platform == "tpu":
        for pb in (4, 8, 16, 32):
            fn = jax.jit(
                lambda q, k, v, pt, ln, pb=pb: pa.paged_attention(
                    q, k, v, pt, ln, pages_per_block=pb
                )
            )
            try:
                results["pallas_pb{}".format(pb)] = _time(
                    fn, q, k_pool, v_pool, page_table, lengths_dev
                )
            except Exception as ex:  # record, keep sweeping
                print(json.dumps({"scenario": name,
                                  "contender": "pallas_pb{}".format(pb),
                                  "error": str(ex)[:200]}))

    # dense baseline: full-capacity cache, masked softmax (what the dense
    # cache_mode engine does) — pays capacity-proportional bandwidth
    k_dense = jax.random.normal(ks[3], (batch, HKV, seq_cap, D), jnp.bfloat16)
    v_dense = jax.random.normal(ks[4], (batch, HKV, seq_cap, D), jnp.bfloat16)

    def dense_attn(q, k, v, lengths):
        s = jnp.einsum("bhgd,bhsd->bhgs", q, k, preferred_element_type=jnp.float32)
        s = s / np.sqrt(D)
        mask = jnp.arange(seq_cap)[None, None, None, :] < lengths[:, None, None, None]
        s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum(
            "bhgs,bhsd->bhgd", p.astype(k.dtype), v, preferred_element_type=jnp.float32
        ).astype(q.dtype)

    try:
        results["dense_fullcap"] = _time(
            jax.jit(dense_attn), q, k_dense, v_dense, lengths_dev
        )
    except Exception as ex:  # an OOM here IS a result: paging fit, dense didn't
        print(json.dumps({"scenario": name, "contender": "dense_fullcap",
                          "error": str(ex)[:200]}))

    for cname, ms in results.items():
        print(json.dumps({"scenario": name, "contender": cname,
                          "ms": round(ms, 3), "platform": platform}))

    bytes_per_tok = HKV * D * 2 * 2  # K+V, bf16
    dense_bytes = batch * seq_cap * bytes_per_tok
    paged_bytes = n_pages * PAGE * bytes_per_tok
    best_pallas = min(
        (v for k, v in results.items() if k.startswith("pallas")), default=None
    )
    summary = {
        "metric": "paged_attention_decode_{}".format(name),
        "platform": platform,
        "batch": batch,
        "seq_cap": seq_cap,
        "live_frac": round(float(lengths.sum()) / (batch * seq_cap), 3),
        "xla_gather_ms": round(results["xla_gather"], 3),
        # capacity argument: same HBM budget fits this many more sequences
        "dense_cache_mb": round(dense_bytes / 2**20, 1),
        "paged_pool_mb": round(paged_bytes / 2**20, 1),
        "capacity_ratio": round(dense_bytes / paged_bytes, 2),
    }
    if "dense_fullcap" in results:
        summary["dense_ms"] = round(results["dense_fullcap"], 3)
    if best_pallas is not None:
        summary["best_pallas_ms"] = round(best_pallas, 3)
        summary["pallas_vs_gather"] = round(
            results["xla_gather"] / best_pallas, 3
        )
        if "dense_fullcap" in results:
            summary["pallas_vs_dense"] = round(
                results["dense_fullcap"] / best_pallas, 3
            )
    print(json.dumps(summary))


def main() -> None:
    from clearml_serving_tpu.ops import paged_attention as pa
    from clearml_serving_tpu.utils.tpu import is_tpu_device

    dev = jax.devices()[0]
    platform = "tpu" if is_tpu_device(dev) else dev.platform

    # r1-r3 continuity point: uniform half-full occupancy at b16
    _scenario(
        "b16_uniform", 16, 1024, [512] * 16, platform, pa
    )
    # where paging pays: big ragged batches at long capacity (VERDICT r3 #3)
    for batch in (32, 64):
        lengths = [RAGGED_MIX[i % len(RAGGED_MIX)] for i in range(batch)]
        _scenario(
            "b{}_ragged_4k".format(batch), batch, 4096, lengths, platform, pa
        )


if __name__ == "__main__":
    main()
