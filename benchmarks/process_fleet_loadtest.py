"""Process-backend fleet loadtest (docs/replication.md "process
backends", docs/disaggregation.md).

Replays the PR-14 disaggregation trace (benchmarks/disagg_loadtest.py —
repeated conversations + batch one-shots, plus a seeded-sampling tail)
against two arms —

1. ``mono``:         ONE in-process replica (the byte-identity baseline,
                     same model spec the workers rebuild), and
2. ``proc_disagg``:  TWO worker PROCESSES split prefill/decode
                     (serving/process_replica.py), every admission's
                     prefix crossing the socket KV wire (llm/kv_wire.py)

— and certifies the ISSUE-19 acceptance criteria on the committed
artifact (``benchmarks/PROCESS_FLEET_cpu.json``, asserted by
tests/test_loadtest_artifact.py in tier-1):

- ship hit rate >= 0.9 on the clean path: decode-side admissions find
  the shipped prefix resident after a REAL socket hop (worker-side
  ``kv_ship`` counters read over the health RPC, not harness
  bookkeeping);
- streams byte-identical to the mono in-process arm, greedy AND seeded
  (the process boundary is a pure transport, never a numerics change);
- 0 KV-sanitizer violations, 0 ownership-ledger leaks, 0 post-warmup
  XLA compiles, 0 implicit cross-device transfers — each worker arms
  its own sanitizer/strict ledger/strict compile sentry/shard sentry
  from the inherited environment and fences its own sentry after the
  full warmup sweep; the certificates come back over the health RPC.

Measurement model: same caveat as the PR-14 loadtest — on this one-core
container both workers AND the parent share one core, so the goodput
column carries scheduler interference no real fleet has and is reported
for SHAPE only. The artifact certifies correctness, ship hit rate, and
the zero-violation certificates, not throughput. (On a real slice each
worker owns its chips and the socket hop is the only added latency.)

    python benchmarks/process_fleet_loadtest.py --smoke
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import disagg_loadtest as base  # noqa: E402  (the PR-14 trace is the spec)

ARTIFACT = REPO / "benchmarks" / "PROCESS_FLEET_cpu.json"

# artifact schema (asserted by tests/test_loadtest_artifact.py in tier-1)
SCHEMA_KEYS = {
    "metric", "platform", "smoke", "replicas", "engine", "trace", "arms",
    "headline",
}
ARM_KEYS = {
    "name", "backend", "replicas", "roles", "requests", "completed",
    "shed", "errors", "duration_s", "goodput_tok_s",
    "interactive_ttft_p50_ms", "interactive_ttft_p99_ms",
    "streams_identical_to_mono", "seeded_identical_to_mono",
    "post_warmup_compiles", "warmup_requests", "sanitizer_checks",
    "sanitizer_violations", "ledger_leaks", "implicit_transfers",
    "kv_ship", "wire", "restarts",
}
HEADLINE_KEYS = {
    "ship_hit_rate", "ship_hit_bound", "ship_ok", "ship_legs",
    "ship_drops", "streams_identical", "seeded_identical",
    "goodput_tok_s_mono", "goodput_tok_s_proc", "goodput_note",
    "post_warmup_compiles", "compile_sentry_mode", "sanitizer_checks",
    "sanitizer_violations", "ledger_leaks", "implicit_transfers",
    "wire_bytes_total", "wire_frames_total", "worker_restarts",
}

N_SEEDED = 3


def seeded_prompt(i: int) -> List[int]:
    return [(i * 37 + j * 11) % 239 + 1 for j in range(40)]


async def _warm_extras(group) -> None:
    """One throwaway seeded request BEFORE the full sweep fences the
    sentry: the sampling-extras variant (per-request seeds route through
    SamplingExtras) traces on first use by declared design (llm/warmup.py
    coverage note), so it must compile in the warmup phase for the
    measured seeded tail to stay compile-free under strict."""
    from clearml_serving_tpu.llm.engine import GenRequest

    from clearml_serving_tpu.errors import EngineUnavailableError

    last: Optional[BaseException] = None
    for _attempt in range(40):
        request = GenRequest(
            prompt_ids=seeded_prompt(N_SEEDED), max_new_tokens=4,
            temperature=0.8, seed=7,
        )
        try:
            async for _ in group.generate(request):
                pass
            return
        except EngineUnavailableError as ex:
            # ring not admitted yet (the gate task races the builder's
            # return on the process backend): retry, don't fence-poison
            last = ex
            await asyncio.sleep(0.5)
    raise RuntimeError("extras warmup never admitted: {}".format(last))


async def _seeded_tail(group) -> Dict[int, List[int]]:
    """A few SAMPLED streams (fixed seed): byte-identity must hold for
    the seeded sampler too, not just argmax."""
    from clearml_serving_tpu.llm.engine import GenRequest

    out: Dict[int, List[int]] = {}
    for i in range(N_SEEDED):
        request = GenRequest(
            prompt_ids=seeded_prompt(i), max_new_tokens=6,
            temperature=0.8, seed=1000 + i,
        )
        toks: List[int] = []
        async for token in group.generate(request):
            toks.append(int(token))
        out[i] = toks
    return out


def _worker_certs(group) -> Dict[str, Any]:
    """Certificates + ship/wire counters from every replica's health
    block. For the process backend this is the RPC into each worker —
    the parent has no other view of a worker's sanitizer/ledger/sentry."""
    certs = {
        "sanitizer_checks": 0, "sanitizer_violations": 0,
        "ledger_leaks": 0, "implicit_transfers": 0,
        "post_warmup_compiles": 0, "wire_bytes": 0, "wire_frames": 0,
    }
    ship_sum: Dict[str, int] = {}
    for replica in group.replicas:
        h = replica.engine.health()
        sanitizer = h.get("sanitizer")
        if sanitizer:
            certs["sanitizer_checks"] += int(sanitizer.get("checks", 0))
            certs["sanitizer_violations"] += int(sanitizer.get("failures", 0))
        ledger = h.get("ledger")
        if ledger:
            certs["ledger_leaks"] += int(ledger.get("leaks", 0))
        sharding = h.get("sharding")
        if sharding:
            certs["implicit_transfers"] += int(
                sharding.get("implicit_transfers", 0)
            )
        compile_block = h.get("compile")
        if compile_block:
            certs["post_warmup_compiles"] = max(
                certs["post_warmup_compiles"],
                int(compile_block.get("serve", 0)),
            )
        ship = h.get("kv_ship")
        if ship:
            for key in ("ships", "ship_pages", "ship_drops", "receives",
                        "receive_pages", "receive_empty",
                        "receive_failures", "hits", "recomputes"):
                ship_sum[key] = ship_sum.get(key, 0) + int(ship.get(key, 0))
            wire = (ship.get("transport") or {}).get("wire")
            if wire:
                certs["wire_bytes"] += (
                    int(wire.get("bytes_sent", 0))
                    + int(wire.get("bytes_received", 0))
                )
                certs["wire_frames"] += (
                    int(wire.get("frames_sent", 0))
                    + int(wire.get("frames_received", 0))
                )
    judged = ship_sum.get("hits", 0) + ship_sum.get("recomputes", 0)
    ship_sum["hit_rate"] = (
        round(ship_sum["hits"] / judged, 4) if judged else None
    )
    certs["kv_ship"] = ship_sum or None
    return certs


async def _run_mono_arm() -> dict:
    group, cfg = base.build_group(1, None)
    try:
        await group.warmup(full=False)
        await _warm_extras(group)
        warm = await group.warmup(full=True)
        trace = await base._run_trace(group, seed=13)
        streams = {}
        for rec in trace.pop("records"):
            if rec["status"] == "ok":
                key = (
                    ("c", rec["conv"], rec["turn"])
                    if rec["cls"] == "interactive" else ("b", rec["idx"])
                )
                streams[key] = rec["tokens"]
        seeded = await _seeded_tail(group)
        await group.wait_drained()
        certs = _worker_certs(group)
        arm = dict(
            trace,
            name="mono",
            backend="inprocess",
            replicas=1,
            roles=["hybrid"],
            streams_identical_to_mono=None,
            seeded_identical_to_mono=None,
            warmup_requests=warm["requests"],
            post_warmup_compiles=certs["post_warmup_compiles"],
            sanitizer_checks=certs["sanitizer_checks"],
            sanitizer_violations=certs["sanitizer_violations"],
            ledger_leaks=certs["ledger_leaks"],
            implicit_transfers=certs["implicit_transfers"],
            kv_ship=certs["kv_ship"],
            wire=None,
            restarts=0,
        )
        return {"arm": arm, "streams": streams, "seeded": seeded,
                "cfg": cfg}
    finally:
        group.stop()


async def _run_process_arm(expected: dict, expected_seeded: dict) -> dict:
    from clearml_serving_tpu.serving.process_replica import (
        build_process_fleet,
    )

    cfg = base.engine_cfg()
    group = build_process_fleet(
        {
            "arch": "llama",
            "config": {
                "preset": "llama-tiny", "dtype": "float32",
                "kv_quant": "int8",
            },
            "seed": 0,
        },
        cfg,
        2,
        roles=["prefill", "decode"],
        warmup_mode="startup",
        cpu_devices=1,
        startup_timeout=600.0,
    )
    try:
        # workers arrive startup-warming (unfenced); the full=False pass
        # awaits the in-flight gate without fencing, then the extras
        # request drives the seeded-sampling variant through BOTH roles
        # before the full sweep fences each worker's own sentry
        await group.warmup(full=False)
        await _warm_extras(group)
        warm = await group.warmup(full=True)
        trace = await base._run_trace(group, seed=13)
        streams = {}
        for rec in trace.pop("records"):
            if rec["status"] == "ok":
                key = (
                    ("c", rec["conv"], rec["turn"])
                    if rec["cls"] == "interactive" else ("b", rec["idx"])
                )
                streams[key] = rec["tokens"]
        seeded = await _seeded_tail(group)
        await group.wait_drained()
        certs = _worker_certs(group)
        identical = bool(streams) and streams == expected
        seeded_identical = seeded == expected_seeded
        arm = dict(
            trace,
            name="proc_disagg",
            backend="process",
            replicas=2,
            roles=["prefill", "decode"],
            streams_identical_to_mono=identical,
            seeded_identical_to_mono=seeded_identical,
            warmup_requests=warm["requests"],
            post_warmup_compiles=certs["post_warmup_compiles"],
            sanitizer_checks=certs["sanitizer_checks"],
            sanitizer_violations=certs["sanitizer_violations"],
            ledger_leaks=certs["ledger_leaks"],
            implicit_transfers=certs["implicit_transfers"],
            kv_ship=certs["kv_ship"],
            wire={
                "bytes_total": certs["wire_bytes"],
                "frames_total": certs["wire_frames"],
            },
            restarts=sum(r.restarts for r in group.replicas),
        )
        return {"arm": arm}
    finally:
        group.stop()


async def _run_async(smoke: bool) -> dict:
    mono = await _run_mono_arm()
    proc = await _run_process_arm(mono["streams"], mono["seeded"])
    a1, a2 = mono["arm"], proc["arm"]
    ship = a2["kv_ship"] or {}
    return {
        "metric": "llm_process_fleet_loadtest" + (
            "_cpusmoke" if smoke else ""
        ),
        "platform": "cpu",
        "smoke": smoke,
        "replicas": 2,
        "engine": {
            k: v for k, v in mono["cfg"].items() if k != "prefill_buckets"
        },
        "trace": {
            "conversations": base.N_CONVERSATIONS,
            "turns": base.N_TURNS,
            "conv_base_tokens": base.CONV_BASE,
            "turn_step_tokens": base.TURN_STEP,
            "conv_max_new": base.CONV_MAX_NEW,
            "batch_requests": base.N_BATCH,
            "batch_prompt_tokens": base.BATCH_PROMPT,
            "batch_max_new": base.BATCH_MAX_NEW,
            "seeded_requests": N_SEEDED,
        },
        "arms": [a1, a2],
        "headline": {
            "ship_hit_rate": ship.get("hit_rate"),
            "ship_hit_bound": 0.9,
            "ship_ok": bool(
                ship.get("hit_rate") is not None
                and ship["hit_rate"] >= 0.9
            ),
            "ship_legs": ship.get("ships", 0),
            "ship_drops": ship.get("ship_drops", 0),
            "streams_identical": bool(a2["streams_identical_to_mono"]),
            "seeded_identical": bool(a2["seeded_identical_to_mono"]),
            "goodput_tok_s_mono": a1["goodput_tok_s"],
            "goodput_tok_s_proc": a2["goodput_tok_s"],
            "goodput_note": (
                "two worker processes + parent share ONE core here: the "
                "goodput column carries scheduler interference no real "
                "fleet has; this artifact certifies correctness, ship "
                "hit rate, and the zero-violation certificates"
            ),
            "post_warmup_compiles": max(
                a1["post_warmup_compiles"], a2["post_warmup_compiles"]
            ),
            "compile_sentry_mode": "strict",
            "sanitizer_checks": (
                a1["sanitizer_checks"] + a2["sanitizer_checks"]
            ),
            "sanitizer_violations": (
                a1["sanitizer_violations"] + a2["sanitizer_violations"]
            ),
            "ledger_leaks": a1["ledger_leaks"] + a2["ledger_leaks"],
            "implicit_transfers": (
                a1["implicit_transfers"] + a2["implicit_transfers"]
            ),
            "wire_bytes_total": (a2["wire"] or {}).get("bytes_total", 0),
            "wire_frames_total": (a2["wire"] or {}).get("frames_total", 0),
            "worker_restarts": a2["restarts"],
        },
    }


def run(smoke: bool = True, write_artifact: bool = True) -> dict:
    """Arms every certificate BEFORE any engine exists — the parent's
    mono arm reads them in-process, the worker processes inherit them
    through the environment and report back over the health RPC."""
    os.environ["TPUSERVE_SANITIZE"] = "1"
    os.environ["TPUSERVE_LEDGER"] = "strict"
    os.environ["TPUSERVE_COMPILE_SENTRY"] = "strict"
    os.environ["TPUSERVE_SHARD_SENTRY"] = "1"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from clearml_serving_tpu.engines.jax_engine import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    row = asyncio.run(_run_async(smoke))
    if write_artifact:
        ARTIFACT.write_text(json.dumps(row, indent=2) + "\n")
    return row


def main() -> None:
    smoke = "--smoke" in sys.argv
    row = run(smoke=smoke)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
