"""Replica-fleet router loadtest (docs/replication.md).

Replays a REPEATED-CONVERSATION + batch trace against one replica and
against a 2-replica engine group behind the prefix-affine router
(serving/replica_router.py), then runs the kill-one-replica chaos case on
the fleet. Headline (ISSUE 12 acceptance, asserted on the committed
artifact by tests/test_loadtest_artifact.py):

- affine-hit rate >= 0.9 on the repeated-conversation slice (turn >= 2
  requests whose routed replica already held their prefix KV),
- aggregate goodput >= 1.6x the single-replica arm,
- 0 post-warmup XLA compiles (strict compile sentry; the run FAILS
  otherwise), 0 KV-sanitizer violations,
- the chaos case (watchdog-trip one replica mid-trace) completes with 0
  user-visible 503s and byte-identical streams for untouched
  conversations.

Measurement model, stated plainly: every replica gets the SAME per-chip
budget (slots, KV pool, prefix-cache pages). The ROUTING runs for real —
every request of the trace goes through the live router (ring sweeps,
HRW order, route counters) — and then each replica EXECUTES its routed
substream in isolation, with the fleet's duration taken as the MAX over
its replicas' substream durations. That is a parallel wall-clock
ESTIMATE: it models replicas as non-interfering, which is exactly true
of the production deployment (one replica per chip group / host,
parallel/multihost.py) and is the only honest way to measure a fleet on
this ONE-core CPU container — time-sharing two engine loops on one core
measures scheduler interference that no real fleet has (observed: false
watchdog trips and 2-6x wall-time inflation from co-scheduling). The
chaos case still runs both replicas CONCURRENTLY: it asserts
correctness (zero 503s, byte identity, re-admission), not timing.

The scrambled-routing arm replays the same trace on the same fleet with
per-turn pseudo-random replica assignment — what a affinity-blind load
balancer would do. Its affine-hit rate and goodput quantify what the
prefix-affine hash is worth: conversations alternating replicas leave
KV gaps on both, and every gap is re-prefill work.

    python bench.py --loadtest --replicas 2 --smoke   # CPU; updates
                                                      # LOADTEST_replicas_cpu.json
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

REPO = Path(__file__).resolve().parent.parent
ARTIFACT = REPO / "benchmarks" / "LOADTEST_replicas_cpu.json"

# artifact schema (asserted by tests/test_loadtest_artifact.py in tier-1)
SCHEMA_KEYS = {
    "metric", "platform", "smoke", "replicas", "engine", "trace", "arms",
    "chaos", "headline",
}
ARM_KEYS = {
    "replicas", "routing", "requests", "completed", "shed", "errors",
    "duration_s", "substream_durations_s", "parallel_estimate",
    "goodput_tok_s", "interactive_ttft_p50_ms", "interactive_ttft_p99_ms",
    "affine_hit_rate", "affine_eligible", "routes", "preemptions",
    "post_warmup_compiles", "warmup_requests",
}
CHAOS_KEYS = {
    "requests", "completed", "unavailable_errors", "other_errors",
    "failovers", "ejections", "readmissions", "ring_recovered",
    "untouched_streams_identical", "failover_stream_identical",
    "post_warmup_compiles",
}
HEADLINE_KEYS = {
    "affine_hit_rate", "affine_hit_bound", "affine_ok",
    "goodput_tok_s_single", "goodput_tok_s_fleet", "speedup",
    "speedup_bound", "speedup_ok", "interactive_p99_ttft_ms_single",
    "interactive_p99_ttft_ms_fleet",
    # the affinity-blind contrast arm: same fleet, per-turn random
    # assignment — what the prefix-affine hash is worth
    "affine_hit_rate_random", "goodput_tok_s_random",
    "post_warmup_compiles",
    "compile_sentry_mode", "sanitizer_checks", "sanitizer_violations",
    "chaos_unavailable_errors", "chaos_ok",
}

# the trace: C multi-turn conversations (interactive chat whose history
# grows by TURN_STEP tokens per turn — the radix cache's repeated-prefix
# workload) + closed-loop batch summarization pressure. 24 conversations
# at their final 11-page storable prefix = 264 pages of working set:
# far over ONE replica's 160-page prefix budget (leaf-LRU decays every
# run's tail, so turns re-prefill most of their history), comfortably
# under the fleet's 2x160 with the HRW split (14/10 for these ids).
N_CONVERSATIONS = 24
N_TURNS = 5
CONV_BASE = 128          # tokens of history at turn 0
TURN_STEP = 16           # tokens appended per turn (1 prefix block)
CONV_MAX_NEW = 8
N_BATCH = 12             # batch one-shots across BATCH_WORKERS workers
BATCH_WORKERS = 2
BATCH_PROMPT = 48
BATCH_MAX_NEW = 24

# chaos phase sizing
CHAOS_CONVS_PER_REPLICA = 2
CHAOS_TURNS = 3
SENTINEL = 251           # plants the watchdog-stall fault on the victim


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def conv_history(conv: int, n: int) -> List[int]:
    """Deterministic per-conversation token stream (same (conv, n) always
    yields the same prefix, so turn t+1 extends turn t's exact history)."""
    return [(conv * 67 + i * 13) % 239 + 1 for i in range(n)]


def conv_prompt(conv: int, turn: int) -> List[int]:
    return conv_history(conv, CONV_BASE + TURN_STEP * turn)


def batch_prompt(i: int) -> List[int]:
    return [(i * 101 + j * 17) % 239 + 1 for j in range(BATCH_PROMPT)]


def engine_cfg() -> Dict[str, Any]:
    """One replica = one chip's budget. The 160-page prefix budget holds
    ~14 conversations at their final 11-page storable prefix: the fleet's
    14/10 split stays fully resident per replica, one replica decays."""
    return dict(
        max_batch=4,
        max_seq_len=256,
        prefill_buckets=[32, 64, 128, 160, 192],
        eos_token_id=None,          # fixed work per request
        decode_steps=1,
        cache_mode="paged",
        page_size=16,
        chunked_prefill_size=16,
        prefix_cache=384,
        prefix_block=16,
        num_pages=257,              # 256 usable (page 0 is the null page)
        prefix_cache_pages=160,
        max_pending=32,
        preempt_batch=True,
        preempt_budget=2,
        brownout=True,
        brownout_dwell=1.0,
        # the chaos case trips this; 2s (not the robustness-suite 0.3s)
        # because co-scheduled replicas share this host's ONE core — a
        # busy sibling must not read as a stall (observed: 0.5s
        # false-tripped the fleet arm under full load)
        watchdog_interval=2.0,
        # a single-core host gains no overlap from pipelining but pays its
        # commit/quarantine latency in TTFT (bench.py --pipeline-ab note)
        pipeline_depth=1 if (os.cpu_count() or 1) == 1 else None,
    )


def build_group(n_replicas: int):
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import LLMEngineCore
    from clearml_serving_tpu.llm.replica import ReplicaGroup

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    cfg = engine_cfg()
    engines = [
        LLMEngineCore(bundle, params, replica="r{}".format(i), **cfg)
        for i in range(n_replicas)
    ]
    # warmup_mode="startup" makes post-ejection re-admission re-warm with
    # the cheap per-bucket pass (fast, compile-free after the full sweep
    # below) — the gate machinery the chaos case must drive
    return ReplicaGroup(engines, warmup_mode="startup"), cfg


async def _consume(target, request, rec: dict, records: List[dict]) -> None:
    """Drive one request against ``target`` (a ReplicaGroup in the chaos
    phase, a bare engine in the isolated substreams) and record the
    outcome."""
    from clearml_serving_tpu.errors import (
        DeadlineExceededError,
        EngineOverloadedError,
        EngineUnavailableError,
    )

    try:
        toks: List[int] = []
        async for token in target.generate(request):
            toks.append(int(token))
        rec["status"] = "ok"
        rec["tokens"] = toks
        if request.first_token_at is not None:
            rec["ttft_ms"] = (
                request.first_token_at - request.submitted_at
            ) * 1e3
        rec["t_done"] = time.perf_counter()
    except EngineOverloadedError:
        rec["status"] = "shed"
    except EngineUnavailableError as ex:
        # the chaos criterion: a user-visible 503 — the failure drain must
        # keep this at zero even with a replica mid-trip
        rec["status"] = "unavailable"
        rec["error"] = repr(ex)[:200]
    except DeadlineExceededError:
        rec["status"] = "deadline"
    except asyncio.CancelledError:
        rec["status"] = "cancelled"
        raise
    except Exception as ex:  # noqa: BLE001 - harness must keep counting
        rec["status"] = "error"
        rec["error"] = repr(ex)[:200]
    finally:
        records.append(rec)


def _assign(group, scramble: bool, seed: int):
    """Route the whole trace through the LIVE router (route counters and
    ring sweeps run for real) and return per-replica substreams:
    ``(conv_turns[name][conv] -> [turns...], batch_ids[name])``. With
    ``scramble`` the router is bypassed per (conv, turn) by a hash — the
    affinity-blind contrast assignment."""
    import hashlib

    from clearml_serving_tpu.llm.engine import GenRequest

    names = [r.name for r in group.replicas]
    conv_turns: Dict[str, Dict[int, List[int]]] = {n: {} for n in names}
    batch_ids: Dict[str, List[int]] = {n: [] for n in names}

    def scrambled(tag: str) -> str:
        h = hashlib.blake2b(
            "{}/{}".format(seed, tag).encode(), digest_size=4
        ).digest()
        return names[int.from_bytes(h, "little") % len(names)]

    for conv in range(N_CONVERSATIONS):
        for turn in range(N_TURNS):
            ids = conv_prompt(conv, turn)
            if scramble:
                name = scrambled("c{}/{}".format(conv, turn))
            else:
                replica, _ = group.router.pick(GenRequest(
                    prompt_ids=ids, max_new_tokens=CONV_MAX_NEW,
                    priority="interactive",
                ))
                name = replica.name
            conv_turns[name].setdefault(conv, []).append(turn)
    for i in range(N_BATCH):
        if scramble:
            name = scrambled("b{}".format(i))
        else:
            replica, _ = group.router.pick(GenRequest(
                prompt_ids=batch_prompt(i), max_new_tokens=BATCH_MAX_NEW,
                priority="batch",
            ))
            name = replica.name
        batch_ids[name].append(i)
    return conv_turns, batch_ids


async def _run_substream(replica, conv_turns, batch_ids, seed: int) -> dict:
    """Execute one replica's routed substream in ISOLATION (no sibling on
    the core): conversation sessions run their assigned turns in order
    with think times, batch workers run closed-loop. Affine hit = a
    turn>=1 request whose replica already held (nearly) the WHOLE history
    in its radix cache — measured against the real tree, not the route
    label; leaf-LRU decay leaves head blocks resident on a thrashing
    cache, and counting those partial hits would flatter an arm that
    still re-prefills most of every turn."""
    from clearml_serving_tpu.llm.engine import GenRequest

    engine = replica.engine
    rng = random.Random(seed)
    records: List[dict] = []
    affine = {"eligible": 0, "hits": 0}

    async def session(conv: int, turns: List[int]) -> None:
        await asyncio.sleep(0.02 * (conv % 8))
        for turn in turns:
            ids = conv_prompt(conv, turn)
            if turn >= 1:
                affine["eligible"] += 1
                prefix = engine._prefix
                if prefix is not None and prefix.match_len(ids) >= (
                    len(ids) - 2 * prefix.block
                ):
                    affine["hits"] += 1
            request = GenRequest(
                prompt_ids=ids, max_new_tokens=CONV_MAX_NEW,
                priority="interactive",
            )
            rec = {"cls": "interactive", "conv": conv, "turn": turn}
            await _consume(engine, request, rec, records)
            await asyncio.sleep(rng.uniform(0.005, 0.03))

    async def batch_worker(wid: int) -> None:
        for i in batch_ids[wid::BATCH_WORKERS]:
            request = GenRequest(
                prompt_ids=batch_prompt(i), max_new_tokens=BATCH_MAX_NEW,
                priority="batch",
            )
            rec = {"cls": "batch", "idx": i}
            await _consume(engine, request, rec, records)

    t0 = time.perf_counter()
    await asyncio.gather(
        *[session(c, turns) for c, turns in sorted(conv_turns.items())],
        *[batch_worker(w) for w in range(BATCH_WORKERS)],
    )
    await engine.wait_drained()
    done_times = [r["t_done"] for r in records if "t_done" in r]
    duration = (max(done_times) if done_times else time.perf_counter()) - t0
    return {
        "records": records,
        "duration_s": duration,
        "affine": affine,
    }


async def _run_trace(group, seed: int, scramble: bool = False) -> dict:
    """The measured phase: route everything, then execute each replica's
    substream in isolation. Fleet duration = MAX over substream durations
    (the parallel wall-clock estimate the module docstring defends);
    goodput = total delivered tokens / that duration."""
    conv_turns, batch_ids = _assign(group, scramble, seed)
    preempt0 = sum(
        r.engine.counters["preemptions"] for r in group.replicas
    )
    records: List[dict] = []
    durations: Dict[str, float] = {}
    affine = {"eligible": 0, "hits": 0}
    for i, replica in enumerate(group.replicas):
        sub = await _run_substream(
            replica, conv_turns[replica.name], batch_ids[replica.name],
            seed + i,
        )
        records.extend(sub["records"])
        durations[replica.name] = round(sub["duration_s"], 3)
        affine["eligible"] += sub["affine"]["eligible"]
        affine["hits"] += sub["affine"]["hits"]
    duration = max(durations.values())
    done = [r for r in records if r["status"] == "ok"]
    ttfts = [
        r["ttft_ms"] for r in done
        if r["cls"] == "interactive" and r.get("ttft_ms") is not None
    ]
    return {
        "routing": "random" if scramble else (
            "affine" if len(group.replicas) > 1 else "single"
        ),
        "requests": len(records),
        "completed": len(done),
        "shed": sum(1 for r in records if r["status"] == "shed"),
        "errors": sum(
            1 for r in records
            if r["status"] not in ("ok", "shed")
        ),
        "duration_s": round(duration, 2),
        "substream_durations_s": durations,
        "parallel_estimate": len(group.replicas) > 1,
        "goodput_tok_s": round(
            sum(len(r.get("tokens", [])) for r in done)
            / max(1e-6, duration), 2,
        ),
        "interactive_ttft_p50_ms": round(_percentile(ttfts, 0.5) or 0.0, 2),
        "interactive_ttft_p99_ms": round(_percentile(ttfts, 0.99) or 0.0, 2),
        "affine_eligible": affine["eligible"],
        "affine_hit_rate": round(
            affine["hits"] / max(1, affine["eligible"]), 4
        ),
        "preemptions": sum(
            r.engine.counters["preemptions"] for r in group.replicas
        ) - preempt0,
    }


async def _run_chaos(group) -> dict:
    """Kill-one-replica mid-trace: fresh conversations split across both
    replicas; a sentinel token in one victim-routed conversation arms a
    one-shot decode stall that trips the victim's watchdog. The contract:
    every stream completes (failed ones resume on the sibling), zero
    user-visible 503s, untouched conversations byte-identical to their
    pre-chaos replay, and the victim re-warms through the gate back into
    the ring."""
    from clearml_serving_tpu.llm import faults
    from clearml_serving_tpu.llm.engine import GenRequest

    # fresh conversation ids (disjoint from the measured trace), grouped
    # by routed replica so the chaos case provably touches both
    by_replica: Dict[str, List[int]] = {r.name: [] for r in group.replicas}
    conv = 1000
    while any(
        len(v) < CHAOS_CONVS_PER_REPLICA for v in by_replica.values()
    ):
        ids = conv_prompt(conv, 0)
        name = group.router.order_for(ids)[0].name
        if len(by_replica[name]) < CHAOS_CONVS_PER_REPLICA:
            by_replica[name].append(conv)
        conv += 1
    victim_name = group.replicas[-1].name
    victim_conv = by_replica[victim_name][0]

    def prompt_for(c: int, turn: int) -> List[int]:
        ids = conv_prompt(c, turn)
        if c == victim_conv:
            # the sentinel rides the WHOLE conversation (prompt prefix),
            # so the one-shot stall fault targets exactly this stream
            ids = [SENTINEL] + ids[1:]
        return ids

    # pre-chaos replay: expected greedy tokens per (conv, turn) — the
    # byte-identity baseline (radix caching never changes tokens)
    expected: Dict[tuple, List[int]] = {}
    for name, convs in by_replica.items():
        for c in convs:
            for turn in range(CHAOS_TURNS):
                request = GenRequest(
                    prompt_ids=prompt_for(c, turn),
                    max_new_tokens=CONV_MAX_NEW,
                )
                toks = []
                async for t in group.generate(request):
                    toks.append(int(t))
                expected[(c, turn)] = toks
    await group.wait_drained()

    stats0 = group.router.stats()
    failovers0 = group.failovers
    faults.configure([
        {"point": "engine.decode.stall", "action": "delay",
         "delay": 5.0, "times": 1, "match_token": SENTINEL},
    ])
    records: List[dict] = []

    async def chaos_session(c: int) -> None:
        for turn in range(CHAOS_TURNS):
            request = GenRequest(
                prompt_ids=prompt_for(c, turn),
                max_new_tokens=CONV_MAX_NEW, priority="interactive",
            )
            rec = {"cls": "interactive", "conv": c, "turn": turn}
            await _consume(group, request, rec, records)

    try:
        await asyncio.gather(
            *[chaos_session(c) for convs in by_replica.values()
              for c in convs]
        )
    finally:
        faults.clear()

    # the victim recovers, re-warms through the gate, rejoins the ring
    ring_recovered = False
    t0 = time.monotonic()
    while time.monotonic() - t0 < 120.0:
        group.router.sweep()
        if group.router.ring_size == len(group.replicas):
            ring_recovered = True
            break
        await asyncio.sleep(0.05)
    await group.wait_drained()

    untouched_ok = True
    failover_ok = True
    for rec in records:
        if rec["status"] != "ok":
            continue
        same = rec["tokens"] == expected[(rec["conv"], rec["turn"])]
        if rec["conv"] == victim_conv:
            failover_ok = failover_ok and same
        else:
            untouched_ok = untouched_ok and same
    stats1 = group.router.stats()
    return {
        "requests": len(records),
        "completed": sum(1 for r in records if r["status"] == "ok"),
        "unavailable_errors": sum(
            1 for r in records if r["status"] == "unavailable"
        ),
        "other_errors": sum(
            1 for r in records
            if r["status"] not in ("ok", "unavailable")
        ),
        "failovers": group.failovers - failovers0,
        "ejections": sum(stats1["ejections"].values())
        - sum(stats0["ejections"].values()),
        "readmissions": sum(stats1["readmissions"].values())
        - sum(stats0["readmissions"].values()),
        "ring_recovered": ring_recovered,
        "untouched_streams_identical": untouched_ok,
        "failover_stream_identical": failover_ok,
    }


def _sentry_serve_count() -> int:
    from clearml_serving_tpu.llm import compile_sentry

    if not compile_sentry.enabled():
        return -1
    return int(compile_sentry.get().stats_brief().get("serve", -1))


async def _run_arm(n_replicas: int, with_chaos: bool,
                   scramble: bool = False) -> dict:
    from clearml_serving_tpu.llm import compile_sentry

    group, cfg = build_group(n_replicas)
    try:
        if compile_sentry.enabled():
            # fresh fence per arm: the next arm's engines re-warm their
            # own jit caches and those compiles must count as warmup
            compile_sentry.get().reset(strict=compile_sentry.strict_enabled())
        warm = await group.warmup(full=True)
        arm = await _run_trace(group, seed=7 + n_replicas, scramble=scramble)
        arm["replicas"] = n_replicas
        arm["routes"] = group.router.stats()["requests"]
        arm["warmup_requests"] = warm["requests"]
        arm["post_warmup_compiles"] = _sentry_serve_count()
        chaos = None
        if with_chaos:
            chaos = await _run_chaos(group)
            chaos["post_warmup_compiles"] = _sentry_serve_count()
        sanitizer_checks = 0
        sanitizer_failures = 0
        for replica in group.replicas:
            sanitizer = replica.engine._sanitizer
            if sanitizer is None:
                sanitizer_failures = -1
                continue
            s = sanitizer.stats()
            sanitizer_checks += s.get("checks", 0)
            sanitizer_failures += s.get("failures", 0)
        arm["sanitizer_checks"] = sanitizer_checks
        arm["sanitizer_violations"] = sanitizer_failures
        return {"arm": arm, "chaos": chaos, "cfg": cfg}
    finally:
        group.stop()


async def _run_async(smoke: bool, replicas: int) -> dict:
    from clearml_serving_tpu.llm import compile_sentry

    single = await _run_arm(1, with_chaos=False)
    fleet = await _run_arm(replicas, with_chaos=True)
    scrambled = await _run_arm(replicas, with_chaos=False, scramble=True)
    a1, a2, a3 = single["arm"], fleet["arm"], scrambled["arm"]
    chaos = fleet["chaos"]
    speedup = (
        a2["goodput_tok_s"] / a1["goodput_tok_s"]
        if a1["goodput_tok_s"] else None
    )
    chaos_ok = bool(
        chaos["unavailable_errors"] == 0
        and chaos["other_errors"] == 0
        and chaos["completed"] == chaos["requests"]
        and chaos["ring_recovered"]
        and chaos["untouched_streams_identical"]
    )
    sentry_mode = (
        compile_sentry.get().stats_brief().get("mode", "off")
        if compile_sentry.enabled() else "off"
    )
    post_warmup = max(
        a1["post_warmup_compiles"], a2["post_warmup_compiles"],
        a3["post_warmup_compiles"], chaos["post_warmup_compiles"],
    )
    return {
        "metric": "llm_replica_loadtest" + ("_cpusmoke" if smoke else ""),
        "platform": "cpu",
        "smoke": smoke,
        "replicas": replicas,
        "engine": {
            k: v for k, v in fleet["cfg"].items() if k != "prefill_buckets"
        },
        "trace": {
            "conversations": N_CONVERSATIONS,
            "turns": N_TURNS,
            "conv_base_tokens": CONV_BASE,
            "turn_step_tokens": TURN_STEP,
            "conv_max_new": CONV_MAX_NEW,
            "batch_requests": N_BATCH,
            "batch_prompt_tokens": BATCH_PROMPT,
            "batch_max_new": BATCH_MAX_NEW,
        },
        "arms": [a1, a2, a3],
        "chaos": chaos,
        "headline": {
            "affine_hit_rate": a2["affine_hit_rate"],
            "affine_hit_bound": 0.9,
            "affine_ok": bool(a2["affine_hit_rate"] >= 0.9),
            "goodput_tok_s_single": a1["goodput_tok_s"],
            "goodput_tok_s_fleet": a2["goodput_tok_s"],
            "speedup": round(speedup, 2) if speedup else None,
            "speedup_bound": 1.6,
            "speedup_ok": bool(speedup is not None and speedup >= 1.6),
            "interactive_p99_ttft_ms_single": a1["interactive_ttft_p99_ms"],
            "interactive_p99_ttft_ms_fleet": a2["interactive_ttft_p99_ms"],
            "affine_hit_rate_random": a3["affine_hit_rate"],
            "goodput_tok_s_random": a3["goodput_tok_s"],
            "post_warmup_compiles": post_warmup,
            "compile_sentry_mode": sentry_mode,
            "sanitizer_checks": a1["sanitizer_checks"]
            + a2["sanitizer_checks"] + a3["sanitizer_checks"],
            "sanitizer_violations": max(
                a1["sanitizer_violations"], a2["sanitizer_violations"],
                a3["sanitizer_violations"],
            ),
            "chaos_unavailable_errors": chaos["unavailable_errors"],
            "chaos_ok": chaos_ok,
        },
    }


def run(smoke: bool = True, replicas: int = 2,
        write_artifact: bool = True) -> dict:
    """Entry point for ``bench.py --loadtest --replicas N``. Forces the
    CPU backend, arms the KV sanitizer AND the strict compile sentry
    BEFORE any engine exists (completing at all is the zero-recompile
    certificate), runs both arms + the chaos case, optionally updates the
    committed artifact."""
    if replicas < 2:
        raise ValueError("the replica loadtest needs --replicas >= 2")
    os.environ["TPUSERVE_SANITIZE"] = "1"
    # forced, not defaulted: a pre-exported "1" must not silently
    # downgrade the certification run to count-only mode
    os.environ["TPUSERVE_COMPILE_SENTRY"] = "strict"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from clearml_serving_tpu.engines.jax_engine import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    row = asyncio.run(_run_async(smoke, replicas))
    if write_artifact:
        ARTIFACT.write_text(json.dumps(row, indent=2) + "\n")
    return row


def main() -> None:
    import sys

    smoke = "--smoke" in sys.argv
    row = run(smoke=smoke)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
