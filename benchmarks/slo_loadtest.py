"""SLO loadtest harness (docs/slo_scheduling.md, benchmarks/ROOFLINE.md).

Open-loop Poisson replay of a MIXED trace — long-prefix chat, short
completions, tool-call loops, batch summarization, embedding-style
best-effort scoring — against a REAL continuous-batching engine
(llm/engine.py) with priority classes, the preemptible batch lane and the
brownout controller armed, plus the runtime KV sanitizer
(TPUSERVE_SANITIZE=1) auditing page accounting through every preemption.

The harness first measures the engine's unloaded interactive TTFT and its
saturation throughput (closed loop), then sweeps offered load at fixed
multiples of saturation (0.5x, 1x, 2x) and reports, per class and per load:
p50/p99 TTFT, goodput (tokens/s of completed requests) and shed counts.

Headline claim it measures (ISSUE 6 acceptance): at >= 2x the measured
saturation load, interactive p99 TTFT stays within 3x its unloaded value
while batch goodput degrades smoothly (no cliff), with zero sanitizer
violations across >= 10 preemptions.

Open-loop matters: a closed-loop client backs off exactly when the server
struggles, hiding the overload the scheduler exists to survive; Poisson
arrivals at a fixed offered rate do not.

    python bench.py --loadtest --smoke     # CPU smoke; updates
                                           # benchmarks/LOADTEST_cpu.json
    python bench.py --loadtest             # longer run, same artifact shape

Wired into benchmarks/tpu_battery.py as phase 6 (subprocess, CPU-forced).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

REPO = Path(__file__).resolve().parent.parent
ARTIFACT = REPO / "benchmarks" / "LOADTEST_cpu.json"

# artifact schema (asserted by tests/test_loadtest_artifact.py in tier-1)
SCHEMA_KEYS = {
    "metric", "platform", "smoke", "engine", "mix", "unloaded_ttft_ms",
    "saturation_rps", "loads", "headline", "warmup",
}
LOAD_KEYS = {
    "x_saturation", "offered_rps", "arrivals", "duration_s", "classes",
    "preemptions", "brownout_stage_max",
}
CLASS_KEYS = {
    "requests", "completed", "shed", "errors", "ttft_p50_ms", "ttft_p99_ms",
    "goodput_tok_s",
}
HEADLINE_KEYS = {
    "interactive_p99_ttft_unloaded_ms", "interactive_p99_ttft_at_2x_ms",
    "ttft_ratio_at_2x", "ttft_bound", "ttft_within_bound",
    "batch_goodput_curve_tok_s", "batch_no_cliff", "preemptions_total",
    "sanitizer_checks", "sanitizer_violations",
    # compile-surface certification (docs/static_analysis.md TPU6xx): XLA
    # compilations observed AFTER the warmup fence by the strict compile
    # sentry — the committed artifact asserts 0, so every number in it is
    # zero-recompile-certified (no mid-run compile stall hid in a tail)
    "post_warmup_compiles", "compile_sentry_mode",
    # ownership certification (docs/static_analysis.md TPU7xx): lost
    # releases found by the strict ownership ledger across every
    # preemption/shed/deadline/cancel path the sweep exercised — the
    # committed artifact asserts 0, so the run is leak-free-certified
    "leaks", "ledger_mode",
    # sharding certification (docs/static_analysis.md TPU8xx): implicit
    # device<->host transfers found by the strict sharding sentry's
    # loop-boundary audits across the whole sweep — the committed artifact
    # asserts 0, so every number in it was produced without a silent host
    # round-trip or layout drift on the serve path
    "implicit_transfers", "unplanned_reshards", "shard_sentry_mode",
}

# the mixed trace: weights sum to 1. Chat + tool loops share system
# prefixes (the radix cache serves them warm, like production chat fleets);
# batch summarization holds slots long enough to need the preemptible lane;
# best-effort scoring models embedding-style one-shot work.
#
# The mix is deliberately BATCH-DOMINATED in arrivals and tokens (the
# ISSUE 6 scenario: an offline batch flood drowning interactive users):
# interactive demand alone must stay well under engine capacity even at 2x
# total overload, so the headline measures what the scheduler controls —
# whether batch pressure leaks into interactive TTFT — rather than
# interactive-on-interactive queueing, which no scheduler can remove. On
# the smoke engine's 4 slots that requires a small interactive arrival
# share (15%): at 35% interactive the class alone ran the slots at ~55%
# utilization and its own M/G/c queueing dominated the measured tail.
TRACES = [
    {"name": "chat_long_prefix", "cls": "interactive", "weight": 0.08,
     "shared": 96, "unique": 8, "max_new": 16},
    {"name": "short_completion", "cls": "interactive", "weight": 0.05,
     "shared": 0, "unique": 12, "max_new": 12},
    {"name": "tool_call_loop", "cls": "interactive", "weight": 0.02,
     "shared": 32, "unique": 12, "max_new": 8},
    {"name": "batch_summarize", "cls": "batch", "weight": 0.65,
     "shared": 0, "unique": 48, "max_new": 96},
    {"name": "embed_score", "cls": "best_effort", "weight": 0.20,
     "shared": 0, "unique": 24, "max_new": 1},
]

CLASSES = ("interactive", "batch", "best_effort")


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def _shared_prefix(trace: dict) -> List[int]:
    # deterministic per trace type: every request of the type shares it
    seed = sum(ord(c) for c in trace["name"])
    return [(seed * 31 + i * 7) % 250 + 1 for i in range(trace["shared"])]


def _make_prompt(trace: dict, rng: random.Random) -> List[int]:
    tail = [rng.randrange(1, 251) for _ in range(trace["unique"])]
    return _shared_prefix(trace) + tail


def _pick_trace(rng: random.Random) -> dict:
    x = rng.random()
    acc = 0.0
    for trace in TRACES:
        acc += trace["weight"]
        if x < acc:
            return trace
    return TRACES[-1]


def build_engine(smoke: bool):
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    cfg = dict(
        max_batch=4,
        max_seq_len=256,
        prefill_buckets=[32, 64, 128, 160],
        eos_token_id=None,          # fixed work per request
        decode_steps=1,             # shortest chunks: an interactive arrival
                                    # waits at most one step for a boundary
        cache_mode="paged",
        page_size=16,
        # batch cold prefills run as gate-paced 16-token segments, so a
        # first-token-critical interactive admission never waits out a
        # monolithic long-prompt prefill occupying the host/device
        chunked_prefill_size=16,
        prefix_cache=128,
        prefix_block=16,
        # pool sized for the workload, not the default slots-only floor of
        # 65 pages: 4 slots at the worst batch length (48 prompt + 96 new =
        # 9 pages) plus a prefix budget that can hold the shared chat
        # prefix AND several preempted batch histories at once. A starved
        # cache here doesn't stress the scheduler — it just turns every
        # preempt->resume into a full re-prefill and measures XLA compile
        # times instead of scheduling
        num_pages=97,               # 96 usable (page 0 is the null page)
        prefix_cache_pages=48,
        max_pending=16,             # admission control + brownout signals on
        preempt_batch=True,
        preempt_budget=2,
        brownout=True,
        brownout_batch_cap=32,
        brownout_dwell=1.0,
        # a single-core host gains no overlap from pipelining (bench.py
        # --pipeline-ab note) but pays its commit/quarantine latency in
        # TTFT; multi-core hosts should drop this override
        pipeline_depth=1 if (os.cpu_count() or 1) == 1 else None,
    )
    return LLMEngineCore(bundle, params, **cfg), cfg


async def _consume(engine, request, rec: dict, records: List[dict]) -> None:
    from clearml_serving_tpu.errors import (
        DeadlineExceededError,
        EngineOverloadedError,
    )

    try:
        n = 0
        async for _ in engine.generate(request):
            n += 1
        rec["status"] = "ok"
        rec["tokens"] = n
        if request.first_token_at is not None:
            rec["ttft_ms"] = (
                request.first_token_at - request.submitted_at
            ) * 1e3
        rec["t_done"] = time.perf_counter()
    except EngineOverloadedError:
        rec["status"] = "shed"
    except DeadlineExceededError:
        rec["status"] = "deadline"
    except asyncio.CancelledError:
        rec["status"] = "cancelled"
        raise
    except Exception as ex:  # noqa: BLE001 - harness must keep counting
        rec["status"] = "error"
        rec["error"] = repr(ex)[:200]
    finally:
        records.append(rec)


def _class_summary(records: List[dict], duration: float) -> Dict[str, dict]:
    out = {}
    for cls in CLASSES:
        rows = [r for r in records if r["cls"] == cls]
        done = [r for r in rows if r["status"] == "ok"]
        ttfts = [r["ttft_ms"] for r in done if r.get("ttft_ms") is not None]
        out[cls] = {
            "requests": len(rows),
            "completed": len(done),
            "shed": sum(1 for r in rows if r["status"] == "shed"),
            "errors": sum(
                1 for r in rows if r["status"] in ("error", "cancelled")
            ),
            "ttft_p50_ms": round(_percentile(ttfts, 0.50) or 0.0, 2),
            "ttft_p99_ms": round(_percentile(ttfts, 0.99) or 0.0, 2),
            "goodput_tok_s": round(
                sum(r.get("tokens", 0) for r in done) / max(1e-6, duration),
                2,
            ),
        }
    return out


async def _open_loop(engine, rate: float, n_arrivals: int, seed: int,
                     drain_timeout: float) -> dict:
    from clearml_serving_tpu.llm.engine import GenRequest

    rng = random.Random(seed)
    records: List[dict] = []
    tasks: List[asyncio.Task] = []
    preempt0 = engine.counters["preemptions"]
    max_stage = 0
    t0 = time.perf_counter()
    for _ in range(n_arrivals):
        trace = _pick_trace(rng)
        request = GenRequest(
            prompt_ids=_make_prompt(trace, rng),
            max_new_tokens=trace["max_new"],
            priority=trace["cls"],
        )
        rec = {"cls": trace["cls"], "trace": trace["name"],
               "t_submit": time.perf_counter()}
        tasks.append(
            asyncio.create_task(_consume(engine, request, rec, records))
        )
        if engine._brownout is not None:
            max_stage = max(max_stage, engine._brownout.stage)
        await asyncio.sleep(rng.expovariate(rate))
    if tasks:
        _, pending = await asyncio.wait(tasks, timeout=drain_timeout)
        for task in pending:
            task.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
    if engine._brownout is not None:
        max_stage = max(max_stage, engine._brownout.stage)
    done_times = [r["t_done"] for r in records if "t_done" in r]
    duration = (max(done_times) if done_times else time.perf_counter()) - t0
    return {
        "offered_rps": round(rate, 2),
        "arrivals": n_arrivals,
        "duration_s": round(duration, 2),
        "classes": _class_summary(records, duration),
        "preemptions": engine.counters["preemptions"] - preempt0,
        "brownout_stage_max": max_stage,
    }


async def _closed_loop_saturation(engine, n_total: int, seed: int) -> float:
    """Max sustainable request rate: closed-loop workers at 2x the slot
    count drive the full mix until n_total requests complete."""
    from clearml_serving_tpu.llm.engine import GenRequest

    completed = 0
    t0 = time.perf_counter()

    async def worker(wid: int) -> None:
        nonlocal completed
        rng = random.Random(seed + wid)
        records: List[dict] = []
        while completed < n_total:
            trace = _pick_trace(rng)
            request = GenRequest(
                prompt_ids=_make_prompt(trace, rng),
                max_new_tokens=trace["max_new"],
                priority=trace["cls"],
            )
            rec = {"cls": trace["cls"]}
            await _consume(engine, request, rec, records)
            if rec["status"] == "ok":
                completed += 1
            elif rec["status"] == "shed":
                await asyncio.sleep(0.02)  # closed loop: brief backoff

    workers = max(2, 2 * engine.max_batch)
    await asyncio.gather(*(worker(i) for i in range(workers)))
    return completed / (time.perf_counter() - t0)


async def _unloaded_ttft(engine, rate: float, n: int,
                         seed: int) -> List[float]:
    """Unloaded interactive TTFT: the SAME open-loop arrival process as the
    sweep, at a trickle rate (~1/10 of saturation) where requests never
    contend for slots or queue — but each arrival still lands against a
    live engine loop and pays the same admission/commit machinery the
    loaded points pay. (A fully sequential idle-engine measure would
    exclude even the chunk-boundary wait, understating the baseline every
    real deployment observes.)"""
    from clearml_serving_tpu.llm.engine import GenRequest

    rng = random.Random(seed)
    chat = TRACES[0]
    ttfts: List[float] = []
    tasks = []
    records: List[dict] = []
    for _ in range(n):
        request = GenRequest(
            prompt_ids=_make_prompt(chat, rng),
            max_new_tokens=chat["max_new"],
        )
        rec: dict = {"cls": "interactive", "req": request}
        tasks.append(
            asyncio.create_task(_consume(engine, request, rec, records))
        )
        await asyncio.sleep(rng.expovariate(rate))
    await asyncio.gather(*tasks, return_exceptions=True)
    for rec in records:
        if rec.get("status") == "ok" and rec.get("ttft_ms") is not None:
            ttfts.append(rec["ttft_ms"])
    return ttfts


async def _run_async(smoke: bool) -> dict:
    engine, cfg = build_engine(smoke)
    mults = (0.5, 1.0, 2.0)
    try:
        # Shape warmup via the SHARED warmup registry (llm/warmup.py —
        # extracted from this harness's original inline block and
        # generalized over the engine config): every prefill bucket, the
        # radix-hit gather + tail chunk per bucket, every resume-commit
        # final-segment length, every cold-commit page count,
        # multi-segment tails, and the power-of-two CoW copy programs —
        # all BEFORE anything is measured. The trace mix rides along as
        # extra_prompts (twice: the second pass runs the warm radix path
        # production chat fleets live on). run_warmup then sets the
        # compile sentry's warmup fence: with TPUSERVE_COMPILE_SENTRY=
        # strict (run() arms it), ANY further XLA compile fails the run —
        # the headline below commits post_warmup_compiles == 0, so every
        # number in the artifact is zero-recompile-certified.
        rng = random.Random(0)
        from clearml_serving_tpu.llm.warmup import run_warmup

        warm = await run_warmup(
            engine,
            full=True,
            extra_prompts=[_make_prompt(t, rng) for t in TRACES],
        )

        saturation = await _closed_loop_saturation(
            engine, 40 if smoke else 120, seed=2
        )
        await engine.wait_drained()

        ttfts = await _unloaded_ttft(
            engine, rate=max(0.5, saturation * 0.1),
            n=48 if smoke else 96, seed=1,
        )
        await engine.wait_drained()

        loads = []
        for k, mult in enumerate(mults):
            rate = max(0.5, saturation * mult)
            # long enough that per-class p99s rest on dozens of samples
            # (interactive is 15% of arrivals), not on the worst single one
            horizon = 10.0 if smoke else 20.0
            n_arrivals = max(40, min(600, int(rate * horizon)))
            row = await _open_loop(
                engine, rate, n_arrivals, seed=10 + k,
                drain_timeout=120.0 if smoke else 300.0,
            )
            row["x_saturation"] = mult
            loads.append(row)
            await engine.wait_drained()
    finally:
        sanitizer = engine._sanitizer
        sanitizer_stats = (
            sanitizer.stats() if sanitizer is not None
            else {"checks": 0, "failures": -1}
        )
        sentry = engine._compile_sentry
        sentry_stats = (
            sentry.stats_brief() if sentry is not None
            else {"mode": "off", "serve": -1, "fenced": False}
        )
        ledger = engine._ledger
        ledger_stats = (
            ledger.stats() if ledger is not None
            else {"strict": False, "leaks": -1, "double_releases": -1}
        )
        shard = engine._shard_sentry
        shard_stats = (
            shard.stats_brief() if shard is not None
            else {"strict": False, "implicit_transfers": -1,
                  "unplanned_reshards": -1}
        )
        loop_exc = None
        task = engine._loop_task
        if task is not None and task.done() and not task.cancelled():
            loop_exc = task.exception()
        engine.stop()
    if loop_exc is not None:
        # a sanitizer violation (or any loop death) must fail the headline
        sanitizer_stats = dict(sanitizer_stats)
        sanitizer_stats["failures"] = max(1, sanitizer_stats.get("failures", 1))

    unloaded_p99 = _percentile(ttfts, 0.99) or 0.0
    at_2x = loads[-1]["classes"]["interactive"]
    ratio = (at_2x["ttft_p99_ms"] / unloaded_p99) if unloaded_p99 else None
    batch_curve = [row["classes"]["batch"]["goodput_tok_s"] for row in loads]
    # "no cliff": past saturation, batch goodput degrades smoothly — the
    # overloaded point keeps a meaningful fraction of the saturated rate
    # instead of collapsing toward zero
    no_cliff = bool(
        batch_curve[1] > 0 and batch_curve[2] >= 0.3 * batch_curve[1]
    )
    preemptions_total = sum(row["preemptions"] for row in loads)
    return {
        "metric": "llm_slo_loadtest" + ("_cpusmoke" if smoke else ""),
        "platform": "cpu",
        "smoke": smoke,
        "engine": {k: v for k, v in cfg.items() if k != "prefill_buckets"},
        "mix": {t["name"]: {"class": t["cls"], "weight": t["weight"],
                            "prompt_shared": t["shared"],
                            "prompt_unique": t["unique"],
                            "max_new_tokens": t["max_new"]}
                for t in TRACES},
        "unloaded_ttft_ms": {
            "p50": round(_percentile(ttfts, 0.50) or 0.0, 2),
            "p99": round(unloaded_p99, 2),
            "samples": len(ttfts),
        },
        "saturation_rps": round(saturation, 2),
        "loads": loads,
        "headline": {
            "interactive_p99_ttft_unloaded_ms": round(unloaded_p99, 2),
            "interactive_p99_ttft_at_2x_ms": at_2x["ttft_p99_ms"],
            "ttft_ratio_at_2x": round(ratio, 2) if ratio else None,
            "ttft_bound": 3.0,
            "ttft_within_bound": bool(ratio is not None and ratio <= 3.0),
            "batch_goodput_curve_tok_s": batch_curve,
            "batch_no_cliff": no_cliff,
            "preemptions_total": preemptions_total,
            "sanitizer_checks": sanitizer_stats.get("checks", 0),
            "sanitizer_violations": sanitizer_stats.get("failures", 0),
            # zero-recompile certification: XLA compiles the strict sentry
            # counted AFTER llm/warmup.py's fence (tier-1 asserts 0)
            "post_warmup_compiles": sentry_stats.get("serve", -1),
            "compile_sentry_mode": sentry_stats.get("mode", "off"),
            # leak-free certification (docs/static_analysis.md TPU7xx):
            # lost releases + double frees found by the strict ownership
            # ledger across the whole sweep (tier-1 asserts 0) — and the
            # run itself FAILS on one in strict mode, so completing at
            # all is the certificate
            "leaks": (
                ledger_stats.get("leaks", -1)
                + ledger_stats.get("double_releases", 0)
                if ledger_stats.get("leaks", -1) >= 0 else -1
            ),
            "ledger_mode": (
                "strict" if ledger_stats.get("strict")
                else ("count" if ledger is not None else "off")
            ),
            # sharding certification (docs/static_analysis.md TPU8xx):
            # silent host materializations / layout drift found by the
            # strict sharding sentry's loop-boundary audits (tier-1
            # asserts 0) — strict mode fails the run on one, so
            # completing at all is the certificate
            "implicit_transfers": shard_stats.get("implicit_transfers", -1),
            "unplanned_reshards": shard_stats.get("unplanned_reshards", -1),
            "shard_sentry_mode": (
                "strict" if shard_stats.get("strict")
                else ("count" if shard is not None else "off")
            ),
        },
        "warmup": warm,
    }


def run(smoke: bool = True, write_artifact: bool = True) -> dict:
    """Entry point shared by ``bench.py --loadtest`` and the TPU battery's
    phase 6. Forces the CPU backend and arms the KV sanitizer AND the
    strict compile sentry BEFORE the engine exists, runs the sweep,
    optionally updates the committed artifact, and returns the result
    row. Strict sentry means the run itself FAILS on any post-warmup XLA
    compile — completing at all is the zero-recompile certificate the
    headline commits."""
    os.environ["TPUSERVE_SANITIZE"] = "1"
    # forced like the sanitizer, not defaulted: a pre-exported "1" in the
    # environment would silently downgrade the certification run to
    # count-only mode while the docstring still claims strict
    os.environ["TPUSERVE_COMPILE_SENTRY"] = "strict"
    # leak-free certification (docs/static_analysis.md TPU7xx): the strict
    # ownership ledger fails the run on ANY lost release across the
    # sweep's preemption/shed/deadline paths — the committed headline's
    # `leaks: 0` is proven, not sampled
    os.environ["TPUSERVE_LEDGER"] = "strict"
    # sharding certification (docs/static_analysis.md TPU8xx): the strict
    # sharding sentry fails the run on ANY implicit device<->host transfer
    # or unplanned reshard its loop-boundary audits find — the committed
    # headline's `implicit_transfers: 0` is proven, not sampled
    os.environ["TPUSERVE_SHARD_SENTRY"] = "strict"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from clearml_serving_tpu.llm import (
        compile_sentry,
        lifecycle_ledger,
        sharding_sentry,
    )

    if compile_sentry.enabled():
        # a fresh fence for THIS run (the sentry is process-wide and the
        # battery may have exercised it already in-process)
        compile_sentry.get().reset(strict=compile_sentry.strict_enabled())
    if lifecycle_ledger.enabled():
        # fresh books for THIS run, same reason
        lifecycle_ledger.arm().reset(
            strict=lifecycle_ledger.strict_enabled()
        )
    if sharding_sentry.enabled():
        # a fresh spec table for THIS run, same reason
        sharding_sentry.arm().reset(
            strict=sharding_sentry.strict_enabled()
        )
    row = asyncio.run(_run_async(smoke))
    if write_artifact:
        ARTIFACT.write_text(json.dumps(row, indent=2) + "\n")
    return row


def main() -> None:
    import sys

    smoke = "--smoke" in sys.argv
    row = run(smoke=smoke)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
