"""Engine-level speculative-decoding A/B (VERDICT r3 #5: "a TPU A/B tok/s
line").

Builds the SERVING engine (LLMEngineCore — slot batching, admission,
emission; not bench.py's raw fused scan) twice — speculation off / ngram —
and drives identical concurrent greedy workloads through generate().
Repetitive prompts keep the n-gram proposer in its favorable regime
(summaries/extraction/code-shaped traffic); outputs are greedy-exact either
way, so the delta is pure speculation win (or loss, on draft-miss traffic —
the miss workload is reported too).

Run standalone (CPU smoke or TPU via inherited JAX_PLATFORMS=axon):
    python benchmarks/spec_ab.py [--preset llama-tiny] [--batch 4]
Emits one JSON line per (workload, mode) to stdout; tpu_battery.py phase 3
relays them into benchmarks/TPU_RESULTS.jsonl.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _prompts(kind: str, batch: int, prompt_len: int, vocab: int):
    rng = np.random.RandomState(7)
    out = []
    for b in range(batch):
        if kind == "repeat":
            # period-8 loop: the spec_ngram=2 matcher locks on quickly
            period = list(rng.randint(2, min(vocab, 30000), size=8))
            ids = (period * (prompt_len // 8 + 1))[:prompt_len]
        else:  # "miss": i.i.d. tokens, drafts rarely hit
            ids = list(rng.randint(2, min(vocab, 30000), size=prompt_len))
        out.append([1] + [int(t) for t in ids])
    return out


def run_ab(
    preset: str = "llama-tiny",
    batch: int = 4,
    prompt_len: int = 96,
    new_tokens: int = 160,
    decode_steps: int = 8,
    spec_k: int = 4,
    quantize=None,
    dtype: str = "float32",
    scan_layers: bool = False,
    kv_quant=None,
):
    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    cfg = {"preset": preset, "dtype": dtype}
    if scan_layers:
        cfg["scan_layers"] = True
    if kv_quant:
        cfg["kv_quant"] = kv_quant
    bundle = models.build_model("llama", cfg)
    import jax

    params = bundle.init(jax.random.PRNGKey(0))
    vocab = int(bundle.config["vocab_size"])
    max_seq = prompt_len + new_tokens + 8
    bucket = 1
    while bucket < prompt_len + 1:
        bucket *= 2
    results = []
    for mode in (None, "ngram"):
        engine = LLMEngineCore(
            bundle, params,
            max_batch=batch, max_seq_len=max_seq,
            prefill_buckets=[bucket],
            eos_token_id=None,  # run the full budget: equal-token A/B
            decode_steps=decode_steps,
            speculation=mode, spec_k=spec_k,
            quantize=quantize,
            prefill_segments_per_decode=None,
        )
        # greedy workloads exercise the exact argmax chain; "sampled"
        # (temperature 0.8 on repetitive prompts) exercises the rejection
        # chain (spec_sampling) — the A/B shows its win on real traffic
        for kind, temperature in (
            ("repeat", 0.0), ("miss", 0.0), ("sampled", 0.8)
        ):
            prompts = _prompts(
                "repeat" if kind == "sampled" else kind,
                batch, prompt_len, vocab,
            )

            async def drive():
                async def one(p):
                    n = 0
                    req = GenRequest(prompt_ids=p, max_new_tokens=new_tokens,
                                     temperature=temperature)
                    async for _ in engine.generate(req):
                        n += 1
                    return n

                # warmup: compile prefill + decode paths
                await one(prompts[0])
                t0 = time.time()
                counts = await asyncio.gather(*[one(p) for p in prompts])
                dt = time.time() - t0
                return sum(counts), dt

            total, dt = asyncio.run(drive())
            results.append({
                "metric": "llm_engine_spec_ab_{}_{}".format(
                    kind, mode or "off"
                ),
                "value": round(total / dt, 2),
                "unit": "tok/s/chip",
                "workload": kind,
                "speculation": mode or "off",
                "batch": batch,
                "preset": preset,
                "tokens": total,
                "wall_s": round(dt, 2),
            })
            print(json.dumps(results[-1]), flush=True)
        engine.stop()
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama-tiny")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=96)
    ap.add_argument("--new-tokens", type=int, default=160)
    ap.add_argument("--decode-steps", type=int, default=8)
    ap.add_argument("--quantize", default=None)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--scan-layers", action="store_true")
    ap.add_argument("--kv-quant", default=None)
    a = ap.parse_args()
    run_ab(
        preset=a.preset, batch=a.batch, prompt_len=a.prompt_len,
        new_tokens=a.new_tokens, decode_steps=a.decode_steps,
        quantize=a.quantize, dtype=a.dtype, scan_layers=a.scan_layers,
        kv_quant=a.kv_quant,
    )


if __name__ == "__main__":
    main()
