"""Single-process TPU measurement battery (run by benchmarks/tpu_watch.sh).

The tunnel grants flaky, possibly short-lived sessions, so once a connection
is healthy everything must run in ONE process: device probe (with a SIGALRM
watchdog — jax.devices() HANGS rather than errors while the tunnel is down),
then the full battery:

  1. headline 8B-int8 decode throughput + TTFT (same measurement bench.py's
     TPU worker runs, via bench._measure) at a sweep of batch sizes
  2. paged-attention kernel vs XLA gather vs dense (benchmarks/paged_bench.py)

Results append to benchmarks/TPU_RESULTS.jsonl (committed as evidence) and
echo to stdout.  Exit codes: 0 = battery complete, 3 = tunnel down (watchdog
fired), 4 = backend present but not a TPU.

Run via the inherited environment: JAX_PLATFORMS=axon must be present (the
tunnel registers as the experimental "axon" PJRT platform; jax will not
auto-select it — see bench.py's module docstring).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import signal
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT_PATH = REPO / "benchmarks" / "TPU_RESULTS.jsonl"
PROBE_TIMEOUT = int(os.environ.get("BATTERY_PROBE_TIMEOUT", 150))


def emit(obj: dict) -> None:
    obj = dict(obj)
    obj["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    line = json.dumps(obj)
    print(line, flush=True)
    with OUT_PATH.open("a") as f:
        f.write(line + "\n")


def main() -> int:
    # Watchdog around first device touch.  A *Python* SIGALRM handler never
    # fires here: the axon PJRT client init hangs inside C while HOLDING the
    # GIL, so no bytecode ever runs again.  SIG_DFL makes the kernel kill the
    # process directly (exit 142 = 128+SIGALRM), which the watcher loop
    # treats as "tunnel down, retry".
    signal.signal(signal.SIGALRM, signal.SIG_DFL)
    signal.alarm(PROBE_TIMEOUT)
    print("probing device (watchdog {}s)...".format(PROBE_TIMEOUT), flush=True)
    import jax  # noqa: E402

    import bench  # repo-root bench.py (for _measure + TARGET_TOK_S)
    from clearml_serving_tpu.utils.tpu import is_tpu_device

    dev = jax.devices()[0]
    signal.alarm(0)
    if not is_tpu_device(dev):
        print("backend is {}/{} — not a TPU".format(dev.platform, dev.device_kind))
        return 4
    backend = "{}:{}".format(dev.platform, dev.device_kind)
    emit({"event": "tunnel_healthy", "backend": backend})
    successes = 0

    # -- phase 1: headline 8B int8 decode throughput + TTFT, batch sweep ----
    # b16 with a bf16 KV cache; b32+ need the int8 KV cache to fit next
    # to the int8 weights on a 16 GB chip (measured 2026-07-29: b8=477,
    # b16=738, b32-kvint8=859 tok/s — throughput still climbing with batch,
    # so the sweep now explores upward + a longer fused chunk)
    base_cfg = {"preset": "llama3-8b", "dtype": "bfloat16", "scan_layers": True}
    for batch, kv, chunk, wq in (
        (16, None, 25, "int8"),
        (32, "int8", 25, "int8"),
        (48, "int8", 25, "int8"),
        (64, "int8", 25, "int8"),
        (64, "int8", 50, "int8"),
        (32, "int8", 25, "int4"),   # w4a16: weight reads halve vs int8
        (64, "int8", 25, "int4"),
    ):
        cfg = dict(base_cfg, **({"kv_quant": kv} if kv else {}))
        t0 = time.time()
        try:
            tok_s, ttft_ms = bench._measure(
                cfg, batch=batch, seq_len=1024, chunk=chunk,
                rounds=4, quantize=wq,
            )
            successes += 1
            emit({
                "metric": "llm_decode_throughput_llama3-8b-{}_b{}{}{}".format(
                    wq, batch, "-kvint8" if kv else "",
                    "-c{}".format(chunk) if chunk != 25 else "",
                ),
                "value": round(tok_s, 2),
                "unit": "tok/s/chip",
                "vs_baseline": round(tok_s / bench.TARGET_TOK_S, 4),
                "platform": "tpu",
                "backend": backend,
                "ttft_p512_b1_ms": round(ttft_ms, 2),
                "wall_s": round(time.time() - t0, 1),
            })
        except Exception as ex:
            emit({"metric": "llm_decode_throughput_llama3-8b-int8_b{}".format(batch),
                  "error": repr(ex)[:300], "wall_s": round(time.time() - t0, 1)})

    # -- phase 2: paged-attention kernel vs gather vs dense -----------------
    from benchmarks import paged_bench

    buf = io.StringIO()
    t0 = time.time()
    try:
        with contextlib.redirect_stdout(buf):
            paged_bench.main()
        successes += 1
    except Exception as ex:
        emit({"metric": "paged_bench", "error": repr(ex)[:300]})
    for line in buf.getvalue().splitlines():
        try:
            emit(json.loads(line))
        except Exception:
            print(line, flush=True)
    paged_wall_s = round(time.time() - t0, 1)

    # -- phase 3: SERVING-ENGINE speculative A/B (VERDICT r3 #5) ------------
    # LLMEngineCore end to end (admission/emission included), 8B int8,
    # speculation off vs ngram on draft-friendly and draft-hostile traffic.
    from benchmarks import spec_ab

    t1 = time.time()
    try:
        for row in spec_ab.run_ab(
            preset="llama3-8b", batch=16, prompt_len=256, new_tokens=256,
            decode_steps=25, quantize="int8", dtype="bfloat16",
            scan_layers=True, kv_quant="int8",
        ):
            emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_engine_spec_ab", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t1, 1)})

    # -- phase 4: pipelined-decode A/B (docs/pipelined_decode.md) -----------
    # the real engine at TPUSERVE_PIPELINE_DEPTH=1 (serial) vs 2 (double-
    # buffered chunk dispatch + device-resident token chaining); on a TPU
    # the depth-2 win is the retired chunk's ~90 ms host dispatch/readback
    # hidden behind the next chunk's device compute
    t2 = time.time()
    try:
        row = bench.run_pipeline_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "scan_layers": True,
             "kv_quant": "int8"},
            batch=16, decode_steps=25, new_tokens=200, prompt_len=128,
            max_seq_len=1024, quantize="int8",
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        row["wall_s"] = round(time.time() - t2, 1)
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_pipelined_decode_ab", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t2, 1)})

    # -- phase 5: int8 paged KV A/B (docs/paged_kv_quant.md) ----------------
    # bf16 vs int8 page pools on the real engine, 8B int8 weights: the
    # int8 pools halve the dominant per-step KV DMA term (ROOFLINE gap #3)
    # and the pool HBM footprint (gap #2 via capacity) — the step-time and
    # pool-bytes deltas here are the tentpole's measured evidence
    t3 = time.time()
    try:
        row = bench.run_paged_quant_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "scan_layers": True},
            batch=16, decode_steps=25, new_tokens=200, prompt_len=128,
            max_seq_len=1024, quantize="int8",
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        row["wall_s"] = round(time.time() - t3, 1)
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_paged_kv_quant_ab", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t3, 1)})

    # -- phase 6: SLO loadtest CPU smoke (docs/slo_scheduling.md) -----------
    # fast sanity of the scheduling stack — priority classes, preemptible
    # batch lane, brownout — in a SUBPROCESS with the CPU backend forced
    # (this process is bound to the axon/TPU platform; the loadtest drives
    # the real engine end to end and must not contend for the chip). The
    # child updates benchmarks/LOADTEST_cpu.json.
    import subprocess

    t4 = time.time()
    try:
        env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
        out = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--loadtest", "--smoke"],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=str(REPO),
        )
        lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
        if out.returncode == 0 and lines:
            row = json.loads(lines[-1])
            row["wall_s"] = round(time.time() - t4, 1)
            emit(row)
            successes += 1
        else:
            emit({"metric": "llm_slo_loadtest_cpusmoke",
                  "error": "rc={}: {}".format(
                      out.returncode, (out.stderr or "").strip()[-300:]),
                  "wall_s": round(time.time() - t4, 1)})
    except Exception as ex:
        emit({"metric": "llm_slo_loadtest_cpusmoke", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t4, 1)})

    # -- phase 7: w4a16 fused dequant-matmul A/B (docs/w4a16.md) ------------
    # int4-fused vs int4-XLA-dequant vs int8 on the real engine, 8B decode
    # shapes (random quantized trees — full precision never materializes on
    # the chip): the fused kernel's step-time delta over the XLA route and
    # the quartered weight-read bytes are the tentpole's measured evidence
    t5 = time.time()
    try:
        row = bench.run_int4_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "kv_quant": "int8"},
            batch=16, decode_steps=25, new_tokens=200, prompt_len=128,
            max_seq_len=1024, from_bf16=False,
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        row["wall_s"] = round(time.time() - t5, 1)
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_int4_weight_ab", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t5, 1)})

    # -- phase 8: ragged scheduler A/B (docs/ragged_attention.md) -----------
    # mixed prefill+decode single-launch scheduler vs the two-dispatch path
    # on 8B decode shapes: decode stall during a long admission, occupancy,
    # stream byte-identity. The ragged Pallas kernel engages on TPU (D=128,
    # page 16/32); the CPU smoke artifact is covered by battery consumers
    # running bench.py --ragged-ab off-chip.
    t6 = time.time()
    try:
        row = bench.run_ragged_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "kv_quant": "int8"},
            batch=16, decode_steps=4, new_tokens=96,
            decode_prompt_len=64, admit_prompt_len=768,
            step_token_budget=256, max_seq_len=1024, cache_mode="paged",
            # int8 paged tile is (32, 128): 16-token pages would route the
            # ragged kernel to the XLA gather (docs/paged_kv_quant.md)
            page_size=32,
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        row["wall_s"] = round(time.time() - t6, 1)
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_ragged_scheduler_ab", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t6, 1)})

    # -- phase 8b: multi-step decode rows + spec-as-row (ISSUE 13) ----------
    # the per-launch decode bubble is the thing multi-step windows exist to
    # amortize (~90 ms tunnel dispatch per PR-4): measure dispatches per
    # decode token at q=1 vs q=4 on 8B decode shapes, and spec-as-row vs
    # the legacy serial scan (on chip the ragged Pallas kernel skips
    # unowned q-blocks, so the tok/s comparison is meaningful here in a
    # way the CPU smoke's XLA-reference arm is not). These rows decide the
    # default ragged_decode_steps (ROADMAP phase-8 follow-up).
    try:
        row = bench.run_ragged_decode_steps_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "kv_quant": "int8"},
            q=4, new_tokens=128, decode_prompt_len=64, admit_prompt_len=128,
            step_token_budget=256, max_seq_len=1024, cache_mode="paged",
            page_size=32,
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_ragged_decode_steps_ab",
              "error": repr(ex)[:300]})
    try:
        row = bench.run_spec_row_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "kv_quant": "int8"},
            spec_k=3, batch=8, new_tokens=96, step_token_budget=64,
            max_seq_len=1024, cache_mode="paged", page_size=32,
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_spec_row_ab", "error": repr(ex)[:300]})

    # -- phase 9: host-RAM KV tiering A/B (docs/kv_tiering.md) --------------
    # constrained-HBM shared-prefix trace on 8B int8-KV shapes: warm TTFT
    # by serving tier {hbm, host, cold}, promotion DMA overlap ratio, and
    # tok/s of a concurrent decode stream — on a real chip the promotion
    # hides behind the tail prefill's compute, which the 1-core CPU smoke
    # can only approximate
    t7 = time.time()
    try:
        row = bench.run_kv_tier_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "scan_layers": True,
             "kv_quant": "int8"},
            n_prefixes=3, prefix_len=768, tail_len=32,
            # int8 paged tile is (32, 128): 32-token pages keep the Pallas
            # kernel engaged (docs/paged_kv_quant.md)
            page_size=32, prefix_block=32,
            device_cache_pages=24, host_pages=96,
            max_seq_len=1024, num_pages=160,
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        row["wall_s"] = round(time.time() - t7, 1)
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_kv_tier_ab", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t7, 1)})

    # -- phase 10: draft-tree vs draft-chain verify rows (ISSUE 20) ---------
    # the acceptance-gap close on 8B decode shapes: the n-gram forest
    # proposer's branched verify rows vs the single chain at the SAME k+1
    # verify budget — accepted decode tokens per ragged launch is the
    # headline, and on chip each accepted token amortizes the ~90 ms
    # tunnel dispatch the verify row already paid for
    t8 = time.time()
    try:
        row = bench.run_spec_tree_ab(
            {"preset": "llama3-8b", "dtype": "bfloat16", "kv_quant": "int8"},
            spec_k=4, spec_branch=2, batch=8, new_tokens=96,
            step_token_budget=64, max_seq_len=1024, cache_mode="paged",
            page_size=32,
        )
        row["platform"] = "tpu"
        row["backend"] = backend
        row["wall_s"] = round(time.time() - t8, 1)
        emit(row)
        successes += 1
    except Exception as ex:
        emit({"metric": "llm_spec_tree_ab", "error": repr(ex)[:300],
              "wall_s": round(time.time() - t8, 1)})

    emit({
        "event": "battery_done",
        "paged_wall_s": paged_wall_s,
        "spec_ab_wall_s": round(time.time() - t1, 1),
        "pipeline_ab_wall_s": round(time.time() - t2, 1),
        "paged_quant_ab_wall_s": round(time.time() - t3, 1),
        "loadtest_wall_s": round(time.time() - t4, 1),
        "int4_ab_wall_s": round(time.time() - t5, 1),
        "ragged_ab_wall_s": round(time.time() - t6, 1),
        "kv_tier_ab_wall_s": round(time.time() - t7, 1),
        "spec_tree_ab_wall_s": round(time.time() - t8, 1),
        "successes": successes,
    })
    # A probe that succeeded but zero completed measurements means the
    # session died mid-battery: report "tunnel down" so the watcher retries
    # instead of writing DONE with nothing but error records captured.
    return 0 if successes else 3


if __name__ == "__main__":
    raise SystemExit(main())
