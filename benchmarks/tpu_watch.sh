#!/bin/bash
# Standing TPU-tunnel watcher (VERDICT r2 next-step #1: treat the 8B TPU
# bench as a trigger, not a task).  Loops the single-process battery
# (benchmarks/tpu_battery.py): the battery itself probes with a SIGALRM
# watchdog and exits 3 while the tunnel is down, so the loop just re-runs
# it every few minutes until it completes.  Run detached:
#   nohup bash benchmarks/tpu_watch.sh >/tmp/tpu_watch.log 2>&1 &
# IMPORTANT: the inherited env must keep JAX_PLATFORMS=axon (the tunnel's
# experimental PJRT platform name) — do not strip or override it.
set -u
OUT=/tmp/tpu_watch
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

i=0
while true; do
    i=$((i + 1))
    echo "$(date -u +%H:%M:%S) battery attempt #$i"
    timeout "${BATTERY_TIMEOUT:-2400}" python benchmarks/tpu_battery.py
    rc=$?
    echo "$(date -u +%H:%M:%S) battery rc=$rc"
    if [ "$rc" -eq 0 ]; then
        date -u +%FT%TZ >"$OUT/DONE"
        # battery banked: also capture request-level percentiles on the TPU
        # (BASELINE.md metric is req/s + p50/p99 TTFT per endpoint; the CPU
        # artifact exists, this is the TPU counterpart). Best-effort.
        echo "$(date -u +%H:%M:%S) loadtest (tpu) starting"
        timeout "${LOADTEST_TIMEOUT:-1200}" python benchmarks/loadtest_report.py \
            --platform default && echo "loadtest done" || echo "loadtest failed"
        exit 0
    fi
    if [ "$rc" -eq 4 ]; then
        # backend present but not a TPU: a persistent env misconfiguration
        # (JAX_PLATFORMS stripped/overridden) that retrying cannot fix
        echo "FATAL: backend is not a TPU — check JAX_PLATFORMS=axon" \
            | tee "$OUT/MISCONFIG"
        exit 4
    fi
    sleep "${BATTERY_RETRY_SLEEP:-180}"
done
