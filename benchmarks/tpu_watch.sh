#!/bin/bash
# Standing TPU-tunnel watcher (VERDICT r2 next-step #1: treat the 8B TPU
# bench as a trigger, not a task).  Probes the tunnel; on the first healthy
# probe runs the full measurement battery and writes results to
# /tmp/tpu_watch/.  Run under tmux: `tmux new-session -d -s tpuwatch
# 'bash benchmarks/tpu_watch.sh'`.
set -u
OUT=/tmp/tpu_watch
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

probe() {
    timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null \
        | grep -q tpu
}

i=0
while true; do
    i=$((i + 1))
    echo "$(date -u +%H:%M:%S) probe #$i"
    if probe; then
        echo "$(date -u +%H:%M:%S) TPU HEALTHY — running battery"
        # 1. headline 8B int8 bench (generous budget: cold compile + tunnel)
        BENCH_TPU_TIMEOUT=1500 BENCH_PROBE_TIMEOUT=120 \
            python bench.py >"$OUT/bench_8b.json" 2>"$OUT/bench_8b.err"
        echo "$(date -u +%H:%M:%S) bench done rc=$?"
        # 2. paged-attention kernel vs gather vs dense (subprocess-free; the
        #    probe above proved the backend answers)
        timeout 900 python benchmarks/paged_bench.py \
            >"$OUT/paged.json" 2>"$OUT/paged.err"
        echo "$(date -u +%H:%M:%S) paged done rc=$?"
        date -u +%FT%TZ >"$OUT/DONE"
        exit 0
    fi
    sleep 240
done
