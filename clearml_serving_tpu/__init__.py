"""tpu-serving: a TPU-native model-serving framework.

Capability-parity rebuild of clearml-serving (reference: /root/reference) with a
JAX/XLA/Pallas engine tier. See SURVEY.md for the reference layer map this
package reproduces, re-designed TPU-first.
"""

from .version import __version__

__all__ = ["__version__"]
