"""Operator CLI.

Command-tree parity with the reference CLI (clearml_serving/__main__.py:332-630):
``create``, ``list``, ``config``, ``model {add, remove, upload, canary,
auto-update, list}``, ``metrics {add, remove, list}``.

Same offline mutation pattern as the reference (:141-143): the CLI never talks
to a live serving container — it opens the control-plane service document,
``deserialize(skip_sync=True)`` → mutate in-memory maps → ``serialize()``;
running routers pick the change up on their next poll.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from .serving.endpoints import (
    CanaryEP,
    EndpointMetricLogging,
    MetricType,
    ModelEndpoint,
    ModelMonitoring,
)
from .serving.model_request_processor import ModelRequestProcessor
from .version import __version__

VERBOSE = False


def _open_processor(args, force_create=False, name=None) -> ModelRequestProcessor:
    processor = ModelRequestProcessor(
        service_id=getattr(args, "id", None),
        force_create=force_create,
        name=name,
    )
    if not force_create:
        _verify_session_version(processor, assume_yes=getattr(args, "yes", False))
        processor.deserialize(skip_sync=True)
    return processor


def _verify_session_version(processor: ModelRequestProcessor, assume_yes: bool) -> None:
    """Warn when CLI major.minor differs from the service's stored version
    (reference __main__.py:24-40)."""
    stored = processor.get_version()
    cur = ".".join(__version__.split(".")[:2])
    got = ".".join(str(stored).split(".")[:2])
    if cur != got:
        if assume_yes:
            return
        answer = input(
            "Warning: serving service version {} does not match CLI version {} — "
            "continue? [y/N] ".format(stored, __version__)
        )
        if answer.strip().lower() not in ("y", "yes"):
            sys.exit(1)


def _parse_aux_config(args) -> Optional[dict]:
    """--aux-config as a file (json) or key=value pairs (reference :295-304)."""
    aux = getattr(args, "aux_config", None)
    if not aux:
        return None
    if len(aux) == 1 and aux[0].endswith((".json", ".cfg", ".conf")):
        with open(aux[0]) as f:
            return json.load(f)
    out = {}
    for kv in aux:
        if "=" not in kv:
            raise SystemExit("--aux-config entries must be key=value or a .json file")
        key, value = kv.split("=", 1)
        try:
            value = json.loads(value)
        except json.JSONDecodeError:
            pass
        # dotted keys nest: batching.buckets=[1,2] -> {"batching": {"buckets": ...}}
        node = out
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return out


def _io_spec_kwargs(args) -> dict:
    return dict(
        input_size=getattr(args, "input_size", None),
        input_type=getattr(args, "input_type", None),
        input_name=getattr(args, "input_name", None),
        output_size=getattr(args, "output_size", None),
        output_type=getattr(args, "output_type", None),
        output_name=getattr(args, "output_name", None),
    )


# ---------------------------------------------------------------- commands


def func_create_service(args):
    processor = ModelRequestProcessor(
        force_create=True,
        name=args.name or "tpu-serving",
        project=args.project,
        tags=args.tags,
    )
    processor.serialize()
    print("New serving service created: id={}".format(processor.get_id()))


def func_list_services(args):
    services = ModelRequestProcessor.list_control_plane_services()
    print(json.dumps(services, indent=2, default=str))


def func_config_service(args):
    processor = _open_processor(args)
    processor.configure(
        external_serving_base_url=args.base_serve_url,
        external_engine_grpc_address=args.engine_grpc_server,
        external_stats_broker=args.stats_broker,
        default_metric_log_freq=args.metric_log_freq,
    )
    print("Serving service {} configured".format(processor.get_id()))


def func_model_upload(args):
    processor = _open_processor(args)
    if not args.path and not args.url:
        raise SystemExit("model upload requires --path or --url")
    record = processor.registry.register(
        name=args.name,
        project=args.project,
        tags=args.tags,
        framework=args.framework,
        path=args.path,
        uri=args.url,
        publish=bool(args.publish),
    )
    print("Model uploaded: id={} name={}".format(record.id, record.name))


def func_model_list(args):
    processor = _open_processor(args)
    out = {
        "endpoints": {k: v.as_dict(remove_null_entries=True) for k, v in processor.list_endpoints().items()},
        "model_monitoring": {
            k: v.as_dict(remove_null_entries=True) for k, v in processor.list_model_monitoring().items()
        },
        "canary": {k: v.as_dict(remove_null_entries=True) for k, v in processor.list_canary_endpoints().items()},
    }
    print(json.dumps(out, indent=2, default=str))


def func_model_remove(args):
    processor = _open_processor(args)
    if processor.remove_endpoint(args.endpoint):
        kind = "endpoint"
    elif processor.remove_model_monitoring(args.endpoint):
        kind = "model monitoring"
    elif processor.remove_canary_endpoint(args.endpoint):
        kind = "canary"
    else:
        raise SystemExit("endpoint {!r} not found".format(args.endpoint))
    processor.serialize()
    print("Removed {} {!r}".format(kind, args.endpoint))


def func_model_endpoint_add(args):
    processor = _open_processor(args)
    endpoint = ModelEndpoint(
        engine_type=args.engine,
        serving_url=args.endpoint,
        model_id=args.model_id,
        version=args.version,
        auxiliary_cfg=_parse_aux_config(args),
        **_io_spec_kwargs(args),
    )
    if not args.model_id and (args.name or args.project or args.tags):
        records = processor.registry.query(
            project=args.project, name=args.name, tags=args.tags,
            only_published=args.published, max_results=1,
        )
        if not records:
            raise SystemExit("no model found matching the query")
        endpoint.model_id = records[0].id
        print("Selected model id={}".format(endpoint.model_id))
    url = processor.add_endpoint(endpoint, preprocess_code=args.preprocess)
    processor.serialize()
    print("Endpoint {!r} added".format(url))


def func_model_auto_update_add(args):
    processor = _open_processor(args)
    monitoring = ModelMonitoring(
        base_serving_url=args.endpoint,
        engine_type=args.engine,
        monitor_project=args.project,
        monitor_name=args.name,
        monitor_tags=args.tags,
        only_published=args.published,
        max_versions=args.max_versions,
        auxiliary_cfg=_parse_aux_config(args),
        **_io_spec_kwargs(args),
    )
    name = processor.add_model_monitoring(monitoring, preprocess_code=args.preprocess)
    processor.serialize()
    print("Model auto-update {!r} added".format(name))


def func_canary_add(args):
    processor = _open_processor(args)
    canary = CanaryEP(
        endpoint=args.endpoint,
        weights=args.weights,
        load_endpoints=args.input_endpoints or [],
        load_endpoint_prefix=args.input_endpoint_prefix,
    )
    processor.add_canary_endpoint(canary)
    processor.serialize()
    print("Canary endpoint {!r} added".format(args.endpoint))


def func_metrics_add(args):
    processor = _open_processor(args)
    metrics = {}
    for spec in args.variable_scalar or []:
        name, buckets = spec.split("=", 1)
        if "/" in buckets:
            lo, hi, step = (float(v) for v in buckets.split("/"))
            bucket_list = []
            v = lo
            while v <= hi + 1e-9:
                bucket_list.append(round(v, 9))
                v += step
        else:
            bucket_list = [float(v) for v in buckets.split(",") if v != ""]
        metrics[name] = MetricType(type="scalar", buckets=bucket_list)
    for spec in args.variable_enum or []:
        name, values = spec.split("=", 1)
        metrics[name] = MetricType(type="enum", buckets=values.split(","))
    for name in args.variable_value or []:
        metrics[name] = MetricType(type="value")
    for name in args.variable_counter or []:
        metrics[name] = MetricType(type="counter")
    processor.add_metric_logging(
        EndpointMetricLogging(
            endpoint=args.endpoint, log_frequency=args.log_freq, metrics=metrics
        )
    )
    processor.serialize()
    print("Metrics logging added for {!r}".format(args.endpoint))


def func_metrics_remove(args):
    processor = _open_processor(args)
    if args.variable:
        for var in args.variable:
            processor.remove_metric_logging(args.endpoint, var)
    else:
        processor.remove_metric_logging(args.endpoint)
    processor.serialize()
    print("Metrics removed for {!r}".format(args.endpoint))


def func_metrics_list(args):
    processor = _open_processor(args)
    out = {k: v.as_dict() for k, v in processor.list_endpoint_logging().items()}
    print(json.dumps(out, indent=2, default=str))


# ---------------------------------------------------------------- parser


def cli(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpu-serving",
        description="TPU-native model-serving CLI (clearml-serving capability parity)",
    )
    parser.add_argument("--debug", action="store_true")
    parser.add_argument("--yes", action="store_true", help="assume yes on prompts")
    parser.add_argument("--id", type=str, default=None, help="serving service id")
    sub = parser.add_subparsers(dest="command")

    p = sub.add_parser("create", help="create a new serving service")
    p.add_argument("--name", type=str, default="tpu-serving")
    p.add_argument("--project", type=str, default="DevOps")
    p.add_argument("--tags", nargs="+", default=None)
    p.set_defaults(func=func_create_service)

    p = sub.add_parser("list", help="list serving services")
    p.set_defaults(func=func_list_services)

    p = sub.add_parser("config", help="configure the serving service")
    p.add_argument("--base-serve-url", type=str, default=None)
    p.add_argument("--engine-grpc-server", type=str, default=None)
    p.add_argument("--stats-broker", type=str, default=None)
    p.add_argument("--metric-log-freq", type=float, default=None)
    p.set_defaults(func=func_config_service)

    model = sub.add_parser("model", help="model endpoint management")
    model_sub = model.add_subparsers(dest="model_command")

    p = model_sub.add_parser("list", help="list model endpoints")
    p.set_defaults(func=func_model_list)

    p = model_sub.add_parser("remove", help="remove an endpoint/monitoring/canary")
    p.add_argument("--endpoint", type=str, required=True)
    p.set_defaults(func=func_model_remove)

    p = model_sub.add_parser("upload", help="upload/register a model")
    p.add_argument("--name", type=str, required=True)
    p.add_argument("--project", type=str, default=None)
    p.add_argument("--tags", nargs="+", default=None)
    p.add_argument("--framework", type=str, default=None)
    p.add_argument("--path", type=str, default=None)
    p.add_argument("--url", type=str, default=None)
    p.add_argument("--publish", action="store_true")
    p.set_defaults(func=func_model_upload)

    def _add_io_spec(p):
        p.add_argument("--input-size", nargs="+", type=json.loads, default=None,
                       help="input shapes, e.g. --input-size [1,4]")
        p.add_argument("--input-type", nargs="+", type=str, default=None)
        p.add_argument("--input-name", nargs="+", type=str, default=None)
        p.add_argument("--output-size", nargs="+", type=json.loads, default=None)
        p.add_argument("--output-type", nargs="+", type=str, default=None)
        p.add_argument("--output-name", nargs="+", type=str, default=None)
        p.add_argument("--aux-config", nargs="+", default=None,
                       help="key=value pairs or a .json file")
        p.add_argument("--preprocess", type=str, default=None,
                       help="preprocess code file or package dir")

    p = model_sub.add_parser("add", help="add a static model endpoint")
    p.add_argument("--engine", type=str, required=True)
    p.add_argument("--endpoint", type=str, required=True)
    p.add_argument("--version", type=str, default=None)
    p.add_argument("--model-id", type=str, default=None)
    p.add_argument("--name", type=str, default=None, help="model query: name")
    p.add_argument("--project", type=str, default=None, help="model query: project")
    p.add_argument("--tags", nargs="+", default=None, help="model query: tags")
    p.add_argument("--published", action="store_true")
    _add_io_spec(p)
    p.set_defaults(func=func_model_endpoint_add)

    p = model_sub.add_parser("auto-update", help="add a model auto-deploy query")
    p.add_argument("--engine", type=str, required=True)
    p.add_argument("--endpoint", type=str, required=True)
    p.add_argument("--max-versions", type=int, default=None)
    p.add_argument("--name", type=str, default=None)
    p.add_argument("--project", type=str, default=None)
    p.add_argument("--tags", nargs="+", default=None)
    p.add_argument("--published", action="store_true")
    _add_io_spec(p)
    p.set_defaults(func=func_model_auto_update_add)

    p = model_sub.add_parser("canary", help="add a canary/A-B endpoint")
    p.add_argument("--endpoint", type=str, required=True)
    p.add_argument("--weights", nargs="+", type=float, required=True)
    p.add_argument("--input-endpoints", nargs="+", default=None)
    p.add_argument("--input-endpoint-prefix", type=str, default=None)
    p.set_defaults(func=func_canary_add)

    metrics = sub.add_parser("metrics", help="statistics logging management")
    metrics_sub = metrics.add_subparsers(dest="metrics_command")

    p = metrics_sub.add_parser("add", help="add logged metrics for an endpoint")
    p.add_argument("--endpoint", type=str, required=True)
    p.add_argument("--log-freq", type=float, default=None)
    p.add_argument("--variable-scalar", nargs="+", default=None,
                   help="name=min/max/step or name=v1,v2,...")
    p.add_argument("--variable-enum", nargs="+", default=None, help="name=a,b,c")
    p.add_argument("--variable-value", nargs="+", default=None)
    p.add_argument("--variable-counter", nargs="+", default=None)
    p.set_defaults(func=func_metrics_add)

    p = metrics_sub.add_parser("remove", help="remove logged metrics")
    p.add_argument("--endpoint", type=str, required=True)
    p.add_argument("--variable", nargs="+", default=None)
    p.set_defaults(func=func_metrics_remove)

    p = metrics_sub.add_parser("list", help="list logged metrics")
    p.set_defaults(func=func_metrics_list)

    args = parser.parse_args(argv)
    global VERBOSE
    VERBOSE = bool(args.debug)
    if not getattr(args, "func", None):
        parser.print_help()
        return 1
    args.func(args)
    return 0


def main():
    try:
        sys.exit(cli())
    except KeyboardInterrupt:
        sys.exit(130)
    except SystemExit:
        raise
    except Exception as ex:
        if VERBOSE:
            raise
        print("Error: {}".format(ex), file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
