"""tpuserve-analyze: project-native static analysis for the TPU serving tree.

The orchestration layer survives on reconciliation loops; the engine tier
survives on *invariants* — PagePool refcount conservation under a lock,
donation-safe ``jax.jit`` boundaries, no blocking work on the asyncio serving
path, structured errors on router paths. None of those are enforceable by a
generic linter, so this package implements them as AST rules over stdlib
``ast`` only (no third-party deps — it must run under ``JAX_PLATFORMS=cpu``
in any container the tests run in, without importing jax or the code under
analysis).

Usage::

    python -m clearml_serving_tpu.analyze [paths ...]    # default: package tree
    scripts/check.sh                                     # ruff -> mypy -> this

Every finding carries a rule code, ``file:line:col``, a message, and a fix-it
hint. A deliberate violation is silenced inline::

    time.sleep(0.1)  # tpuserve: ignore[TPU101] warmup outside the event loop

An ignore comment on a ``def``/``class``/``async def`` line exempts that whole
scope (used for "lock held by caller" helpers). The rule catalog lives in
docs/static_analysis.md; tests/test_analyze.py pins every rule with positive,
negative, and ignore-comment fixtures, plus a tree-wide zero-findings gate
that runs in tier-1.
"""

from __future__ import annotations

import ast
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "RULES",
    "analyze_source",
    "analyze_file",
    "analyze_paths",
    "expand_select",
    "iter_python_files",
]

# -- rule catalog -------------------------------------------------------------
# code -> (one-line summary, fix-it hint). The authoritative prose catalog is
# docs/static_analysis.md; keep the two in sync (test_analyze checks this
# table covers every emitted code).
RULES: Dict[str, Tuple[str, str]] = {
    "TPU000": (
        "file does not parse",
        "fix the syntax error; nothing else can be checked until it parses",
    ),
    "TPU101": (
        "blocking sleep/subprocess call inside `async def`",
        "use `await asyncio.sleep(...)` or move the work to "
        "`asyncio.to_thread(...)`",
    ),
    "TPU102": (
        "synchronous file/socket I/O inside `async def`",
        "wrap the call in `asyncio.to_thread(...)` (or do it before entering "
        "the event loop)",
    ),
    "TPU103": (
        "device synchronization (`block_until_ready`/`jax.device_get`) "
        "inside `async def`",
        "dispatch on a worker thread (`asyncio.to_thread`) so the event loop "
        "never blocks on the device",
    ),
    "TPU104": (
        "unawaited `.acquire()` inside `async def` (blocks the event loop "
        "for threading locks, silently returns a coroutine for asyncio ones)",
        "use `async with lock:` / `await lock.acquire()`, or take threading "
        "locks on a worker thread",
    ),
    "TPU201": (
        "`jax.jit`-wrapped function closes over `self` (mutable state is "
        "baked into the trace; mutations after compile are silently ignored)",
        "pass the state as an explicit argument (pytree leaf or static arg)",
    ),
    "TPU202": (
        "donated buffer used again after the donating jitted call "
        "(the buffer is invalidated by donation)",
        "rebind the result over the donated name "
        "(`self.k = self._write(self.k, ...)`) before any further use",
    ),
    "TPU203": (
        "unhashable literal (list/dict/set) passed at a static argument "
        "position of a jitted function (TypeError at trace time; dynamic "
        "values there recompile per call)",
        "pass a tuple (hashable) or make the argument dynamic",
    ),
    "TPU301": (
        "guarded attribute mutated outside its declared lock scope",
        "wrap the mutation in `with self.<lock>:` or annotate the helper "
        "with `# tpuserve: ignore[TPU301] lock held by caller`",
    ),
    "TPU401": (
        "bare `except:` / `except Exception: pass` swallows errors on a "
        "router path",
        "catch the narrowest type, re-raise, or map to the errors.py "
        "hierarchy; annotate genuinely best-effort sites",
    ),
    "TPU402": (
        "`raise Exception(...)` on a router path defeats structured error "
        "mapping (every caller sees an opaque 500)",
        "raise a clearml_serving_tpu.errors.RequestError subclass (or a "
        "specific builtin like ValueError)",
    ),
    "TPU403": (
        "faults.fire() call site names a point missing from the "
        "faults.KNOWN_POINTS registry",
        "add the point (with a docstring entry) to llm/faults.py "
        "KNOWN_POINTS so chaos specs can target it",
    ),
    "TPU501": (
        "worker-reachable code mutates thread-affine state (declared via "
        "`__affine_to__`; affine state has no lock on purpose — exactly one "
        "thread owns it)",
        "move the mutation to the owning thread (hand results back through "
        "a snapshot/queue), or annotate the protocol-serialized site with "
        "`# tpuserve: ignore[TPU501] reason`",
    ),
    "TPU502": (
        "cross-thread handoff of a mutable host buffer without a copy "
        "(`jnp.asarray` of a numpy array is zero-copy on CPU; a late device "
        "read races in-place mutation — the PR-4 wrong-token race)",
        "snapshot at the handoff: `jnp.asarray(self._buf.copy())`",
    ),
    "TPU503": (
        "`await` while holding a synchronous lock (coroutines needing the "
        "lock deadlock against the suspended holder; worker threads convoy)",
        "release the lock before awaiting, or use `asyncio.Lock` with "
        "`async with`",
    ),
    "TPU504": (
        "lock-helper (`lock held by caller`) called without the declared "
        "lock lexically held — a TPU301 scope ignore is a hole this rule "
        "closes across the call graph",
        "wrap the call in `with <receiver>.<lock>:`, or annotate the "
        "call site with `# tpuserve: ignore[TPU504] reason`",
    ),
    "TPU601": (
        "request-varying length reaches an eager device upload/alloc "
        "without a registered bucketizer (each distinct length is a "
        "distinct XLA program: unbounded compile-key cardinality)",
        "route the value through llm/shapes.py (pow2_bucket / "
        "pad_to_multiple / pad_pages) or a registered `__bucketizers__` "
        "helper before it shapes device data",
    ),
    "TPU602": (
        "dtype/weak-type drift into a jit boundary (bare float literal, "
        "float() conversion, or dtype-less np.asarray at a `*_jit` call "
        "site splits the compile cache against the cached-constant "
        "pattern)",
        "pass an explicitly-typed cached device constant "
        "(`jnp.float32(x)` / `np.asarray(x, np.int32)`), invalidated at "
        "commit like the engine's sampling constants",
    ),
    "TPU603": (
        "jit entry violates the class's `__compile_keys__` compile "
        "surface: either undeclared, or declared serve-path but absent "
        "from the warmup shape registry (llm/warmup.py WARMUP_COVERED)",
        "declare the entry under a `__compile_keys__` role; serve-path "
        "entries must be added to llm/warmup.py's registry (and its "
        "sweep) so startup/loadtest warmup compiles them before the fence",
    ),
    "TPU604": (
        "request-varying value fed to a static_argnums/static_argnames "
        "position (static args hash into the compile key: this recompiles "
        "per request)",
        "make the argument dynamic, or bucketize it first so the static "
        "key space is finite",
    ),
    "TPU701": (
        "declared acquire can leak: some path (usually an exception edge) "
        "reaches the function exit without a matching release, "
        "drop-to-recompute handler, or ownership transfer",
        "release on the failure path (try/except + release + raise, or "
        "try/finally), route through the registered drop handler, or "
        "annotate a real ownership transfer with "
        "`# tpuserve: ignore[TPU701] <where ownership went>`",
    ),
    "TPU702": (
        "release not dominated by its acquire: a second matching release "
        "on a path that already discharged the obligation (the "
        "double-free / use-after-free shape)",
        "release exactly once per acquire; guard the cleanup path so "
        "recovery code cannot re-free what the normal path freed",
    ),
    "TPU703": (
        "page-id publish not fence-ordered: freshly minted pool pages "
        "become visible (`.pages = ...`) without the enqueue-before-"
        "publish fence (import_pages/promote_pages) ordering their "
        "payload first — the drop_ship_fence/drop_tier_fence defect class",
        "enqueue the upload/scatter BEFORE assigning the page ids to any "
        "shared structure; consumers are then ordered after the copy by "
        "data dependency on the pool handles (docs/kv_tiering.md)",
    ),
    "TPU704": (
        "transport shipment consumed twice, or its payload slabs reused "
        "after the store_shipped attach consumed them (recv is a "
        "consume-once pop; the import copies the slab rows it needs)",
        "pop once per key and drop the handle after the attach; re-read "
        "the imported pages through the radix cache, not the shipment",
    ),
    "TPU801": (
        "mesh-axis literal not in the parallel/mesh.py __mesh_axes__ "
        "registry (a typo'd axis in a PartitionSpec/collective fails at "
        "trace time on multi-chip hardware we rarely reach)",
        "use a declared axis, or add the new axis to parallel/mesh.py "
        "__mesh_axes__ (and its docstring) so every sharding rule and "
        "kernel agrees on the vocabulary",
    ),
    "TPU802": (
        "serve-path jit surface without sharding declarations: a class "
        "declaring serve-role `__compile_keys__` must declare "
        "`__shardings__` naming the sharding builder covering each "
        "donated/sharded operand family, and every named builder must be "
        "in parallel/sharding.py's __sharding_builders__ registry",
        "declare `__shardings__ = {\"params\": "
        "\"parallel.sharding.llama_param_sharding\", ...}` next to "
        "__compile_keys__, and register new builders in "
        "parallel/sharding.py __sharding_builders__",
    ),
    "TPU803": (
        "multihost-unsafe host access: np.asarray/device_get/.tolist()/"
        "int() on a value tainted as sharded-global (deadlocks or reads "
        "one shard's garbage under more than one process)",
        "read through .addressable_shards (per-host data), or annotate a "
        "declared-replicated read with `# tpuserve: ignore[TPU803] <why "
        "it is replicated>`",
    ),
    "TPU804": (
        "silent replication fallback in a sharding builder: a path "
        "returns a replicated spec for an operand other paths shard "
        "(replicate-instead-of-shard defeats TP memory scaling with no "
        "error)",
        "annotate the fallback with `# tpuserve: ignore[TPU804] <why "
        "this operand must replicate>`, or shard it",
    ),
}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None. Shared by every rule
    module — name-chain resolution must behave identically across rules."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class Finding:
    code: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""

    def render(self) -> str:
        out = "{}:{}:{}: {} {}".format(
            self.path, self.line, self.col, self.code, self.message
        )
        if self.hint:
            out += "\n    fix: {}".format(self.hint)
        return out

    def sort_key(self):
        return (self.path, self.line, self.col, self.code)

    def as_dict(self) -> Dict[str, object]:
        """Stable machine-readable shape for `--format json` (one object per
        line): CI diff annotators key on rule/file/line."""
        return {
            "rule": self.code,
            "file": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fix": self.hint,
        }


# -- inline escape hatch ------------------------------------------------------

_IGNORE_RE = re.compile(
    r"#\s*tpuserve:\s*ignore(?:\[(?P<codes>[A-Z0-9,\s]+)\])?", re.IGNORECASE
)


def _ignore_map(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> set of ignored codes (None = ignore every rule on that line).

    Built from the token stream, not a substring scan, so a ``tpuserve:
    ignore`` inside a string literal never silences anything.
    """
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _IGNORE_RE.search(tok.string)
            if not m:
                continue
            codes = m.group("codes")
            line = tok.start[0]
            if not codes:
                out[line] = None  # ignore every rule on this line
            elif out.get(line, set()) is not None:
                parsed = {c.strip().upper() for c in codes.split(",") if c.strip()}
                out[line] = (out.get(line) or set()) | parsed
    except tokenize.TokenError:
        pass
    return out


def _scope_ignores(tree: ast.AST, ignores: Dict[int, Optional[Set[str]]]):
    """Expand def/class-line ignores to cover the whole scope body."""
    expanded: Dict[int, Optional[Set[str]]] = dict(ignores)
    for node in ast.walk(tree):
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        # the comment may sit on the `def` line or on the decorator line
        decl_lines = [node.lineno] + [d.lineno for d in node.decorator_list]
        scoped: Optional[Set[str]] = set()
        hit = False
        for ln in decl_lines:
            if ln in ignores:
                hit = True
                if ignores[ln] is None:
                    scoped = None
                    break
                scoped |= ignores[ln]  # type: ignore[operator]
        if not hit:
            continue
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        for ln in range(node.lineno, end + 1):
            prev = expanded.get(ln, set())
            if scoped is None or (ln in expanded and prev is None):
                expanded[ln] = None
            else:
                expanded[ln] = (prev or set()) | scoped
    return expanded


def _filter_ignored(
    findings: List[Finding], ignores: Dict[int, Optional[Set[str]]]
) -> List[Finding]:
    kept = []
    for f in findings:
        allowed = ignores.get(f.line, set())
        if allowed is None or (allowed and f.code in allowed):
            continue
        kept.append(f)
    return kept


# -- driver -------------------------------------------------------------------


def expand_select(select: Iterable[str]) -> Set[str]:
    """Rule selector -> concrete rule codes. Accepts exact codes
    (``TPU301``), family patterns (``TPU7xx``/``TPU3XX``), and bare family
    prefixes (``TPU7``): CI and pre-commit runs select whole families as
    the catalog grows. Unknown exact codes pass through (the caller may be
    selecting against a newer catalog)."""
    chosen: Set[str] = set()
    for raw in select:
        token = raw.strip().upper()
        if not token:
            continue
        if token.endswith("XX") and len(token) > 2:
            prefix = token[:-2]
            chosen |= {c for c in RULES if c.startswith(prefix)}
        elif token in RULES:
            chosen.add(token)
        else:
            matches = {c for c in RULES if c.startswith(token)}
            chosen |= matches or {token}
    return chosen


def analyze_source(
    source: str,
    path: str = "<string>",
    select: Optional[Iterable[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """All findings for one module's source text (ignores already applied).
    ``timings`` (module name -> seconds) accumulates per-family analyzer
    cost when provided (scripts/check.sh reports it)."""
    from . import (
        rules_async,
        rules_compile,
        rules_errors,
        rules_jit,
        rules_lifecycle,
        rules_locks,
        rules_sharding,
        rules_threads,
    )

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as ex:
        return [
            Finding(
                "TPU000", path, ex.lineno or 0, ex.offset or 0,
                "syntax error: {}".format(ex.msg),
                "the analyzer (and the interpreter) cannot parse this file",
            )
        ]
    chosen = expand_select(select) if select is not None else None
    # family -> rule module: a selected run skips modules with no selected
    # codes entirely (the CI fast lanes run one family, not all-then-drop)
    modules = (
        (rules_async, ("TPU1",)),
        (rules_jit, ("TPU2",)),
        (rules_locks, ("TPU3",)),
        (rules_errors, ("TPU4",)),
        (rules_threads, ("TPU5",)),
        (rules_compile, ("TPU6",)),
        (rules_lifecycle, ("TPU7",)),
        (rules_sharding, ("TPU8",)),
    )
    findings: List[Finding] = []
    for mod, prefixes in modules:
        if chosen is not None and not any(
            c.startswith(prefixes) for c in chosen
        ):
            continue
        if timings is None:
            findings.extend(mod.check(tree, path, source))
        else:
            t0 = time.perf_counter()
            findings.extend(mod.check(tree, path, source))
            name = mod.__name__.rsplit(".", 1)[-1]
            timings[name] = timings.get(name, 0.0) + (
                time.perf_counter() - t0
            )
    ignores = _scope_ignores(tree, _ignore_map(source))
    findings = _filter_ignored(findings, ignores)
    if chosen is not None:
        findings = [f for f in findings if f.code in chosen]
    findings.sort(key=Finding.sort_key)
    return findings


def analyze_file(
    path: str,
    select: Optional[Iterable[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return analyze_source(fh.read(), path, select=select, timings=timings)


_SKIP_DIRS = {".git", "__pycache__", ".venv", "node_modules", "build", "dist"}


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return out


def analyze_paths(
    paths: Sequence[str],
    select: Optional[Iterable[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(analyze_file(path, select=select, timings=timings))
    return findings
