"""CLI: ``python -m clearml_serving_tpu.analyze [paths ...]``.

Exit status 0 when the tree is clean, 1 when any finding survives the
inline ignores — tier-1 (scripts/check.sh) treats non-zero as a hard fail
and prints the per-rule table so the offending invariant is obvious.

``--select`` accepts exact codes and family patterns (``TPU7xx``/``TPU3``)
so CI lanes can run one family; ``--changed-only`` restricts findings to
lines a ``git diff`` against ``--diff-base`` (default HEAD) touched, so
pre-commit runs stay proportional to the change, not the tree;
``--timings`` prints per-family analyzer cost (scripts/check.sh reports
it so the gate's latency stays visible as the rule count grows). Exit
codes and ``--format json|github`` are identical in every mode.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from collections import Counter
from typing import Dict, Optional, Set

from . import RULES, analyze_paths

_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")


def changed_lines(paths, base: str = "HEAD") -> Optional[Dict[str, Set[int]]]:
    """abs path -> line numbers touched by ``git diff base`` (working tree
    included). None when git is unavailable / not a repository — the
    caller then falls back to a full run rather than silently passing."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        # absolute pathspecs: the diff runs from the repo root while the
        # caller's paths are relative to ITS cwd — a relative pathspec
        # would silently match nothing from a subdirectory and report the
        # run clean
        diff = subprocess.run(
            ["git", "diff", "--unified=0", base, "--"]
            + [os.path.abspath(p) for p in paths],
            capture_output=True, text=True, check=True, cwd=top,
        ).stdout
    except (OSError, subprocess.CalledProcessError):
        return None
    out: Dict[str, Set[int]] = {}
    current: Optional[Set[int]] = None
    for line in diff.splitlines():
        if line.startswith("+++ "):
            name = line[4:]
            if name.startswith("b/"):
                name = name[2:]
            if name == "/dev/null":
                current = None
            else:
                current = out.setdefault(
                    os.path.abspath(os.path.join(top, name)), set()
                )
        elif current is not None:
            m = _HUNK_RE.match(line)
            if m:
                start = int(m.group(1))
                count = int(m.group(2)) if m.group(2) is not None else 1
                current.update(range(start, start + max(count, 1)))
    return out


def _default_root() -> str:
    # the package directory itself (…/clearml_serving_tpu)
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sarif(findings) -> dict:
    """SARIF 2.1.0 document: one run, rule metadata from the catalog,
    one result per finding. `--format sarif` exists for the code-scanning
    upload lane in .github/workflows/checks.yml."""
    rules = [
        {
            "id": code,
            "shortDescription": {"text": RULES[code][0]},
            "help": {"text": "fix: {}".format(RULES[code][1])},
        }
        for code in sorted(RULES)
    ]
    results = []
    for f in findings:
        message = f.message
        if f.hint:
            message += " fix: {}".format(f.hint)
        results.append({
            "ruleId": f.code,
            "level": "error",
            "message": {"text": message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": os.path.relpath(f.path).replace(os.sep, "/"),
                    },
                    "region": {
                        "startLine": max(1, f.line),
                        "startColumn": max(1, f.col + 1),
                    },
                },
            }],
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "tpuserve-analyze",
                    "informationUri":
                        "docs/static_analysis.md",
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m clearml_serving_tpu.analyze",
        description="project-native static analysis (stdlib ast only; "
        "rule catalog in docs/static_analysis.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories (default: the installed package tree)",
    )
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule codes or family patterns to run "
        "(TPU301, TPU7xx, TPU5; default: all)",
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="report only findings on lines touched by `git diff "
        "<--diff-base>` (pre-commit/CI fast path; exit codes unchanged)",
    )
    parser.add_argument(
        "--diff-base", default="HEAD",
        help="base ref for --changed-only (default: HEAD — the working "
        "tree's uncommitted changes; use origin/main for PR lanes)",
    )
    parser.add_argument(
        "--timings", action="store_true",
        help="print per-family analyzer wall time after the run",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="suppress per-finding output; only the summary table",
    )
    parser.add_argument(
        "--format", choices=("human", "json", "github", "sarif"),
        default="human",
        help="json = one finding object per line "
        "(rule/file/line/col/message/fix) for CI diff annotation; "
        "github = GitHub Actions workflow-command annotations "
        "(::error file=...,line=...) rendered inline on the PR diff; "
        "sarif = one SARIF 2.1.0 run (rule metadata from the catalog) "
        "for code-scanning upload; exit codes are identical to human "
        "output",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            summary, hint = RULES[code]
            print("{}  {}\n         fix: {}".format(code, summary, hint))
        return 0

    paths = args.paths or [_default_root()]
    select = (
        [c.strip() for c in args.select.split(",") if c.strip()]
        if args.select
        else None
    )
    timings = {} if args.timings else None
    findings = analyze_paths(paths, select=select, timings=timings)
    if args.changed_only:
        touched = changed_lines(paths, base=args.diff_base)
        if touched is None:
            print(
                "tpuserve-analyze: --changed-only needs a git checkout; "
                "running the full set instead", file=sys.stderr,
            )
        else:
            findings = [
                f for f in findings
                if f.line in touched.get(os.path.abspath(f.path), ())
            ]

    def _print_timings() -> None:
        if timings is None:
            return
        total = sum(timings.values())
        print("\nper-family analyzer time:")
        for name in sorted(timings, key=timings.get, reverse=True):
            print("  {:<18} {:>7.1f} ms".format(name, timings[name] * 1e3))
        print("  {:<18} {:>7.1f} ms".format("total", total * 1e3))

    if args.format == "json":
        # machine output: findings only, nothing else on stdout — a clean
        # tree prints zero lines and exits 0
        for finding in findings:
            print(json.dumps(finding.as_dict(), sort_keys=True))
        return 1 if findings else 0
    if args.format == "sarif":
        # one SARIF 2.1.0 run: rule metadata comes from the catalog so
        # code-scanning UIs show the summary + fix-it hint next to each
        # result; the whole doc goes to stdout (CI redirects it to the
        # upload artifact). Exit codes match every other format.
        print(json.dumps(_sarif(findings), sort_keys=True))
        return 1 if findings else 0
    if args.format == "github":
        # GitHub Actions workflow commands: one ::error per finding (the
        # runner renders them as inline diff annotations). Same contract
        # as json: findings only on stdout, identical exit codes. Message
        # text must stay single-line — workflow commands end at newline —
        # so the fix-it hint rides the same line.
        for finding in findings:
            message = finding.message
            if finding.hint:
                message += " fix: {}".format(finding.hint)
            print(
                "::error file={},line={},col={},title={}::{}".format(
                    finding.path, finding.line, finding.col, finding.code,
                    message.replace("%", "%25").replace("\r", "%0D")
                    .replace("\n", "%0A"),
                )
            )
        return 1 if findings else 0
    if not args.quiet:
        for finding in findings:
            print(finding.render())
    if findings:
        counts = Counter(f.code for f in findings)
        print("\ntpuserve-analyze: {} finding(s)".format(len(findings)))
        width = max(len(c) for c in counts)
        for code in sorted(counts):
            print(
                "  {:<{w}}  {:>4}  {}".format(
                    code, counts[code], RULES.get(code, ("?", ""))[0], w=width
                )
            )
        print(
            "\nsilence a deliberate violation with "
            "`# tpuserve: ignore[CODE] reason` on the offending line."
        )
        _print_timings()
        return 1
    print(
        "tpuserve-analyze: clean ({} rule(s) over {}{})".format(
            len(RULES), ", ".join(paths),
            ", changed lines only" if args.changed_only else "",
        )
    )
    _print_timings()
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe; findings already flowed
        sys.exit(1)
