"""CLI: ``python -m clearml_serving_tpu.analyze [paths ...]``.

Exit status 0 when the tree is clean, 1 when any finding survives the
inline ignores — tier-1 (scripts/check.sh) treats non-zero as a hard fail
and prints the per-rule table so the offending invariant is obvious.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter

from . import RULES, analyze_paths


def _default_root() -> str:
    # the package directory itself (…/clearml_serving_tpu)
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m clearml_serving_tpu.analyze",
        description="project-native static analysis (stdlib ast only; "
        "rule catalog in docs/static_analysis.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories (default: the installed package tree)",
    )
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule codes to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="suppress per-finding output; only the summary table",
    )
    parser.add_argument(
        "--format", choices=("human", "json", "github"), default="human",
        help="json = one finding object per line "
        "(rule/file/line/col/message/fix) for CI diff annotation; "
        "github = GitHub Actions workflow-command annotations "
        "(::error file=...,line=...) rendered inline on the PR diff; "
        "exit codes are identical to human output",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            summary, hint = RULES[code]
            print("{}  {}\n         fix: {}".format(code, summary, hint))
        return 0

    paths = args.paths or [_default_root()]
    select = (
        [c.strip() for c in args.select.split(",") if c.strip()]
        if args.select
        else None
    )
    findings = analyze_paths(paths, select=select)
    if args.format == "json":
        # machine output: findings only, nothing else on stdout — a clean
        # tree prints zero lines and exits 0
        for finding in findings:
            print(json.dumps(finding.as_dict(), sort_keys=True))
        return 1 if findings else 0
    if args.format == "github":
        # GitHub Actions workflow commands: one ::error per finding (the
        # runner renders them as inline diff annotations). Same contract
        # as json: findings only on stdout, identical exit codes. Message
        # text must stay single-line — workflow commands end at newline —
        # so the fix-it hint rides the same line.
        for finding in findings:
            message = finding.message
            if finding.hint:
                message += " fix: {}".format(finding.hint)
            print(
                "::error file={},line={},col={},title={}::{}".format(
                    finding.path, finding.line, finding.col, finding.code,
                    message.replace("%", "%25").replace("\r", "%0D")
                    .replace("\n", "%0A"),
                )
            )
        return 1 if findings else 0
    if not args.quiet:
        for finding in findings:
            print(finding.render())
    if findings:
        counts = Counter(f.code for f in findings)
        print("\ntpuserve-analyze: {} finding(s)".format(len(findings)))
        width = max(len(c) for c in counts)
        for code in sorted(counts):
            print(
                "  {:<{w}}  {:>4}  {}".format(
                    code, counts[code], RULES.get(code, ("?", ""))[0], w=width
                )
            )
        print(
            "\nsilence a deliberate violation with "
            "`# tpuserve: ignore[CODE] reason` on the offending line."
        )
        return 1
    print(
        "tpuserve-analyze: clean ({} rule(s) over {})".format(
            len(RULES), ", ".join(paths)
        )
    )
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe; findings already flowed
        sys.exit(1)
