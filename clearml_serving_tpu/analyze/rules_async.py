"""TPU1xx — blocking work inside ``async def`` on the serving path.

The router, the OpenAI front, the engine loop, and the gRPC client all share
ONE asyncio event loop; a single synchronous ``time.sleep``, file read, or
``block_until_ready()`` inside an ``async def`` stalls every in-flight
request at once (and defeats the deadline/watchdog machinery of PR 2, which
assumes the loop keeps turning). These rules only fire inside ``async def``
bodies — the same calls on worker threads are the *correct* pattern.

Scope note: nested ``def`` inside an ``async def`` re-enters synchronous
land (it may be handed to ``asyncio.to_thread``), so the visitor tracks the
innermost function kind, not just "am I somewhere under an async def".
"""

from __future__ import annotations

import ast
from typing import List, Optional

from . import Finding, RULES, dotted_name as _dotted

# qualified call names that block the loop outright
_BLOCKING_CALLS = {
    ("time", "sleep"): "TPU101",
    ("os", "system"): "TPU101",
    ("subprocess", "run"): "TPU101",
    ("subprocess", "call"): "TPU101",
    ("subprocess", "check_call"): "TPU101",
    ("subprocess", "check_output"): "TPU101",
    # sync network/file I/O
    ("socket", "create_connection"): "TPU102",
    ("request", "urlopen"): "TPU102",   # urllib.request.urlopen
    ("urllib", "urlopen"): "TPU102",
    ("requests", "get"): "TPU102",
    ("requests", "post"): "TPU102",
    ("requests", "request"): "TPU102",
    # device syncs: the host thread parks until the TPU finishes
    ("jax", "device_get"): "TPU103",
    ("jax", "block_until_ready"): "TPU103",
}

# bare-name calls that block (builtins)
_BLOCKING_BARE = {"open": "TPU102"}

# attribute-only matches: any receiver (``x.block_until_ready()``)
_BLOCKING_ATTRS = {"block_until_ready": "TPU103"}


class _AsyncVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        # innermost function kind stack: True = async, False = sync
        self._fn: List[bool] = []
        # Await expressions wrap their value; remember them so x.acquire()
        # under an await is not flagged
        self._awaited: set = set()

    # -- scope tracking ----------------------------------------------------

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._fn.append(True)
        self.generic_visit(node)
        self._fn.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn.append(False)
        self.generic_visit(node)
        self._fn.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._fn.append(False)
        self.generic_visit(node)
        self._fn.pop()

    def _in_async(self) -> bool:
        return bool(self._fn) and self._fn[-1]

    # -- checks ------------------------------------------------------------

    def visit_Await(self, node: ast.Await) -> None:
        self._awaited.add(id(node.value))
        self.generic_visit(node)

    def _emit(self, code: str, node: ast.AST, detail: str) -> None:
        summary, hint = RULES[code]
        self.findings.append(
            Finding(
                code, self.path, node.lineno, node.col_offset,
                "{} ({})".format(summary, detail), hint,
            )
        )

    def visit_Call(self, node: ast.Call) -> None:
        if self._in_async():
            matched = False
            name = _dotted(node.func)
            if name is not None:
                parts = name.split(".")
                # match on the LAST two components so `self._mod.time.sleep`
                # style aliases still hit; single names match builtins
                pair = tuple(parts[-2:]) if len(parts) >= 2 else None
                if pair in _BLOCKING_CALLS:
                    matched = True
                    self._emit(_BLOCKING_CALLS[pair], node, "call to {}".format(name))
                elif len(parts) == 1 and parts[0] in _BLOCKING_BARE:
                    matched = True
                    self._emit(_BLOCKING_BARE[parts[0]], node, "call to {}()".format(name))
            if isinstance(node.func, ast.Attribute) and not matched:
                # fallback for arbitrary receivers (`x.block_until_ready()`);
                # skipped when the qualified table above already fired so one
                # call never yields two findings
                attr = node.func.attr
                if attr in _BLOCKING_ATTRS:
                    self._emit(
                        _BLOCKING_ATTRS[attr], node,
                        ".{}() forces a device sync".format(attr),
                    )
                elif attr == "acquire" and id(node) not in self._awaited:
                    self._emit(
                        "TPU104", node,
                        "{}.acquire() without await".format(
                            _dotted(node.func.value) or "lock"
                        ),
                    )
        self.generic_visit(node)


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    visitor = _AsyncVisitor(path)
    # visit Await parents before Call children: ast.NodeVisitor already
    # descends parent-first, and visit_Await records the wrapped call before
    # generic_visit reaches it
    visitor.visit(tree)
    return visitor.findings
