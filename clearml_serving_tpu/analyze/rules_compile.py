"""TPU6xx — compile-surface discipline (docs/static_analysis.md).

TPU performance is a *compile-surface* property: the set of (function,
shape, dtype) keys XLA ever sees from the serve loop must be FINITE and
KNOWN AHEAD of serving, because every serve-time recompile is a 100-1000 ms
stall of the loop thread that masquerades as scheduling tail (the PR-6
loadtest and the PR-10 tiering work each independently burned debugging
time on exactly this: unbucketed mini-cache slice keys, unwarmed
resume-commit shapes). This rule family machine-checks the invariant the
way TPU301 checks lock discipline and TPU5xx checks thread affinity,
against two project registries:

- **bucketizers** (``llm/shapes.py`` + ``__bucketizers__`` module
  declarations): the functions that collapse request-varying values into a
  finite key space (power-of-two buckets, page-multiple pads, null-page
  list padding);
- **the warmup shape registry** (``llm/warmup.py`` ``WARMUP_COVERED``):
  the jit entries whose shape keys the shared warmup sweep compiles before
  the serve fence.

Rules:

- **TPU601** — a request-varying value (prompt length, token list, page
  list: a name in ``REQUEST_VARYING``, or anything derived from one by the
  local taint pass) reaches an eager device upload/alloc (``jnp.asarray``/
  ``jnp.array``/``jnp.zeros``-family) without flowing through a registered
  bucketizer. Each distinct length is a distinct XLA program — unbounded
  compile-key cardinality on the serve path.
- **TPU602** — dtype/weak-type drift into a jit boundary: a bare Python
  float literal, a ``float(...)`` conversion, or a dtype-less
  ``np.asarray``/``np.array`` passed to a ``*_jit`` wrapper. Weak-typed
  scalars and platform-default numpy dtypes split the compile cache
  against the explicitly-typed cached-constant pattern (PR 4) and recompile
  when a caller's host types shift.
- **TPU603** — compile-surface closed world: inside a class declaring
  ``__compile_keys__``, every jit-wrapper attribute (``self.X =
  jax.jit(...)`` or any ``self.X_jit = ...``) must be declared under a
  role, and every ``"serve"``-role entry must appear in the warmup shape
  registry (``llm/warmup.py``, parsed from source like faults.KNOWN_POINTS;
  ``WARMUP_COVERED`` below is the build-time mirror, consistency-tested).
  A new dispatch-path jit entry that nobody warmed is exactly the mid-run
  compile stall this family exists to prevent.
- **TPU604** — a request-varying (tainted) value fed to a
  ``static_argnums``/``static_argnames`` position of a jitted wrapper:
  static arguments hash into the compile key, so a per-request value there
  IS a recompile per request.

The taint pass is local (per function, statements in source order) and
fails OPEN on anything it cannot prove: calls to unknown functions launder
taint, slices of clean buffers are clean even when the bounds vary. The
runtime compile sentry (``llm/compile_sentry.py``) is the dynamic net
behind those blind spots, exactly as the KV sanitizer backs TPU301 and the
interleaving explorer backs TPU5xx.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import Finding, RULES, dotted_name as _dotted
from .rules_jit import _collect as _collect_jit_wrappers, _is_jit_call

# -- registries ---------------------------------------------------------------

# Names whose VALUE LENGTH varies per request: prompt token lists, page-id
# lists, grammar token sets. A bare read of one of these (parameter, outer
# binding, attribute leaf like ``request.prompt_ids``) is tainted; a local
# assignment from a clean expression (np.zeros of a bucketed shape, a
# bucketizer call) makes the same name clean. Keep the set DISTINCTIVE —
# a generic name here drowns real findings in false positives.
REQUEST_VARYING: FrozenSet[str] = frozenset({
    "prompt_ids",
    "prompt",
    "token_ids",
    "ids",
    "pages",
    "host_ids",
    "host_pages",
    "allowed",
    "history",
})

# Call leaf names that collapse request-varying values into a finite key
# space. Project-level homes: llm/shapes.py (pow2_bucket/pad_to_multiple/
# pad_pages), the engine's prefill bucket picker, the pool's page-count
# round-up, and the ragged layout builder (its outputs are q-block-aligned
# and total-padded by construction). A module can extend the set for its
# own helpers with a literal module-level declaration::
#
#     __bucketizers__ = ("_my_bucket_helper",)
#
# tests/test_analyze_compile.py pins every project-level name here to a
# real definition in the tree.
BUCKETIZERS: FrozenSet[str] = frozenset({
    "pow2_bucket",
    "pad_to_multiple",
    "pad_pages",
    "decode_steps_bucket",
    "_bucket_for",
    "pages_needed",
    "ragged_layout",
})

# Build-time mirror of llm/warmup.py's WARMUP_COVERED (the jit entries the
# shared warmup sweep drives). TPU603 prefers the registry parsed from the
# llm/warmup.py nearest the analyzed file — this literal is the fallback
# for out-of-tree fixtures, and tests/test_analyze_compile.py asserts the
# two never drift.
WARMUP_COVERED: FrozenSet[str] = frozenset({
    "_prefill_jit",
    "_prefill_ring_jit",
    "_prefill_pipeline_jit",
    "_prefill_chunk_first_jit",
    "_prefill_chunk_jit",
    "_gather_pages_jit",
    "_assemble_prefix_jit",
    "_insert_jit",
    "_merge_rows_jit",
    "_decode_chunk_jit",
    "_decode_paged_chunk_jit",
    "_sample_jit",
    "_first_lp_jit",
    "_set_sampling_row_jit",
    "_spec_chunk_jit",
    "_spec_paged_jit",
    "_ragged_paged_jit",
    "_ragged_dense_jit",
    "_gather_finish_jit",
})

_warmup_cache: Dict[str, FrozenSet[str]] = {}


def _warmup_registry(path: str) -> FrozenSet[str]:
    """WARMUP_COVERED parsed from the llm/warmup.py nearest to ``path``
    (same resolution rule as rules_errors' faults.KNOWN_POINTS)."""
    directory = os.path.dirname(os.path.abspath(path))
    candidate: Optional[str] = None
    for _ in range(8):
        cand = os.path.join(directory, "llm", "warmup.py")
        if os.path.isfile(cand):
            candidate = cand
            break
        parent = os.path.dirname(directory)
        if parent == directory:
            break
        directory = parent
    if candidate is None:
        return WARMUP_COVERED
    if candidate in _warmup_cache:
        return _warmup_cache[candidate]
    covered = WARMUP_COVERED
    try:
        with open(candidate, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "WARMUP_COVERED"
                for t in node.targets
            ):
                continue
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]  # frozenset({...})
            try:
                literal = ast.literal_eval(value)
                covered = frozenset(str(p) for p in literal)
            except (ValueError, SyntaxError):
                pass
            break
    except (OSError, SyntaxError):
        pass
    _warmup_cache[candidate] = covered
    return covered


def _module_bucketizers(tree: ast.AST) -> FrozenSet[str]:
    """Literal module-level ``__bucketizers__ = ("name", ...)`` extensions."""
    out: Set[str] = set()
    for node in getattr(tree, "body", []):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "__bucketizers__"
            for t in node.targets
        ):
            continue
        try:
            literal = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            continue
        if isinstance(literal, (tuple, list, set, frozenset)):
            out |= {str(name) for name in literal}
    return frozenset(out)


# -- expression taint ---------------------------------------------------------

# device upload/alloc entry points whose SHAPE comes from the first
# argument. The module part distinguishes eager device ops (jnp/jax.numpy:
# each novel shape is an XLA program) from host numpy (taints the result,
# sinks only when later uploaded).
_UPLOAD_TAILS = ("asarray", "array")
_ALLOC_TAILS = ("zeros", "ones", "empty", "full", "arange")


def _call_parts(node: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(module leaf, function leaf) of a call's dotted name."""
    name = _dotted(node.func)
    if name is None:
        return None, None
    parts = name.split(".")
    return (parts[-2] if len(parts) >= 2 else None), parts[-1]


def _is_device_call(node: ast.Call) -> bool:
    """True for the jax.numpy entry points whose eager dispatch mints an
    XLA program per shape: `jnp.*` and the spelled-out `jax.numpy.*`.
    Plain-numpy spellings (`np.*`, bare `numpy.*`) are HOST calls — they
    only propagate taint, the later upload is the sink."""
    name = _dotted(node.func)
    if name is None:
        return False
    parts = name.split(".")
    if len(parts) < 2:
        return False
    if parts[-2] == "jnp":
        return True
    return len(parts) >= 3 and parts[-3] == "jax" and parts[-2] == "numpy"


class _TaintPass:
    """Forward pass over one function's own statements: tracks which local
    names hold request-varying-length values, and reports sink hits."""

    def __init__(self, registry: FrozenSet[str],
                 bucketizers: FrozenSet[str]):
        self.registry = registry
        self.bucketizers = bucketizers
        self.tainted: Set[str] = set()
        self.clean: Set[str] = set()

    def name_tainted(self, text: Optional[str]) -> bool:
        if text is None:
            return False
        if text in self.tainted:
            return True
        if text in self.clean:
            return False
        return text.split(".")[-1] in self.registry

    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Name, ast.Attribute)):
            return self.name_tainted(_dotted(node))
        if isinstance(node, ast.Call):
            mod, leaf = _call_parts(node)
            if leaf is None:
                return False  # dynamic callee: fail open
            if leaf in self.bucketizers:
                return False  # registered collapse
            if leaf == "len" and node.args:
                return self.expr_tainted(node.args[0])
            if leaf in ("min", "max", "abs", "sum", "sorted", "list",
                        "tuple"):
                return any(self.expr_tainted(a) for a in node.args)
            if leaf in _UPLOAD_TAILS and node.args:
                return self.expr_tainted(node.args[0])
            if leaf in _ALLOC_TAILS and node.args:
                return self.shape_tainted(node.args[0])
            return False  # unknown call launders: fail open
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.FloorDiv):
                # integer division by a bucket/page size collapses the key
                # space (the `-(-n // m) * m` pad idiom stays clean)
                return False
            return self.expr_tainted(node.left) or self.expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value)
        if isinstance(node, ast.IfExp):
            return self.expr_tainted(node.body) or self.expr_tainted(node.orelse)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return bool(node.generators) and self.expr_tainted(
                node.generators[0].iter
            )
        if isinstance(node, ast.NamedExpr):
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value)
        return False  # literals, lambdas, comparisons, ...

    def shape_tainted(self, node: ast.AST) -> bool:
        """A shape argument is tainted when the whole expression is, or —
        for a literal tuple/list shape — when any DIMENSION is."""
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e) for e in node.elts)
        return self.expr_tainted(node)

    def bind(self, stmt: ast.stmt) -> None:
        """Update the taint state for an assignment statement."""
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(stmt, ast.Assign):
            targets, value = list(stmt.targets), stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        elif isinstance(stmt, ast.AugAssign):
            targets, value = [stmt.target], stmt.value
        if value is None:
            return
        is_tainted = self.expr_tainted(value)
        if isinstance(stmt, ast.AugAssign):
            # x += tainted keeps/raises taint but never cleans
            tgt = _dotted(stmt.target)
            if tgt is not None and is_tainted:
                self.tainted.add(tgt)
                self.clean.discard(tgt)
            return
        for t in targets:
            names = (
                [_dotted(e) for e in t.elts]
                if isinstance(t, ast.Tuple)
                else [_dotted(t)]
            )
            for name in names:
                if name is None:
                    continue
                if is_tainted:
                    self.tainted.add(name)
                    self.clean.discard(name)
                else:
                    self.clean.add(name)
                    self.tainted.discard(name)


# -- per-function statement walk (shared shape with rules_jit) ----------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _own_statements(fn: ast.AST) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    stack: List[ast.AST] = list(getattr(fn, "body", []))
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.stmt):
            out.append(cur)
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _SCOPE_NODES):
                continue
            stack.append(child)
    return out


def _walk_stmt(stmt: ast.AST):
    stack = [stmt]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _SCOPE_NODES + (ast.stmt,)):
                continue
            stack.append(child)


# -- TPU602 helpers -----------------------------------------------------------


def _dtype_drift_detail(arg: ast.AST) -> Optional[str]:
    """Why an argument drifts dtype into a jit boundary, or None."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, float):
        return "bare float literal {!r} (weak-typed)".format(arg.value)
    if isinstance(arg, ast.Call):
        mod, leaf = _call_parts(arg)
        if leaf == "float":
            return "float(...) host conversion (weak-typed)"
        if (
            leaf in _UPLOAD_TAILS
            and mod in ("np", "numpy")
            and not any(kw.arg == "dtype" for kw in arg.keywords)
            and not (len(arg.args) >= 2)
        ):
            return "dtype-less {}.{}(...) (platform-default dtype)".format(
                mod, leaf
            )
    return None


# -- TPU603: __compile_keys__ closed world ------------------------------------


def _compile_keys_decl(cls: ast.ClassDef) -> Optional[Dict[str, Tuple[str, ...]]]:
    for stmt in cls.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "__compile_keys__"
            for t in stmt.targets
        ):
            continue
        try:
            decl = ast.literal_eval(stmt.value)
        except (ValueError, SyntaxError):
            return None
        if not isinstance(decl, dict):
            return None
        return {
            str(role): tuple(str(n) for n in names)
            for role, names in decl.items()
        }
    return None


def _class_jit_attrs(cls: ast.ClassDef) -> List[Tuple[str, ast.AST]]:
    """(attr name, node) for every self-attribute that is a jit wrapper:
    assigned from a jit call, named with the ``_jit`` suffix convention, or
    rebound from a local name that holds a jit call's result."""
    jit_locals: Set[str] = set()
    out: List[Tuple[str, ast.AST]] = []
    seen: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        value_is_jit = isinstance(value, ast.Call) and _is_jit_call(value)
        if value_is_jit:
            for t in node.targets:
                name = _dotted(t)
                if name and "." not in name:
                    jit_locals.add(name)
        for t in node.targets:
            name = _dotted(t)
            if not name or not name.startswith("self."):
                continue
            attr = name.split(".", 1)[1]
            if "." in attr:
                continue
            rhs_name = _dotted(value)
            is_entry = (
                value_is_jit
                or attr.endswith("_jit")
                or (rhs_name is not None and rhs_name in jit_locals)
            )
            if is_entry and attr not in seen:
                seen.add(attr)
                out.append((attr, node))
    return out


# -- entry --------------------------------------------------------------------


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    findings: List[Finding] = []

    def emit(code: str, node: ast.AST, detail: str) -> None:
        summary, hint = RULES[code]
        findings.append(
            Finding(
                code, path, node.lineno, node.col_offset,
                "{} ({})".format(summary, detail), hint,
            )
        )

    bucketizers = BUCKETIZERS | _module_bucketizers(tree)
    _defs, _jit_calls, wrappers = _collect_jit_wrappers(tree)

    # static_argnames registries for TPU604 (rules_jit._collect keeps only
    # int static_argnums; names need their own sweep)
    static_names: Dict[str, Tuple[str, ...]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        if not _is_jit_call(call):
            continue
        for kw in call.keywords:
            if kw.arg != "static_argnames":
                continue
            try:
                literal = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                continue
            names = (
                (literal,) if isinstance(literal, str)
                else tuple(str(n) for n in literal)
            )
            for t in node.targets:
                tname = _dotted(t)
                if tname:
                    static_names[tname.split(".")[-1]] = names

    # -- TPU601/602/604: per-function taint + sink walk --------------------
    fn_nodes = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in fn_nodes:
        taint = _TaintPass(REQUEST_VARYING, bucketizers)
        for stmt in sorted(_own_statements(fn), key=lambda s: s.lineno):
            for node in _walk_stmt(stmt):
                if not isinstance(node, ast.Call):
                    continue
                mod, leaf = _call_parts(node)
                # TPU601: eager device upload/alloc of a tainted value
                if (
                    leaf in _UPLOAD_TAILS
                    and _is_device_call(node)
                    and node.args
                    and taint.expr_tainted(node.args[0])
                ):
                    emit(
                        "TPU601", node,
                        "{}.{}({}) uploads a request-varying length".format(
                            mod, leaf, _dotted(node.args[0]) or "<expr>"
                        ),
                    )
                elif (
                    leaf in _ALLOC_TAILS
                    and _is_device_call(node)
                    and node.args
                    and taint.shape_tainted(node.args[0])
                ):
                    emit(
                        "TPU601", node,
                        "{}.{} shaped by a request-varying value".format(
                            mod, leaf
                        ),
                    )
                # wrapper call sites: TPU602 dtype drift + TPU604 statics
                cal = _dotted(node.func)
                wrapper_leaf = cal.split(".")[-1] if cal else None
                if wrapper_leaf and (
                    wrapper_leaf.endswith("_jit")
                    or wrapper_leaf in wrappers
                    or wrapper_leaf in static_names
                ):
                    for arg in node.args:
                        drift = _dtype_drift_detail(arg)
                        if drift is not None:
                            emit(
                                "TPU602", arg,
                                "{} passed to {}".format(drift, wrapper_leaf),
                            )
                    for kw in node.keywords:
                        if kw.arg is None:
                            continue
                        drift = _dtype_drift_detail(kw.value)
                        if drift is not None:
                            emit(
                                "TPU602", kw.value,
                                "{} passed to {} ({}=)".format(
                                    drift, wrapper_leaf, kw.arg
                                ),
                            )
                    wrapper = wrappers.get(wrapper_leaf)
                    if wrapper is not None:
                        for pos in wrapper.static:
                            if pos < len(node.args) and taint.expr_tainted(
                                node.args[pos]
                            ):
                                emit(
                                    "TPU604", node.args[pos],
                                    "argument {} of {} is static".format(
                                        pos, wrapper_leaf
                                    ),
                                )
                    for kw in node.keywords:
                        if (
                            kw.arg is not None
                            and kw.arg in static_names.get(wrapper_leaf, ())
                            and taint.expr_tainted(kw.value)
                        ):
                            emit(
                                "TPU604", kw.value,
                                "{}= of {} is a static argname".format(
                                    kw.arg, wrapper_leaf
                                ),
                            )
            taint.bind(stmt)

    # -- TPU603: compile-surface closed world ------------------------------
    covered = _warmup_registry(path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        decl = _compile_keys_decl(node)
        if decl is None:
            continue
        declared: Set[str] = set()
        for names in decl.values():
            declared |= set(names)
        serve = set(decl.get("serve", ()))
        for attr, assign in _class_jit_attrs(node):
            if attr not in declared:
                emit(
                    "TPU603", assign,
                    "jit entry `self.{}` is not declared in {}'s "
                    "__compile_keys__".format(attr, node.name),
                )
            elif attr in serve and attr not in covered:
                emit(
                    "TPU603", assign,
                    "serve-path jit entry `self.{}` is missing from the "
                    "warmup shape registry (llm/warmup.py "
                    "WARMUP_COVERED)".format(attr),
                )
    return findings
