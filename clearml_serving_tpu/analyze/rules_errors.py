"""TPU4xx — structured-error discipline on router paths.

PR 2 made failures *mean* something: the router maps the errors.py hierarchy
to 408/429/503/504 with Retry-After, and the chaos suite drives every path
through llm/faults.py. Both contracts erode silently — a new `except
Exception: pass` swallows the structured error, a `raise Exception` comes
out as an opaque 500, and a `faults.fire("typo.point")` never fires because
no spec targets it. These rules pin the contracts.

Router-path scope (TPU401 pass-swallow and TPU402): files under
``serving/``, ``engines/``, ``engine_server/``, and ``llm/openai_api.py`` —
the layers whose exceptions reach clients as HTTP statuses. Bare ``except:``
is flagged everywhere (it catches KeyboardInterrupt/SystemExit too, which no
serving layer may eat).

TPU403 validates ``faults.fire("<point>")`` string literals against the
``KNOWN_POINTS`` registry in llm/faults.py — parsed from source (stdlib ast
only, jax never imported). Registry drift therefore fails CI, not a 3 a.m.
chaos run.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional

from . import Finding, RULES, dotted_name as _dotted

_ROUTER_MARKERS = ("serving", "engines", "engine_server")

# fallback when the analyzed file is a detached fixture and llm/faults.py is
# not reachable from it; kept in sync with faults.KNOWN_POINTS by
# test_analyze (the runtime registry is authoritative)
FALLBACK_POINTS: FrozenSet[str] = frozenset({
    "engine.prefill",
    "engine.decode",
    "engine.decode.stall",
    "engine.decode.retire",
    "engine.dispatch.prepare",
    "engine.watchdog",
    "engine.drain",
    "engine.admit",
    "engine.admit.class",
    "engine.admit.budget",
    "engine.pool",
    "engine.preempt",
    "engine.release",
    "engine.kv.demote",
    "engine.kv.promote",
    "engine.kv.ship",
    "kv.ship.partial",
    "engine.kv.receive",
    "engine.spec.tree",
    "engine.ledger.leak",
    "engine.compile.bucket",
    "engine.shard.drift",
    "transport.wire.send",
    "transport.wire.recv",
    "replica.proc.crash",
    "router.pick",
    "router.eject",
    "grpc.call",
})

_points_cache: Dict[str, FrozenSet[str]] = {}


def _is_router_path(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    parts = norm.split("/")
    if any(marker in parts for marker in _ROUTER_MARKERS):
        return True
    return norm.endswith("llm/openai_api.py")


def _known_points(path: str) -> FrozenSet[str]:
    """KNOWN_POINTS parsed from the llm/faults.py nearest to ``path``."""
    directory = os.path.dirname(os.path.abspath(path))
    for _ in range(8):
        candidate = os.path.join(directory, "llm", "faults.py")
        if os.path.isfile(candidate):
            break
        parent = os.path.dirname(directory)
        if parent == directory:
            candidate = None
            break
        directory = parent
    else:
        candidate = None
    if candidate is None:
        return FALLBACK_POINTS
    if candidate in _points_cache:
        return _points_cache[candidate]
    points = FALLBACK_POINTS
    try:
        with open(candidate, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                for t in node.targets
            ):
                continue
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]  # frozenset({...})
            try:
                literal = ast.literal_eval(value)
                points = frozenset(str(p) for p in literal)
            except (ValueError, SyntaxError):
                pass
            break
    except (OSError, SyntaxError):
        pass
    _points_cache[candidate] = points
    return points


def _imports_fire(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.split(".")[-1] == "faults":
                if any(a.name == "fire" for a in node.names):
                    return True
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    """A handler that does nothing with the error (pure swallow)."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        if isinstance(stmt, ast.Continue):
            continue
        return False
    return True


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    findings: List[Finding] = []
    router = _is_router_path(path)
    bare_fire = _imports_fire(tree)
    known = None  # resolved lazily: most files have no fire() call sites

    def emit(code: str, node: ast.AST, detail: str) -> None:
        summary, hint = RULES[code]
        findings.append(
            Finding(
                code, path, node.lineno, node.col_offset,
                "{} ({})".format(summary, detail), hint,
            )
        )

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                emit(
                    "TPU401", node,
                    "bare `except:` also catches KeyboardInterrupt/SystemExit",
                )
            elif router and _swallows(node):
                caught = _dotted(node.type) or ""
                if caught in ("Exception", "BaseException"):
                    emit(
                        "TPU401", node,
                        "`except {}` with a pass-only body".format(caught),
                    )
        elif isinstance(node, ast.Raise) and router:
            exc = node.exc
            if isinstance(exc, ast.Call):
                exc = exc.func
            name = _dotted(exc) if exc is not None else None
            if name in ("Exception", "BaseException"):
                emit("TPU402", node, "raise {}".format(name))
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name is None:
                continue
            is_fire = name.endswith("faults.fire") or name == "faults.fire" or (
                bare_fire and name == "fire"
            )
            if not is_fire or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                if known is None:
                    known = _known_points(path)
                if first.value not in known:
                    emit(
                        "TPU403", node,
                        "point {!r} not in faults.KNOWN_POINTS".format(
                            first.value
                        ),
                    )
    return findings
