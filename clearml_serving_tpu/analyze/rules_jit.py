"""TPU2xx — ``jax.jit`` boundary hazards.

Three failure modes dominate JAX serving-stack incidents (PAPERS.md: pjit
training report; ragged paged attention):

- TPU201 a jitted function that closes over ``self``: the attribute values
  present at TRACE time are baked into the executable, so later mutations
  are silently ignored — classic "why does the engine still use the old
  table" bug. Methods taking ``self`` as a real parameter are fine (it's a
  traced input); closures are not.
- TPU202 use-after-donation: ``donate_argnums`` invalidates the caller's
  buffer. Reading the donated reference after the call returns garbage (or
  crashes on TPU). The only safe idiom is rebinding the result over the
  donated name in the SAME statement: ``self.k = self._write(self.k, ...)``.
- TPU203 unhashable/dynamic values at static positions: ``static_argnums``
  hashes the argument into the compile cache key — a list/dict/set literal
  is a TypeError at trace time, and a per-call-varying value recompiles on
  every request (the silent-recompile hazard the papers call out).

The pass is module-local by design: it resolves jit wrappers assigned to
names or ``self.<attr>`` within the analyzed file and checks call sites by
the wrapper's final name component. Cross-module donation is out of scope
(no such call sites exist in this tree; the sanitizer covers the runtime
side).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, RULES, dotted_name as _dotted


def _is_jit_call(node: ast.Call) -> bool:
    name = _dotted(node.func)
    return name is not None and (name == "jit" or name.endswith(".jit"))


def _int_tuple(node: Optional[ast.AST]) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums/static_argnums value -> tuple of ints."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _references_self_freely(fn: ast.AST) -> bool:
    """True when the function body reads ``self`` without declaring it."""
    if isinstance(fn, ast.Lambda):
        args = fn.args
        body: List[ast.AST] = [fn.body]
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = fn.args
        body = list(fn.body)
    else:
        return False
    declared = {
        a.arg
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        )
    }
    if "self" in declared:
        return False
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and node.id == "self":
                return True
            # nested defs that declare their own self (rare) still count as
            # a closure over the outer self only if they don't declare it —
            # keep it simple: any `self` Name inside counts unless shadowed,
            # and nothing in this tree shadows `self`.
    return False


class _Wrapper:
    __slots__ = ("donate", "static", "line")

    def __init__(self, donate, static, line):
        self.donate: Set[int] = set(donate or ())
        self.static: Set[int] = set(static or ())
        self.line = line


def _collect(tree: ast.AST):
    """(local defs by name, jit calls, wrapper registry by final name)."""
    defs: Dict[str, List[ast.AST]] = {}
    jit_calls: List[ast.Call] = []
    wrappers: Dict[str, _Wrapper] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
        elif isinstance(node, ast.Call) and _is_jit_call(node):
            jit_calls.append(node)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if not _is_jit_call(call):
                continue
            donate = _int_tuple(_kw(call, "donate_argnums"))
            static = _int_tuple(_kw(call, "static_argnums"))
            if not donate and not static:
                continue
            for target in node.targets:
                name = _dotted(target)
                if name:
                    wrappers[name.split(".")[-1]] = _Wrapper(
                        donate, static, node.lineno
                    )
    return defs, jit_calls, wrappers


def _assign_targets_text(stmt: ast.stmt) -> Set[str]:
    out: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, ast.Tuple):
            for elt in t.elts:
                name = _dotted(elt)
                if name:
                    out.add(name)
        else:
            name = _dotted(t)
            if name:
                out.add(name)
    return out


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    findings: List[Finding] = []
    defs, jit_calls, wrappers = _collect(tree)

    def emit(code: str, node: ast.AST, detail: str) -> None:
        summary, hint = RULES[code]
        findings.append(
            Finding(
                code, path, node.lineno, node.col_offset,
                "{} ({})".format(summary, detail), hint,
            )
        )

    # -- TPU201: jitted function closes over self --------------------------
    for call in jit_calls:
        if not call.args:
            continue
        fn_arg = call.args[0]
        if isinstance(fn_arg, ast.Lambda):
            if _references_self_freely(fn_arg):
                emit("TPU201", call, "lambda passed to jit reads self")
        elif isinstance(fn_arg, ast.Name):
            for fn in defs.get(fn_arg.id, []):
                if _references_self_freely(fn):
                    emit(
                        "TPU201", call,
                        "local function {!r} reads self from its closure".format(
                            fn_arg.id
                        ),
                    )
                    break

    # -- TPU202/TPU203: wrapper call-site discipline -----------------------
    # walk each function body in source order; nested defs are their own
    # scopes (they run later or never) and are analyzed separately
    fn_nodes = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in fn_nodes:
        stmts = sorted(_own_statements(fn), key=lambda s: s.lineno)
        # donated-expr text -> (line of donating stmt, wrapper name)
        killed: Dict[str, Tuple[int, str]] = {}
        for stmt in stmts:
            # runtime order within one statement: the RHS (reads, calls)
            # evaluates BEFORE the assignment binds — so 1) flag reads of
            # names donated by EARLIER statements (catches the
            # `self.k = f(self.k)`-after-donation case), 2) let this
            # statement's rebind resurrect, 3) register this statement's
            # donations (the same-statement rebind idiom stays exempt).
            for node in _walk_stmt(stmt):
                if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Load
                ):
                    text = _dotted(node)
                    if text in killed:
                        line, via = killed[text]
                        emit(
                            "TPU202", node,
                            "{!r} was donated to {} on line {}".format(
                                text, via, line
                            ),
                        )
                        del killed[text]
            assigned = _assign_targets_text(stmt)
            for name in list(killed):
                if name in assigned:
                    del killed[name]  # rebind: fresh buffer under the name
            for node in _walk_stmt(stmt):
                if not isinstance(node, ast.Call):
                    continue
                cal_name = _dotted(node.func)
                if cal_name is None:
                    continue
                wrapper = wrappers.get(cal_name.split(".")[-1])
                if wrapper is None:
                    continue
                # TPU203: unhashable literals at static positions
                for pos in wrapper.static:
                    if pos < len(node.args) and isinstance(
                        node.args[pos],
                        (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp, ast.GeneratorExp),
                    ):
                        emit(
                            "TPU203", node.args[pos],
                            "argument {} of {} is static".format(pos, cal_name),
                        )
                # TPU202: donated args must be rebound by this statement
                for pos in wrapper.donate:
                    if pos >= len(node.args):
                        continue
                    text = _dotted(node.args[pos])
                    if text is None:
                        continue  # temporaries can't be read again
                    if text in assigned:
                        continue  # x = f(x, ...) — the safe idiom
                    killed[text] = (node.lineno, cal_name)
    return findings


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _walk_stmt(stmt: ast.AST):
    """Yield the expression nodes belonging to exactly this statement: no
    nested scopes, and no nested STATEMENTS — those appear in
    _own_statements() in their own right, so descending here would visit
    (and flag) their calls twice."""
    stack = [stmt]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (_SCOPE_NODES) + (ast.stmt,)):
                continue
            stack.append(child)


def _own_statements(fn: ast.AST) -> List[ast.stmt]:
    """Every statement lexically inside ``fn`` but not in a nested scope."""
    out: List[ast.stmt] = []
    stack: List[ast.AST] = list(getattr(fn, "body", []))
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.stmt):
            out.append(cur)
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _SCOPE_NODES):
                continue
            stack.append(child)
    return out
