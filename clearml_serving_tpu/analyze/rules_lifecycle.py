"""TPU7xx — acquire/release ownership discipline over exception paths.

The engine tier moves KV ownership through five hand-audited protocols —
page refs/pins, slot quarantine, host-tier ids, promotion fences, transport
shipments — and every "leak-free" claim in docs/kv_tiering.md and
docs/disaggregation.md used to rest on manual review of the failure paths.
This family makes acquire/release pairing a machine-checked invariant class
(the seventh), the way TPU3xx did locks, TPU5xx thread affinity, and TPU6xx
the compile surface.

Per analyzed function the checker builds a statement-level CFG **with
exception edges**: every statement containing a call/await/assert may raise
into the enclosing handler chain (or out of the function), ``finally``
blocks are routed on every exit kind, and early ``return``/``raise`` paths
are explicit. Declared acquires are then walked path-by-path:

- **TPU701** — an acquire reaches a function exit (normal or raising) on
  some path without a matching release, drop-to-recompute handler, or
  ownership escape. The classic shape: ``pages = pool.allocate(...)`` then
  a fallible call before the ``pool.free`` — the exception path leaks.
- **TPU702** — a second matching release on a path where the obligation was
  already discharged (the double-free / use-after-free shape).
- **TPU703** — freshly minted pool page ids (``allocate_cache_pages``)
  published (``<node>.pages = ...``) without being dominated by the
  enqueue-before-publish fence call (``import_pages`` / ``promote_pages``)
  — the ``drop_ship_fence``/``drop_tier_fence`` defect class of
  llm/schedule_explorer.py, caught at lint time.
- **TPU704** — a transport shipment popped twice for the same key on one
  path, or its payload slabs used again after the ``store_shipped`` attach
  consumed them.

Protocols are declared next to the code via ``__acquires__`` class
annotations (sibling of ``__guarded_by__``/``__affine_to__``/
``__compile_keys__``)::

    class PagePool:
        __acquires__ = {
            "allocate": {"resource": "pages.slot",
                         "releases": ("free", "truncate"),
                         "drops": ("_free_slot_pages",)},
        }

mirrored in :data:`LIFECYCLE_REGISTRY` below (cross-module call sites are
checked even when the declaring file is not being analyzed; the
``__acquires__``/registry agreement is pinned by tests). Entries with
``"static": False`` are cross-function protocols by design (quarantine,
guided-grammar refs, long-lived cache refs): the static pass skips TPU701
for them and the runtime ownership ledger (llm/lifecycle_ledger.py,
``TPUSERVE_LEDGER=1|strict``) audits their pairing instead.

Blind spots (all deliberate, all fail-open, all covered by the ledger):
handles stored into attributes/containers, returned, or passed to any
non-release call count as ownership transfers; pairing across functions and
threads is invisible; aliased handles are not tracked. A silenced site
carries ``# tpuserve: ignore[TPU701] <why ownership moved>``.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from . import Finding, RULES, dotted_name as _dotted

# -- protocol registry --------------------------------------------------------
#
# acquire method name -> tuple of protocol entries. "releases" discharge the
# obligation; "drops" are registered drop-to-recompute handlers (discharge
# too, but documented as the degraded path); "static": False marks a
# protocol whose pairing is cross-function by design — the runtime ledger
# (llm/lifecycle_ledger.py) audits it, the static pass only uses the entry
# for TPU702 matching and the __acquires__ consistency test.
LIFECYCLE_REGISTRY: Dict[str, Tuple[Dict[str, Any], ...]] = {
    # PagePool slot pages (kv_cache.py): allocate/extend/map_shared give a
    # slot references; free/truncate drop them; the engine's deferred path
    # is _free_slot_pages (quarantine barrier). "receivers" filters the
    # obligation to receivers whose FINAL dotted component is listed (the
    # rules_locks mechanism): `allocate`/`extend` are generic names —
    # without the filter every list.extend in the tree would match.
    "allocate": (
        {"resource": "pages.slot", "releases": ("free", "truncate"),
         "drops": ("_free_slot_pages",), "static": True,
         "receivers": ("pool", "_pool", "page_pool", "pages")},
        # HostKVTier id allocator shares the method name; same release name
        {"resource": "host.pages", "releases": ("free",),
         "drops": (), "static": True,
         "receivers": ("host_tier", "_host", "tier", "host")},
    ),
    "extend": (
        {"resource": "pages.slot", "releases": ("free", "truncate"),
         "drops": ("_free_slot_pages",), "static": True,
         "receivers": ("pool", "_pool", "page_pool")},
    ),
    "map_shared": (
        {"resource": "pages.slot", "releases": ("free",),
         "drops": ("_free_slot_pages",), "static": True,
         "receivers": ("pool", "_pool", "page_pool")},
    ),
    # fresh cache-owned page mints (promotion / shipment import targets):
    # the caller must attach them to cache nodes or unref on failure —
    # and the publish is fence-ordered (TPU703)
    "allocate_cache_pages": (
        {"resource": "pages.ref", "releases": ("unref_pages",),
         "drops": (), "static": True, "mint": True},
    ),
    # long-lived radix-cache references: acquired at store, released at
    # node drop — cross-function by design, ledger-audited
    "ref_pages": (
        {"resource": "pages.ref", "releases": ("unref_pages",),
         "drops": (), "static": False},
    ),
    # transient admission pins (sanitizer-attributed separately)
    "pin_pages": (
        {"resource": "pages.pin", "releases": ("unpin_pages",),
         "drops": (), "static": True},
    ),
    # prefix-cache lookup hits: pinned on the caller's behalf; release()
    # (or the engine's _release_prefix_hit) must run on every admission
    # exit; uncount_hit is the recompute-fallback bookkeeping
    "lookup_pages": (
        {"resource": "prefix.hit",
         "releases": ("release", "_release_prefix_hit"),
         "drops": ("uncount_hit",), "static": True},
    ),
    # preemption resume pins (docs/slo_scheduling.md)
    "pin_run": (
        {"resource": "prefix.resume_pin",
         "releases": ("unpin_run", "_release_resume_pin"),
         "drops": (), "static": True},
    ),
    # engine slot quarantine (docs/pipelined_decode.md): acquired at a
    # barriered free, released at the barrier retire — cross-function
    "_quarantine_slot": (
        {"resource": "slot.quarantine",
         "releases": ("_release_quarantine",),
         "drops": ("_discard_pipeline",), "static": False},
    ),
    # guided-grammar registry refs (llm/guided.py): taken at admission
    # compile, dropped at slot release / admission failure — cross-function
    "_ensure_grammar": (
        {"resource": "guided.ref",
         "releases": ("_deref_guided_key", "_deref_guided_request",
                      "_release_guided"),
         "drops": (), "static": False},
    ),
    # KV-transport shipments (llm/kv_transport.py): sent slabs live in the
    # receive mailbox until the consume-once recv pops them (or capacity
    # eviction drops the oldest) — cross-process pairing, ledger-audited;
    # the static half of the shipment contract is TPU704
    "send": (
        {"resource": "transport.shipment",
         "releases": ("recv", "_drop_oldest"),
         "drops": (), "static": False,
         "receivers": ("transport", "endpoint", "_transport",
                       "_kv_transport", "ep")},
    ),
    # socket KV-wire peer connections (llm/kv_wire.py): cached per
    # destination by the sender, dropped on any wire failure or close()
    # — cross-function by design, ledger-audited
    "_connect": (
        {"resource": "transport.wire.conn",
         "releases": ("_drop_conn", "_close_conn", "close"),
         "drops": (), "static": False,
         "receivers": ("transport", "endpoint", "_transport",
                       "_kv_transport", "ep", "self")},
    ),
    # process-replica worker subprocesses (serving/process_replica.py):
    # spawned by the supervisor, reaped on stop or crash-restart —
    # cross-function by design, ledger-audited
    "_spawn": (
        {"resource": "replica.worker_proc",
         "releases": ("_reap", "stop"),
         "drops": (), "static": False,
         "receivers": ("self", "replica", "supervisor")},
    ),
}

# TPU703: the enqueue-before-publish fence protocol. Minted page ids
# (acquire methods flagged "mint" above) must flow through one of these
# calls before any publish-attribute assignment makes them visible.
FENCE_CALLS: FrozenSet[str] = frozenset({
    "import_pages", "promote_pages", "_upload_pages",
})
FENCE_PUBLISH_ATTRS: FrozenSet[str] = frozenset({"pages"})

# TPU704: consume-once transport pops. Receiver-basename filtered (like
# rules_locks' registry) so unrelated ``recv`` methods never match.
RECV_RECEIVERS: Tuple[str, ...] = (
    "transport", "endpoint", "_transport", "_kv_transport", "ep",
)
ATTACH_CALLS: FrozenSet[str] = frozenset({"store_shipped"})

_EXIT_OK = -1
_EXIT_RAISE = -2

# obligation walk state: _HELD, or the node id of the release that first
# discharged the obligation on this path (so a loop re-visiting its own
# release is never a double-free, while a DIFFERENT second release is)
_HELD = -1


def file_declarations(tree: ast.AST) -> Dict[str, Tuple[Dict[str, Any], ...]]:
    """``__acquires__`` class declarations in the analyzed file, normalized
    to the registry entry shape. A declaration at the definition site is
    merged with (not replacing) the project registry."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "__acquires__"
                for t in stmt.targets
            ):
                continue
            try:
                decl = ast.literal_eval(stmt.value)
            except (ValueError, SyntaxError):
                continue
            if not isinstance(decl, dict):
                continue
            for method, entry in decl.items():
                if not isinstance(entry, dict):
                    continue
                normalized = {
                    "resource": str(entry.get("resource", "?")),
                    "releases": tuple(entry.get("releases", ())),
                    "drops": tuple(entry.get("drops", ())),
                    "static": bool(entry.get("static", True)),
                }
                if entry.get("mint"):
                    normalized["mint"] = True
                if "receivers" in entry:
                    normalized["receivers"] = tuple(entry["receivers"])
                out.setdefault(str(method), []).append(normalized)
    return {m: tuple(v) for m, v in out.items()}


def merged_registry(tree: ast.AST) -> Dict[str, Tuple[Dict[str, Any], ...]]:
    registry = {m: tuple(v) for m, v in LIFECYCLE_REGISTRY.items()}
    for method, entries in file_declarations(tree).items():
        have = list(registry.get(method, ()))
        for entry in entries:
            if not any(
                e["resource"] == entry["resource"]
                and set(entry["releases"]) <= set(e["releases"])
                for e in have
            ):
                have.append(entry)
        registry[method] = tuple(have)
    return registry


# -- CFG ----------------------------------------------------------------------


class _CFG:
    """Statement-level control-flow graph of one function body.

    Nodes are integers indexing ``stmts`` (the AST fragment whose events the
    node carries; None = synthetic join). ``nsucc`` are normal-flow edges;
    ``esucc`` are exception edges (taken when the node's evaluation raises —
    the node's own effects are NOT applied on them, except releases, which
    are assumed to take effect before any raise they trigger).
    ``branch[n] = (test_expr, then_heads, else_heads_or_None)`` annotates
    condition joins so the obligation walk can understand ``if handle is
    None:`` vacuous-branch idioms (``None`` else-heads = no orelse: the
    else path is every successor outside ``then_heads``).
    """

    def __init__(self) -> None:
        self.stmts: List[Optional[ast.AST]] = []
        self.nsucc: Dict[int, Set[int]] = {}
        self.esucc: Dict[int, Set[int]] = {}
        self.branch: Dict[
            int, Tuple[ast.AST, Set[int], Optional[Set[int]]]
        ] = {}
        # loop join -> (first, last+1) node-id range of the loop body: a
        # release inside the body discharges at the join (iterating the
        # collection that holds the handles IS the release; zero
        # iterations mean nothing was held)
        self.loop_body: Dict[int, Tuple[int, int]] = {}

    def node(self, stmt: Optional[ast.AST]) -> int:
        nid = len(self.stmts)
        self.stmts.append(stmt)
        self.nsucc[nid] = set()
        self.esucc[nid] = set()
        return nid


def _walk_skip_nested(root: ast.AST):
    """ast.walk, but never descends into nested function/lambda bodies —
    their statements run later, under their own CFG."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


# builtins that cannot realistically raise on the engine's data (calling
# them does not open an exception edge; anything else that LOOKS like a
# call does)
_SAFE_CALLS = frozenset({
    "len", "int", "float", "str", "bool", "list", "dict", "tuple", "set",
    "frozenset", "range", "sorted", "reversed", "min", "max", "sum", "abs",
    "id", "repr", "isinstance", "enumerate", "zip", "print", "getattr",
})
# container mutators that cannot realistically raise either — plus the
# ownership ledger's own instrumentation surface (llm/lifecycle_ledger.py:
# owner() yields even when disarmed, request_tag() is a format call); the
# leak net must not flag the paths its OWN bookkeeping wraps
_SAFE_METHODS = frozenset({
    "append", "appendleft", "add", "discard", "clear",
    "owner", "request_tag",
})


def _may_raise(stmt: ast.AST) -> bool:
    """Statements containing a call/await/assert can raise mid-evaluation.
    (Pure name/constant/subscript statements — and a short list of
    no-raise builtins/container mutators — are treated as non-raising: a
    lint-level CFG, not a soundness proof.)"""
    for node in _walk_skip_nested(stmt):
        if isinstance(node, (ast.Await, ast.Assert)):
            return True
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _SAFE_CALLS
            ):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SAFE_METHODS
            ):
                continue
            return True
    return False


class _Builder:
    """Builds a _CFG for one function. ``finally`` blocks are built once and
    their exits fan out to the union of every continuation routed through
    them (after-try, propagating raise, return, break/continue) — a merged
    approximation that only ever ADDS paths, so the leak walk stays
    conservative in the safe direction."""

    def __init__(self, cfg: _CFG):
        self.cfg = cfg
        # innermost-first stack of (finally_entry, extra_continuations)
        self.finallies: List[Tuple[int, Set[int]]] = []
        # loop stack: (continue_target, after_loop_join)
        self.loops: List[Tuple[int, int]] = []
        self.raise_targets: List[int] = [_EXIT_RAISE]

    # every statement that can raise gets edges to the current raise targets
    def _wire_raise(self, nid: int, stmt: ast.AST) -> None:
        if _may_raise(stmt):
            self.cfg.esucc[nid] |= set(self.raise_targets)

    def _edge(self, preds: Sequence[int], nid: int) -> None:
        for p in preds:
            self.cfg.nsucc[p].add(nid)

    def _through_finally(self, target: int) -> int:
        """Route an abrupt exit (return/break/continue/raise-to-outer)
        through the innermost active finally, recording the ultimate
        target as one of that finally's continuations."""
        if not self.finallies:
            return target
        entry, extras = self.finallies[-1]
        extras.add(target)
        return entry

    def seq(self, stmts: Sequence[ast.AST], preds: List[int]) -> List[int]:
        """Wire ``stmts`` after ``preds``; returns the exits that flow to
        whatever comes next."""
        for stmt in stmts:
            if not preds:
                # unreachable tail (after return/raise): skip building it —
                # dead code cannot leak
                break
            preds = self._stmt(stmt, preds)
        return preds

    def _stmt(self, stmt: ast.AST, preds: List[int]) -> List[int]:
        cfg = self.cfg
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            nid = cfg.node(None)  # definition runs; body analyzed separately
            self._edge(preds, nid)
            return [nid]
        if isinstance(stmt, ast.Return):
            nid = cfg.node(stmt)
            self._edge(preds, nid)
            self._wire_raise(nid, stmt)
            cfg.nsucc[nid].add(self._through_finally(_EXIT_OK))
            return []
        if isinstance(stmt, ast.Raise):
            nid = cfg.node(stmt)
            self._edge(preds, nid)
            # a bare or explicit raise goes to the innermost handler chain
            for target in self.raise_targets:
                cfg.nsucc[nid].add(target)
            return []
        if isinstance(stmt, ast.Break):
            nid = cfg.node(stmt)
            self._edge(preds, nid)
            if self.loops:
                _, after = self.loops[-1]
                cfg.nsucc[nid].add(self._through_finally(after))
            return []
        if isinstance(stmt, ast.Continue):
            nid = cfg.node(stmt)
            self._edge(preds, nid)
            if self.loops:
                cont, _ = self.loops[-1]
                cfg.nsucc[nid].add(self._through_finally(cont))
            return []
        if isinstance(stmt, ast.If):
            join = cfg.node(stmt.test)
            self._edge(preds, join)
            self._wire_raise(join, stmt.test)
            then_exits = self.seq(stmt.body, [join])
            then_heads = set(cfg.nsucc[join])
            if stmt.orelse:
                else_exits = self.seq(stmt.orelse, [join])
                else_heads: Optional[Set[int]] = (
                    set(cfg.nsucc[join]) - then_heads
                )
            else:
                else_exits = [join]  # falls through: join itself is an exit
                else_heads = None
            cfg.branch[join] = (stmt.test, then_heads, else_heads)
            return then_exits + else_exits
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                # a while test re-evaluates every iteration: raise edges
                # belong on the join
                test: Optional[ast.AST] = stmt.test
                join = cfg.node(test)
                self._edge(preds, join)
                self._wire_raise(join, test)
            else:
                # a for iterator evaluates ONCE, before the loop: give it
                # its own node so its raise edge is not replayed per
                # iteration
                it = cfg.node(stmt.iter)
                self._edge(preds, it)
                self._wire_raise(it, stmt.iter)
                join = cfg.node(None)
                self._edge([it], join)
            after = cfg.node(None)  # break target / loop exit join
            self.loops.append((join, after))
            body_start = len(cfg.stmts)
            body_exits = self.seq(stmt.body, [join])
            cfg.loop_body[join] = (body_start, len(cfg.stmts))
            self.loops.pop()
            self._edge(body_exits, join)  # back edge
            exits = [join]
            if stmt.orelse:
                exits = self.seq(stmt.orelse, exits)
            exits = exits + [after]
            return exits
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # the header node carries only the context expressions — body
            # statements get their own nodes (events must not double-count)
            header = ast.copy_location(
                ast.Tuple(
                    elts=[item.context_expr for item in stmt.items],
                    ctx=ast.Load(),
                ),
                stmt,
            )
            nid = cfg.node(header)
            self._edge(preds, nid)
            self._wire_raise(nid, header)
            return self.seq(stmt.body, [nid])
        if isinstance(stmt, ast.Try):
            return self._try(stmt, preds)
        # simple statement (Assign/Expr/AugAssign/Delete/Assert/...)
        nid = cfg.node(stmt)
        self._edge(preds, nid)
        self._wire_raise(nid, stmt)
        if isinstance(stmt, ast.Assert):
            # a failing assert raises; already wired by _wire_raise
            pass
        return [nid]

    def _try(self, stmt: ast.Try, preds: List[int]) -> List[int]:
        cfg = self.cfg
        outer_raise = list(self.raise_targets)
        f_entry: Optional[int] = None
        f_extras: Set[int] = set()
        if stmt.finalbody:
            f_entry = cfg.node(None)
            self.finallies.append((f_entry, f_extras))
        # handlers first, so the body knows where its exceptions land
        handler_entries: List[int] = []
        handler_exits: List[int] = []
        for handler in stmt.handlers:
            h_entry = cfg.node(None)
            handler_entries.append(h_entry)
            # exceptions inside a handler propagate outward (through the
            # finally when present)
            saved = self.raise_targets
            self.raise_targets = (
                [f_entry] if f_entry is not None else outer_raise
            )
            if f_entry is not None:
                f_extras.update(outer_raise)
            handler_exits += self.seq(handler.body, [h_entry])
            self.raise_targets = saved
        # the body raises into the handlers — or past them all (no handler
        # matched) through the finally to the outer chain. A catch-all
        # handler (`except:` / `except Exception` / `except BaseException`)
        # closes the escape: every exception lands in a handler.
        catch_all = any(
            h.type is None
            or _dotted(h.type) in ("Exception", "BaseException")
            for h in stmt.handlers
        )
        body_raise: List[int] = list(handler_entries)
        if f_entry is not None:
            body_raise.append(f_entry)
            f_extras.update(outer_raise)
        elif not handler_entries:
            body_raise = outer_raise
        elif not catch_all:
            body_raise += outer_raise  # unmatched exception type
        saved = self.raise_targets
        self.raise_targets = body_raise
        body_exits = self.seq(stmt.body, preds)
        self.raise_targets = saved
        if stmt.orelse:
            body_exits = self.seq(stmt.orelse, body_exits)
        exits = body_exits + handler_exits
        if f_entry is not None:
            self.finallies.pop()
            self._edge(exits, f_entry)
            f_exits = self.seq(stmt.finalbody, [f_entry])
            after = cfg.node(None)
            self._edge(f_exits, after)
            # merged continuations: everything routed through this finally
            for target in f_extras:
                for fx in f_exits:
                    cfg.nsucc[fx].add(target)
            return [after]
        return exits


def build_cfg(fn: ast.AST) -> Tuple[_CFG, int]:
    """(cfg, entry node id) for a function's body."""
    cfg = _CFG()
    entry = cfg.node(None)
    builder = _Builder(cfg)
    exits = builder.seq(list(getattr(fn, "body", [])), [entry])
    for nid in exits:
        cfg.nsucc[nid].add(_EXIT_OK)
    return cfg, entry


# -- event extraction ---------------------------------------------------------


def _calls_in(stmt: ast.AST) -> List[ast.Call]:
    return [
        node for node in _walk_skip_nested(stmt)
        if isinstance(node, ast.Call)
    ]


def _arg_texts(call: ast.Call) -> List[str]:
    out = []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        text = _dotted(arg)
        if text:
            out.append(text)
        elif isinstance(arg, ast.Constant):
            # literal args distinguish `free(0)` from `free(1)` when the
            # release matcher compares argument overlap
            out.append(repr(arg.value))
    return out


def _names_in(expr: ast.AST) -> Set[str]:
    return {
        n.id for n in _walk_skip_nested(expr)
        if isinstance(n, ast.Name)
    }


class _Obligation:
    __slots__ = ("method", "entries", "var", "recv", "args", "node",
                 "line", "col", "releases", "drops")

    def __init__(self, method: str, entries, var: Optional[str],
                 recv: Optional[str], args: List[str], node: int,
                 line: int, col: int):
        self.method = method
        self.entries = entries
        self.var = var
        self.recv = recv
        self.args = args
        self.node = node
        self.line = line
        self.col = col
        self.releases = frozenset(
            name for e in entries for name in e["releases"]
        )
        self.drops = frozenset(name for e in entries for name in e["drops"])

    @property
    def resource(self) -> str:
        return "|".join(sorted({e["resource"] for e in self.entries}))

    @property
    def static(self) -> bool:
        return any(e.get("static", True) for e in self.entries)


def _find_obligations(cfg: _CFG, registry) -> List[_Obligation]:
    out: List[_Obligation] = []
    for nid, stmt in enumerate(cfg.stmts):
        if stmt is None:
            continue
        var: Optional[str] = None
        call: Optional[ast.Call] = None
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name):
                var = stmt.targets[0].id
            else:
                continue  # escape at birth (attribute/tuple target)
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
        if call is None or not isinstance(call.func, ast.Attribute):
            continue
        method = call.func.attr
        entries = registry.get(method)
        if not entries:
            continue
        recv = _dotted(call.func.value)
        base = recv.split(".")[-1] if recv else None
        matched = tuple(
            e for e in entries
            if "receivers" not in e or (
                base is not None and base in e["receivers"]
            )
        )
        if not matched:
            continue
        out.append(_Obligation(
            method, matched, var, recv, _arg_texts(call), nid,
            stmt.lineno, stmt.col_offset,
        ))
    return out


def _release_matches(ob: _Obligation, call: ast.Call,
                     names: FrozenSet[str]) -> bool:
    """Does ``call`` discharge obligation ``ob``? (``names`` = releases or
    drops to consider.)"""
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in names:
        return False
    recv = _dotted(call.func.value)
    args = _arg_texts(call)
    if ob.var is not None:
        if ob.var in args:
            return True
        if recv == ob.var:  # handle.release() style
            return True
    if recv is not None and ob.recv is not None:
        recv_match = (
            recv == ob.recv
            or recv.split(".")[-1] == ob.recv.split(".")[-1]
        )
        if recv_match:
            if not args or not ob.args:
                return True
            return bool(set(args) & set(ob.args))
    return False


def _mentions_var(stmt: ast.AST, var: str) -> bool:
    for node in _walk_skip_nested(stmt):
        if isinstance(node, ast.Name) and node.id == var:
            return True
    return False


def _escapes(stmt: ast.AST, ob: _Obligation) -> bool:
    """Ownership leaves this function's hands (fail-open: the ledger covers
    what the static pass can no longer see)."""
    if ob.var is None:
        return False
    var = ob.var
    if isinstance(stmt, (ast.Return, ast.Raise)):
        return _mentions_var(stmt, var)
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, (ast.Yield,
                                                              ast.YieldFrom)):
        return _mentions_var(stmt, var)
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (
            list(stmt.targets) if isinstance(stmt, ast.Assign)
            else [stmt.target]
        )
        value = stmt.value
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                if value is not None and _mentions_var(value, var):
                    return True  # stashed into an attribute/container
            if isinstance(t, ast.Name) and t.id == var:
                return True  # rebound: the old handle is someone else's now
            if isinstance(t, ast.Tuple) and any(
                isinstance(e, ast.Name) and e.id == var for e in t.elts
            ):
                return True
    # handed to any call that is not a matching release (checked first by
    # the walker): conservative ownership transfer
    for call in _calls_in(stmt):
        if var in _arg_texts(call):
            return True
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if _mentions_var(arg, var):
                return True
    return False


def _none_branch(test: ast.AST, var: str) -> Optional[str]:
    """Which If branch means ``var`` is None/falsy: "then", "else", or None
    when the test says nothing about the handle."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left_is_var = isinstance(test.left, ast.Name) and test.left.id == var
        comp = test.comparators[0]
        comp_none = isinstance(comp, ast.Constant) and comp.value is None
        if left_is_var and comp_none:
            if isinstance(test.ops[0], ast.Is):
                return "then"
            if isinstance(test.ops[0], ast.IsNot):
                return "else"
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = test.operand
        if isinstance(inner, ast.Name) and inner.id == var:
            return "then"
    if isinstance(test, ast.Name) and test.id == var:
        return "else"
    return None


# -- rule walks ---------------------------------------------------------------


def _walk_obligation(cfg: _CFG, ob: _Obligation, path: str,
                     findings: List[Finding]) -> None:
    reported: Set[Tuple[str, int]] = set()

    def emit(code: str, line: int, col: int, detail: str) -> None:
        if (code, line) in reported:
            return
        reported.add((code, line))
        summary, hint = RULES[code]
        findings.append(Finding(
            code, path, line, col, "{} ({})".format(summary, detail), hint,
        ))

    stack: List[Tuple[int, int]] = [
        (succ, _HELD) for succ in cfg.nsucc.get(ob.node, ())
    ]
    seen: Set[Tuple[int, int]] = set()
    while stack:
        nid, state = stack.pop()
        if (nid, state) in seen:
            continue
        seen.add((nid, state))
        if nid == _EXIT_OK or nid == _EXIT_RAISE:
            if state == _HELD and ob.static:
                kind = (
                    "a raising path" if nid == _EXIT_RAISE
                    else "a normal path"
                )
                emit(
                    "TPU701", ob.line, ob.col,
                    "{} from `{}` acquired here leaks on {}: no matching "
                    "{} reaches the function exit".format(
                        ob.resource, ob.method, kind,
                        "/".join(sorted(ob.releases | ob.drops)) or "release",
                    ),
                )
            continue
        if nid == ob.node:
            continue  # looped back to the acquire: a fresh obligation
        stmt = cfg.stmts[nid]
        next_state = state
        discharged = False

        def _same_release(at: int, here: int) -> bool:
            """True when the path's recorded release covers this node: the
            same statement, or a loop join whose body this release sits in
            (the join discharged on the body's behalf)."""
            if at == here:
                return True
            span = cfg.loop_body.get(at)
            return span is not None and span[0] <= here < span[1]

        if stmt is not None:
            released_here = False
            for call in _calls_in(stmt):
                if _release_matches(ob, call, ob.releases):
                    released_here = True
                    if state != _HELD and not _same_release(state, nid):
                        emit(
                            "TPU702", stmt.lineno, stmt.col_offset,
                            "second release of {} from the `{}` at line {} "
                            "on one path".format(
                                ob.resource, ob.method, ob.line
                            ),
                        )
                    break
                if _release_matches(ob, call, ob.drops):
                    # drop-to-recompute handlers discharge but are
                    # idempotent bookkeeping: never a TPU702
                    released_here = True
                    break
            if released_here:
                next_state = state if state != _HELD else nid
            elif state == _HELD and _escapes(stmt, ob):
                discharged = True
        if (
            state == _HELD
            and not discharged
            and next_state == _HELD
            and nid in cfg.loop_body
        ):
            # a loop whose body releases the obligation discharges at the
            # join: the collection iterated holds the handles, and a
            # zero-iteration pass means nothing was held
            lo, hi = cfg.loop_body[nid]
            for body_nid in range(lo, hi):
                body_stmt = cfg.stmts[body_nid]
                if body_stmt is None:
                    continue
                if any(
                    _release_matches(ob, call, ob.releases)
                    or _release_matches(ob, call, ob.drops)
                    for call in _calls_in(body_stmt)
                ):
                    next_state = nid
                    break
        if discharged:
            continue
        # branch joins understand `if handle is None:`-style vacuity: the
        # branch where the handle is None acquired nothing, so the
        # obligation is vacuous along it
        branch = cfg.branch.get(nid)
        if branch is not None and ob.var is not None and state == _HELD:
            test, then_heads, else_heads = branch
            vacuous = _none_branch(test, ob.var)
            if vacuous is not None:
                if vacuous == "then":
                    dead = then_heads
                elif else_heads is not None:
                    dead = else_heads
                else:  # no orelse: the else path is everything outside then
                    dead = set(cfg.nsucc[nid]) - then_heads
                for succ in cfg.nsucc[nid]:
                    if succ not in dead:
                        stack.append((succ, next_state))
                for succ in cfg.esucc[nid]:
                    stack.append((succ, next_state))
                continue
        for succ in cfg.nsucc[nid]:
            stack.append((succ, next_state))
        for succ in cfg.esucc[nid]:
            # the raise interrupts this statement: releases still count
            # (assumed ordered before anything that can raise); an escape
            # already stopped this path above
            stack.append((succ, next_state))


def _walk_fence(cfg: _CFG, ob: _Obligation, path: str,
                findings: List[Finding]) -> None:
    """TPU703: minted page ids must pass an enqueue fence before publish."""
    if ob.var is None:
        return
    # flow-insensitive taint: names derived from the minted ids
    tainted: Set[str] = {ob.var}
    changed = True
    while changed:
        changed = False
        for stmt in cfg.stmts:
            if not isinstance(stmt, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id not in tainted
                for t in stmt.targets
            ):
                continue
            if _names_in(stmt.value) & tainted:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        if t.id not in tainted:
                            tainted.add(t.id)
                            changed = True
    reported: Set[int] = set()
    stack: List[Tuple[int, bool]] = [
        (succ, False) for succ in cfg.nsucc.get(ob.node, ())
    ]
    seen: Set[Tuple[int, bool]] = set()
    while stack:
        nid, fenced = stack.pop()
        if (nid, fenced) in seen or nid in (_EXIT_OK, _EXIT_RAISE):
            continue
        seen.add((nid, fenced))
        if nid == ob.node:
            continue
        stmt = cfg.stmts[nid]
        stop = False
        if stmt is not None:
            for call in _calls_in(stmt):
                if not isinstance(call.func, ast.Attribute):
                    continue
                attr = call.func.attr
                texts = set(_arg_texts(call))
                if attr in FENCE_CALLS and (texts & tainted or any(
                    _names_in(a) & tainted
                    for a in list(call.args)
                    + [kw.value for kw in call.keywords]
                )):
                    fenced = True
                if attr in ("unref_pages", "free") and texts & tainted:
                    stop = True  # failure path returned the mint
            if not fenced and isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr in FENCE_PUBLISH_ATTRS
                        and _names_in(stmt.value) & tainted
                        and stmt.lineno not in reported
                    ):
                        reported.add(stmt.lineno)
                        summary, hint = RULES["TPU703"]
                        findings.append(Finding(
                            "TPU703", path, stmt.lineno, stmt.col_offset,
                            "{} (page ids minted at line {} published via "
                            "`.{} =` before any {} fence enqueued their "
                            "payload)".format(
                                summary, ob.line, t.attr,
                                "/".join(sorted(FENCE_CALLS)),
                            ),
                            hint,
                        ))
        if stop:
            continue
        for succ in cfg.nsucc[nid] | cfg.esucc[nid]:
            stack.append((succ, fenced))


def _walk_recv(cfg: _CFG, nid: int, stmt: ast.Assign, path: str,
               findings: List[Finding]) -> None:
    """TPU704: consume-once transport pops and attach-consumed payloads."""
    call = stmt.value
    var = stmt.targets[0].id  # validated by caller
    recv = _dotted(call.func.value)
    sig = (recv, call.func.attr, tuple(_arg_texts(call)))
    reported: Set[int] = set()

    def emit(line: int, col: int, detail: str) -> None:
        if line in reported:
            return
        reported.add(line)
        summary, hint = RULES["TPU704"]
        findings.append(Finding(
            "TPU704", path, line, col, "{} ({})".format(summary, detail),
            hint,
        ))

    HELD, ATTACHED = 0, 1
    stack: List[Tuple[int, int]] = [
        (succ, HELD) for succ in cfg.nsucc.get(nid, ())
    ]
    seen: Set[Tuple[int, int]] = set()
    while stack:
        cur, state = stack.pop()
        if (cur, state) in seen or cur in (_EXIT_OK, _EXIT_RAISE):
            continue
        seen.add((cur, state))
        if cur == nid:
            continue
        cstmt = cfg.stmts[cur]
        next_state = state
        if cstmt is not None:
            attached_here = False
            for c in _calls_in(cstmt):
                if not isinstance(c.func, ast.Attribute):
                    continue
                texts = _arg_texts(c)
                if c.func.attr == "recv" and (
                    _dotted(c.func.value), c.func.attr, tuple(texts)
                ) == sig:
                    emit(
                        cstmt.lineno, cstmt.col_offset,
                        "shipment for the same key popped again on a path "
                        "that already consumed it at line {}".format(
                            stmt.lineno
                        ),
                    )
                if c.func.attr in ATTACH_CALLS and var in texts:
                    attached_here = True
            if attached_here:
                next_state = ATTACHED
            elif state == ATTACHED and _mentions_var(cstmt, var):
                emit(
                    cstmt.lineno, cstmt.col_offset,
                    "shipment `{}` used after its store_shipped attach "
                    "consumed the payload slabs".format(var),
                )
            # rebinding the handle starts a fresh shipment
            if isinstance(cstmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == var
                for t in cstmt.targets
            ):
                continue
        for succ in cfg.nsucc[cur] | cfg.esucc[cur]:
            stack.append((succ, next_state))


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    registry = merged_registry(tree)
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cfg, _entry = build_cfg(fn)
        for ob in _find_obligations(cfg, registry):
            _walk_obligation(cfg, ob, path, findings)
            if any(e.get("mint") for e in ob.entries):
                _walk_fence(cfg, ob, path, findings)
        # TPU704 obligations: `v = <transport-ish>.recv(...)`
        for nid, stmt in enumerate(cfg.stmts):
            if not (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == "recv"
            ):
                continue
            recv = _dotted(stmt.value.func.value)
            if recv is None or recv.split(".")[-1] not in RECV_RECEIVERS:
                continue
            _walk_recv(cfg, nid, stmt, path, findings)
    return findings
