"""TPU301 — lock discipline over KV bookkeeping state.

PagePool refcounts, per-slot page tables, pending copy-on-write pairs, and
radix-cache tree state are mutated concurrently by the engine loop thread,
decode worker threads, and admission workers. Every one of those structures
is guarded by a declared lock; a mutation that slips outside the lock is a
refcount-corruption bug that only reproduces under load (the exact class of
failure the runtime KV sanitizer — llm/kv_sanitizer.py — exists to catch
after the fact; this rule catches it before merge).

The guarded-attribute registry comes from two sources, merged:

1. ``__guarded_by__`` class declarations in the analyzed file::

       class PagePool:
           __guarded_by__ = {"_lock": ("_free", "_refs", ...)}

2. the project-level table below (cross-module mutations — e.g. engine.py
   poking ``pool._refs`` — are checked even though the declaration lives in
   kv_cache.py, which the analyzer may not be looking at right now).

A mutation of ``<recv>.<attr>`` (assignment, augmented assignment, ``del``,
or a mutating method call like ``.append``/``.pop``) must sit lexically
inside ``with <recv>.<lock>:``. ``__init__`` bodies are exempt (the object
is not shared yet). Helpers called with the lock already held annotate their
``def`` line with ``# tpuserve: ignore[TPU301] lock held by caller``.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Tuple

from . import Finding, RULES, dotted_name as _dotted

# attr name -> (lock attr name, receiver-basename filter or None).
# Project-wide registry: kv_cache.PagePool and PagedKVCache,
# prefix_cache.RadixPrefixCache. Keep in sync with the __guarded_by__
# declarations at the definition sites (test_analyze checks the two agree).
# A None filter matches any receiver (the attr names are distinctive); a
# tuple restricts the rule to receivers whose FINAL dotted component is
# listed — used for generic names like `k`/`v`, where matching every class's
# `self.k` tree-wide would drown real findings in false positives.
PROJECT_REGISTRY: Dict[str, Tuple[str, Optional[Tuple[str, ...]]]] = {
    # PagePool bookkeeping (kv_cache.py)
    "_free": ("_lock", None),
    "_slot_pages": ("_lock", None),
    "_slot_len": ("_lock", None),
    "_refs": ("_lock", None),
    "_pending_cow": ("_lock", None),
    "_pins": ("_lock", None),
    # RadixPrefixCache tree state (prefix_cache.py)
    "_roots": ("_lock", None),
    "_leaf_nodes": ("_lock", None),
    "_n_nodes": ("_lock", None),
    "_clock": ("_lock", None),
    # host-RAM KV tier (docs/kv_tiering.md): the cache's resident frontier
    # + per-tier accounting, and the HostKVTier id allocator (kv_cache.py;
    # its "_free"/"_used" ride the existing "_free" entry and this one)
    "_frontier": ("_lock", None),
    "_n_resident": ("_lock", None),
    "_host_pages": ("_lock", ("self", "cache", "prefix", "_prefix")),
    "_host_bytes": ("_lock", None),
    "_used": ("_lock", ("self", "tier", "host_tier", "host")),
    # PagedKVCache pool handles: a donating dispatch invalidates the old
    # handle, so rebinds happen only under the dispatch lock. Receiver-
    # filtered to the engine's naming for the paged cache object; inside
    # kv_cache.py itself the class's own __guarded_by__ declaration (no
    # filter) takes precedence.
    "k": ("dispatch_lock", ("paged_cache", "cache", "paged_kv", "kv_cache")),
    "v": ("dispatch_lock", ("paged_cache", "cache", "paged_kv", "kv_cache")),
    # int8 paged KV scale pools (docs/paged_kv_quant.md): rebinds follow the
    # same donation discipline as the data pools
    "k_scale": (
        "dispatch_lock", ("paged_cache", "cache", "paged_kv", "kv_cache"),
    ),
    "v_scale": (
        "dispatch_lock", ("paged_cache", "cache", "paged_kv", "kv_cache"),
    ),
    # in-flight host->device promotion records (docs/kv_tiering.md):
    # appended at copy-enqueue (dispatch path), drained at retire reaps
    "_promotions": (
        "dispatch_lock", ("paged_cache", "cache", "paged_kv", "kv_cache"),
    ),
    # KV-transport receive-slab mailboxes (llm/kv_transport.py,
    # docs/disaggregation.md): senders on replica loop threads, receivers
    # on the group's receive worker
    "_slabs": ("_lock", None),
    "_slab_pages": ("_lock", None),
    "_ship_seq": ("_lock", None),
    # draft-ahead partial-frame assemblies (docs/spec_decode_trees.md):
    # unsealed frames accumulate under the same mailbox lock until the
    # sealing frame fuses them (fusion itself runs OUTSIDE the lock on a
    # popped list — only the map mutations are guarded)
    "_assemblies": ("_lock", None),
    # socket KV-wire backend (llm/kv_wire.py): the per-peer connection
    # cache is shared between the sender's loop thread and close()
    "_conns": ("_lock", ("self", "transport", "endpoint", "_kv_transport",
                         "ep")),
    # process-replica control plane (serving/process_replica.py): the
    # blocking sync channel is shared between the serving loop, to_thread
    # receive workers, and the Prometheus scrape thread
    "_sync_sock": ("_sync_lock", ("self", "proxy", "client", "_client",
                                  "engine")),
    # process-replica supervisor state (serving/process_replica.py): the
    # worker Popen handle and restart budget are rebound by both the
    # supervisor thread (crash/restart) and the serving loop (stop)
    "_proc": ("_lock", ("self", "replica")),
    "_restarts_left": ("_lock", ("self", "replica")),
    # SLO scheduler pending-queue state (engine._ClassedPendingQueue,
    # docs/slo_scheduling.md): per-class heaps + starvation counters
    "_heaps": ("_lock", None),
    "_starve": ("_lock", None),
    # replica-router shared maps (serving/replica_router.py,
    # docs/replication.md): route/event counters written on the serving
    # loop, read by the Prometheus scrape thread
    "_route_counts": ("_lock", ("self", "router", "_router")),
    "_router_events": ("_lock", ("self", "router", "_router")),
}

_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft", "popitem",
    "remove", "clear", "add", "discard", "update", "setdefault",
}


def _strip_subscripts(node: ast.AST) -> ast.AST:
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def _guarded_split(node: ast.AST, registry):
    """(recv_text, attr, lock_attr) when ``node`` is `<recv>.<guarded>` and
    the receiver passes the entry's basename filter."""
    node = _strip_subscripts(node)
    if not isinstance(node, ast.Attribute):
        return None
    attr = node.attr
    entry = registry.get(attr)
    if entry is None:
        return None
    lock, receivers = entry
    recv = _dotted(node.value)
    if recv is None:
        return None
    if receivers is not None and recv.split(".")[-1] not in receivers:
        return None
    return recv, attr, lock


def _file_declarations(tree: ast.AST):
    """Collect ``__guarded_by__`` class declarations: attr -> (lock, None).
    A declaration at the definition site applies to any receiver."""
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "__guarded_by__"
                for t in stmt.targets
            ):
                continue
            try:
                decl = ast.literal_eval(stmt.value)
            except (ValueError, SyntaxError):
                continue
            if not isinstance(decl, dict):
                continue
            for lock_attr, attrs in decl.items():
                for attr in attrs:
                    out[str(attr)] = (str(lock_attr), None)
    return out


class _LockVisitor:
    def __init__(self, path: str, registry):
        self.path = path
        self.registry = registry
        self.findings: List[Finding] = []

    def _emit(self, node: ast.AST, recv: str, attr: str, lock: str) -> None:
        summary, hint = RULES["TPU301"]
        self.findings.append(
            Finding(
                "TPU301", self.path, node.lineno, node.col_offset,
                "{} ({}.{} mutated outside `with {}.{}`)".format(
                    summary, recv, attr, recv, lock
                ),
                hint,
            )
        )

    def _check_mutation(self, target: ast.AST, node: ast.AST,
                        locks: FrozenSet[str]) -> None:
        hit = _guarded_split(target, self.registry)
        if hit is None:
            return
        recv, attr, lock = hit
        if "{}.{}".format(recv, lock) not in locks:
            self._emit(node, recv, attr, lock)

    def walk_function(self, fn: ast.AST) -> None:
        if getattr(fn, "name", "") == "__init__":
            return  # object under construction is not yet shared
        for stmt in getattr(fn, "body", []):
            self._walk(stmt, frozenset())

    def _walk(self, node: ast.AST, locks: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, possibly without the lock; check()
            # visits every def separately with a clean lock state
            return
        if isinstance(node, ast.Lambda):
            self._walk(node.body, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            held = set(locks)
            for item in node.items:
                text = _dotted(item.context_expr)
                if text:
                    held.add(text)
                elif isinstance(item.context_expr, ast.Call):
                    # with lock.acquire_timeout(...) style helpers: count the
                    # receiver chain as held
                    text = _dotted(item.context_expr.func)
                    if text and "." in text:
                        held.add(text.rsplit(".", 1)[0])
            for child in node.body:
                self._walk(child, frozenset(held))
            for item in node.items:
                self._walk(item.context_expr, locks)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                list(node.targets)
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Tuple):
                    for elt in t.elts:
                        self._check_mutation(elt, node, locks)
                else:
                    self._check_mutation(t, node, locks)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                self._check_mutation(t, node, locks)
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                self._check_mutation(node.func.value, node, locks)
        for child in ast.iter_child_nodes(node):
            self._walk(child, locks)


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    registry = dict(PROJECT_REGISTRY)
    registry.update(_file_declarations(tree))
    visitor = _LockVisitor(path, registry)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visitor.walk_function(node)
    return visitor.findings
