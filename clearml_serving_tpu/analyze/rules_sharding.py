"""TPU8xx: sharding / mesh discipline (docs/static_analysis.md).

The multi-process roadmap item turns every mesh-axis name, sharding
annotation, and host/device transfer into a distributed-correctness
contract: a typo'd axis in a ``PartitionSpec`` fails at trace time on
hardware we rarely reach, a host read of a sharded-global array deadlocks
(or reads one shard's garbage) the moment there is more than one process,
and a silent replicate-instead-of-shard fallback defeats TP memory scaling
without any error at all. These rules machine-check the protocol the
``parallel/`` package declares next to its code:

- TPU801 — mesh-axis closed world: every axis literal reaching a
  ``PartitionSpec``/``P(...)`` constructor (including local spec-forwarding
  helpers), a named collective (``psum``/``all_gather``/``ppermute``/...),
  or an ``axis_name=`` parameter default must appear in the axis registry
  ``parallel/mesh.py`` declares via its ``__mesh_axes__`` literal.
- TPU802 — sharding declarations: a class whose ``__compile_keys__``
  declares serve-path jit entries must also declare ``__shardings__``
  (operand family -> sharding-builder dotted name), every named builder
  must exist in the ``parallel/sharding.py`` ``__sharding_builders__``
  registry, and every registered builder must be defined in that module.
- TPU803 — multihost-unsafe host access: ``jax.device_get`` /
  ``np.asarray`` / ``.tolist()`` / ``int()``-style host materialization of
  a value tainted as sharded-global (produced by ``shard_params``,
  ``device_put``-with-sharding, ``with_sharding_constraint``, or a global
  collective like ``broadcast_one_to_all``), outside a readback that goes
  through ``.addressable_shards`` — annotate declared-replicated reads.
- TPU804 — silent replication fallback: inside a declared sharding
  builder, a path that returns a replicated spec (``None`` / bare ``P()``)
  from a function that also returns real axis names must be annotated with
  the reason, so "misaligned projections replicate instead" stops being
  something only a comment knows.

Like every family here: stdlib ``ast`` only, no jax import, no import of
the code under analysis. Cross-module registries are parsed from source
(the same pattern rules_errors uses for ``faults.KNOWN_POINTS``), with
in-module literal fallbacks kept in sync by tests/test_analyze_sharding.py.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import Finding, dotted_name

# -- cross-module registries (parsed from source; literal fallbacks) ----------

# mirror of parallel/mesh.py __mesh_axes__ (tests pin the agreement both ways)
MESH_AXES: FrozenSet[str] = frozenset({"dp", "tp", "sp", "ep", "pp"})

# mirror of parallel/sharding.py __sharding_builders__ (tests pin both ways)
SHARDING_REGISTRY: Tuple[str, ...] = (
    "llama_param_sharding",
    "llama_cache_sharding",
    "llama_quantized_param_sharding",
    "shard_params",
    "replicated",
    "batch_sharding",
)

_axes_cache: Dict[str, FrozenSet[str]] = {}
_builders_cache: Dict[str, Tuple[str, ...]] = {}


def _find_up(path: str, rel: str) -> Optional[str]:
    """Nearest ``rel`` (e.g. ``parallel/mesh.py``) above ``path``."""
    directory = os.path.dirname(os.path.abspath(path))
    for _ in range(8):
        candidate = os.path.join(directory, *rel.split("/"))
        if os.path.isfile(candidate):
            return candidate
        parent = os.path.dirname(directory)
        if parent == directory:
            break
        directory = parent
    return None


def _parse_literal_assign(path: str, name: str):
    """The ast-literal value of a module-level ``name = <literal>`` in
    ``path`` (None when absent/unparseable)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            continue
        value = node.value
        if isinstance(value, ast.Call) and value.args:
            value = value.args[0]  # frozenset({...}) / tuple([...])
        try:
            return ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return None
    return None


def _mesh_axes(path: str) -> FrozenSet[str]:
    """``__mesh_axes__`` parsed from the parallel/mesh.py nearest ``path``."""
    candidate = _find_up(path, "parallel/mesh.py")
    if candidate is None:
        return MESH_AXES
    if candidate not in _axes_cache:
        value = _parse_literal_assign(candidate, "__mesh_axes__")
        _axes_cache[candidate] = (
            frozenset(str(v) for v in value) if value else MESH_AXES
        )
    return _axes_cache[candidate]


def _sharding_builders(path: str) -> Tuple[str, ...]:
    """``__sharding_builders__`` parsed from parallel/sharding.py."""
    candidate = _find_up(path, "parallel/sharding.py")
    if candidate is None:
        return SHARDING_REGISTRY
    if candidate not in _builders_cache:
        value = _parse_literal_assign(candidate, "__sharding_builders__")
        _builders_cache[candidate] = (
            tuple(str(v) for v in value) if value else SHARDING_REGISTRY
        )
    return _builders_cache[candidate]


# -- TPU801: mesh-axis closed world -------------------------------------------

_SPEC_CTORS = frozenset({"PartitionSpec", "P"})
# jax collectives whose string-literal arguments are mesh-axis names
_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "ppermute", "all_to_all", "axis_index", "pvary", "pbroadcast",
})


def _basename(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    return name.rsplit(".", 1)[-1] if name else None


def _spec_helper_names(tree: ast.AST) -> Set[str]:
    """Local spec-forwarding helpers: functions that pass their own
    ``*varargs`` into a ``P(...)``/``PartitionSpec(...)`` call (or into
    another such helper) — ``parallel/sharding.py``'s ``ns``/``col``
    pattern. Calls to these are checked like direct ``P(...)`` calls."""
    helpers: Set[str] = set()
    # fixpoint over at most the nesting depth of helper chains (2 passes
    # cover ns -> col; keep a small bound for pathological trees)
    for _ in range(4):
        added = False
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in helpers or node.args.vararg is None:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                base = _basename(call)
                if base not in _SPEC_CTORS and base not in helpers:
                    continue
                if any(
                    isinstance(a, ast.Starred)
                    and isinstance(a.value, ast.Name)
                    and a.value.id == node.args.vararg.arg
                    for a in call.args
                ):
                    helpers.add(node.name)
                    added = True
                    break
        if not added:
            break
    return helpers


def _axis_literals(expr: ast.AST):
    """(node, axis) for every string constant in a spec/collective argument
    expression (tuples of axes included)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node, node.value


def _check_axes(tree: ast.AST, path: str) -> List[Finding]:
    axes = _mesh_axes(path)
    helpers = _spec_helper_names(tree)
    findings: List[Finding] = []

    def flag(node: ast.AST, axis: str, where: str) -> None:
        findings.append(Finding(
            "TPU801", path, node.lineno, node.col_offset,
            "axis {!r} in {} is not in the mesh-axis registry "
            "(parallel/mesh.py __mesh_axes__: {})".format(
                axis, where, ", ".join(sorted(axes))
            ),
            "use a declared axis, or add the new axis to "
            "parallel/mesh.py __mesh_axes__ (and its docstring) so every "
            "sharding rule and kernel agrees on the vocabulary",
        ))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            base = _basename(node)
            if base in _SPEC_CTORS or base in helpers:
                for arg in node.args:
                    if isinstance(arg, ast.Starred):
                        continue
                    for lit, axis in _axis_literals(arg):
                        if axis not in axes:
                            flag(lit, axis, "a PartitionSpec")
            elif base in _COLLECTIVES:
                args = list(node.args) + [
                    kw.value for kw in node.keywords
                    if kw.arg in ("axis_name", "axis_index_groups") and
                    kw.arg == "axis_name"
                ]
                for arg in args:
                    for lit, axis in _axis_literals(arg):
                        if axis not in axes:
                            flag(lit, axis, "collective {}()".format(base))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # axis defaults: `def ring_attention(..., axis_name="sp")`
            spec = node.args
            for args, defaults in (
                (spec.args + spec.posonlyargs, spec.defaults),
                (spec.kwonlyargs, spec.kw_defaults),
            ):
                names = args[-len(defaults):] if defaults else []
                for arg, default in zip(names, defaults):
                    if (
                        arg is not None and default is not None
                        and arg.arg in ("axis_name", "axis_names")
                    ):
                        for lit, axis in _axis_literals(default):
                            if axis not in axes:
                                flag(lit, axis,
                                     "the {} default of {}()".format(
                                         arg.arg, node.name))
    return findings


# -- TPU802: sharding declarations for serve-path jit entries ----------------


def _dict_literal(node: ast.AST) -> Optional[ast.Dict]:
    return node if isinstance(node, ast.Dict) else None


def _class_dunder(cls: ast.ClassDef, name: str) -> Optional[ast.Assign]:
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in stmt.targets
        ):
            return stmt
    return None


def _check_shardings(tree: ast.AST, path: str) -> List[Finding]:
    findings: List[Finding] = []
    builders = frozenset(_sharding_builders(path))

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        compile_keys = _class_dunder(node, "__compile_keys__")
        shardings = _class_dunder(node, "__shardings__")
        serves = False
        if compile_keys is not None:
            d = _dict_literal(compile_keys.value)
            if d is not None:
                serves = any(
                    isinstance(k, ast.Constant) and k.value == "serve"
                    for k in d.keys
                )
        if serves and shardings is None:
            findings.append(Finding(
                "TPU802", path, node.lineno, node.col_offset,
                "class {} declares serve-path jit entries "
                "(__compile_keys__) but no __shardings__ registry naming "
                "the sharding builder covering each donated/sharded "
                "operand family".format(node.name),
                "declare `__shardings__ = {\"params\": "
                "\"parallel.sharding.llama_param_sharding\", ...}` next "
                "to __compile_keys__ (docs/static_analysis.md TPU8xx)",
            ))
        if shardings is not None:
            d = _dict_literal(shardings.value)
            if d is None:
                findings.append(Finding(
                    "TPU802", path, shardings.lineno, shardings.col_offset,
                    "__shardings__ must be a dict literal (the analyzer "
                    "parses it from source without importing)",
                    "use a literal {family: \"builder.dotted.name\"} dict",
                ))
                continue
            for value in d.values:
                if not (
                    isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                ):
                    findings.append(Finding(
                        "TPU802", path, value.lineno, value.col_offset,
                        "__shardings__ values must be string dotted names "
                        "of sharding builders",
                        "name the builder as a string, e.g. "
                        "\"parallel.sharding.llama_param_sharding\"",
                    ))
                    continue
                builder = value.value.rsplit(".", 1)[-1]
                if builder not in builders:
                    findings.append(Finding(
                        "TPU802", path, value.lineno, value.col_offset,
                        "__shardings__ names builder {!r} which is not in "
                        "the parallel/sharding.py __sharding_builders__ "
                        "registry ({})".format(
                            builder, ", ".join(sorted(builders))
                        ),
                        "add the builder to parallel/sharding.py "
                        "__sharding_builders__ (and define it there), or "
                        "fix the name",
                    ))

    # the registry module itself: every declared builder must be defined
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if not (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__sharding_builders__"
                for t in node.targets
            )
        ):
            continue
        try:
            declared = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            findings.append(Finding(
                "TPU802", path, node.lineno, node.col_offset,
                "__sharding_builders__ must be a literal tuple of builder "
                "names (the analyzer parses it from source)",
                "keep the registry a literal",
            ))
            continue
        defined = {
            n.name for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for name in declared:
            if str(name) not in defined:
                findings.append(Finding(
                    "TPU802", path, node.lineno, node.col_offset,
                    "__sharding_builders__ declares {!r} but no such "
                    "function is defined in this module".format(name),
                    "define the builder here or drop the stale registry "
                    "entry",
                ))
    return findings


# -- TPU803: multihost-unsafe host access ------------------------------------

# calls whose result is a sharded-GLOBAL value: host-materializing it
# without going through addressable_shards (or a declared replicated spec)
# deadlocks or reads one shard's garbage under more than one process
_TAINT_SOURCES = frozenset({
    "shard_params", "with_sharding_constraint", "broadcast_one_to_all",
    "device_put",
})
# host-materialization sinks
_SINK_CALLS = frozenset({"asarray", "device_get", "array"})
_SINK_METHODS = frozenset({"tolist", "item", "__array__"})
_SINK_CASTS = frozenset({"int", "float", "bool"})


def _base_name(node: ast.AST) -> Optional[str]:
    """The root Name id under subscripts/attribute chains."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _check_host_access(tree: ast.AST, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tainted: Set[str] = set()
        shard_read: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                base = _basename(node.value)
                if base in _TAINT_SOURCES:
                    if base == "device_put" and len(node.value.args) < 2:
                        continue  # device_put without a sharding is local
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            tainted.add(target.id)
            elif isinstance(node, ast.Attribute) and (
                node.attr == "addressable_shards"
            ):
                name = _base_name(node.value)
                if name:
                    shard_read.add(name)
        if not tainted:
            continue
        safe = tainted - shard_read

        def flag(node: ast.AST, name: str, sink: str) -> None:
            findings.append(Finding(
                "TPU803", path, node.lineno, node.col_offset,
                "{} host-materializes {!r}, a sharded-global value: under "
                "more than one process this deadlocks (cross-host gather) "
                "or reads one shard's local garbage".format(sink, name),
                "read through .addressable_shards (per-host data), or "
                "annotate a declared-replicated read with "
                "`# tpuserve: ignore[TPU803] <why it is replicated>`",
            ))

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            base = _basename(node)
            if base in _SINK_CALLS and node.args:
                name = _base_name(node.args[0])
                if name in safe:
                    flag(node, name, "{}()".format(base))
            elif base in _SINK_CASTS and len(node.args) == 1:
                name = _base_name(node.args[0])
                if name in safe:
                    flag(node, name, "{}()".format(base))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SINK_METHODS
            ):
                name = _base_name(node.func.value)
                if name in safe:
                    flag(node, name, ".{}()".format(node.func.attr))
    return findings


# -- TPU804: silent replication fallback --------------------------------------


def _return_kinds(fn: ast.AST, axes: FrozenSet[str]):
    """(axis_returns, fallback_returns) for one function body, not
    descending into nested functions (each is classified on its own)."""
    axis_rets: List[ast.Return] = []
    fallback_rets: List[ast.Return] = []
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Return):
            value = node.value
            if value is None or (
                isinstance(value, ast.Constant) and value.value is None
            ):
                fallback_rets.append(node)
            elif (
                isinstance(value, ast.Call)
                and _basename(value) in (_SPEC_CTORS | {"replicated"})
                and not value.args and not value.keywords
            ):
                fallback_rets.append(node)
            elif any(
                axis in axes for _n, axis in _axis_literals(value)
            ):
                axis_rets.append(node)
        for child in ast.iter_child_nodes(node):
            stack.append(child)
    return axis_rets, fallback_rets


def _check_replication_fallback(tree: ast.AST, path: str) -> List[Finding]:
    # only modules that declare themselves sharding-builder registries
    if not (
        isinstance(tree, ast.Module)
        and any(
            isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name)
                and t.id == "__sharding_builders__"
                for t in n.targets
            )
            for n in tree.body
        )
    ):
        return []
    axes = _mesh_axes(path)
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        axis_rets, fallback_rets = _return_kinds(fn, axes)
        if not (axis_rets and fallback_rets):
            continue
        for ret in fallback_rets:
            findings.append(Finding(
                "TPU804", path, ret.lineno, ret.col_offset,
                "sharding builder path in {}() silently falls back to a "
                "replicated spec for an operand other paths shard — "
                "replicate-instead-of-shard defeats TP memory scaling "
                "with no error".format(fn.name),
                "annotate the fallback with `# tpuserve: ignore[TPU804] "
                "<why this operand must replicate>` so the reason is "
                "machine-visible, or shard it",
            ))
    return findings


# -- entry point ---------------------------------------------------------------


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    findings = _check_axes(tree, path)
    findings += _check_shardings(tree, path)
    findings += _check_host_access(tree, path)
    findings += _check_replication_fallback(tree, path)
    return findings
