"""TPU5xx — thread-affinity discipline over the pipelined engine.

The engine tier is a small orchestration system: an asyncio loop thread
(handlers, the decode loop, the watchdog), ``asyncio.to_thread`` dispatch /
readback / prefill workers (docs/pipelined_decode.md), and daemon threads on
the control plane (model_request_processor's sync + stats senders). Which
thread may touch which state is the load-bearing correctness rule of that
design — and before this rule family it lived only in comments ("loop-thread
only", "worker thread half") and reviewer memory.

The pass builds a **thread-context call graph** per module (stdlib ast only,
intra-module, like every other rule family):

- roots: every ``async def`` body runs on the **loop** thread; every function
  handed to ``asyncio.to_thread(f, ...)``, ``threading.Thread(target=f)`` or
  ``loop.run_in_executor(None, f)`` runs on a **worker** thread;
- propagation: contexts flow through intra-module calls (``self.m()``, bare
  ``f()`` through the lexical scope chain, and ``x.m()`` when ``m`` names
  exactly one method in the module) to a fixpoint. A function reachable from
  both kinds of root carries BOTH contexts.

Known blind spots (documented in docs/static_analysis.md): cross-module
calls, dynamic dispatch (callables in variables, ``getattr``), and functions
never reached from a root (no context -> not checked). The rules fail open
on those — the deterministic interleaving explorer
(llm/schedule_explorer.py) is the dynamic net behind this static one.

Rules:

- **TPU501** — a function reachable from the wrong thread mutates state
  declared thread-affine via the ``__affine_to__`` class annotation
  (sibling of ``__guarded_by__``)::

      class LLMEngineCore:
          __affine_to__ = {"loop": ("_inflight", "_quarantine", ...),
                           "worker": ("_next_token_dev", ...)}

  Affinity is the third synchronization discipline next to lock-guarded
  (``__guarded_by__`` / TPU301) and immutable: affine state has NO lock on
  purpose — exactly one thread owns it — so an off-thread mutation is a
  data race with no second chance at runtime.

- **TPU502** — cross-thread handoff of a mutable host buffer without a
  copy: ``jnp.asarray(self._buf)`` on a shared host mirror.
  ``jnp.asarray`` of a suitably-aligned numpy array is ZERO-COPY on CPU,
  and the resulting device value may be read lazily, after the producer
  thread has mutated the buffer in place — the exact rare wrong-token race
  PR 4 fixed by hand in ``_prepare_dispatch``/``_chain_input``. Snapshot
  with ``.copy()`` at the handoff.

- **TPU503** — ``await`` while holding a synchronous lock (``with
  self._lock: ... await ...``): every other coroutine on the loop that
  needs the lock deadlocks against the suspended holder, and worker
  threads convoy behind an arbitrarily long suspension.

- **TPU504** — a "lock held by caller" helper (a ``# tpuserve:
  ignore[TPU301]``-annotated method mutating ``__guarded_by__`` state)
  called from thread-context code WITHOUT the declared lock lexically
  held. TPU301's scope ignores are load-bearing holes; this closes them
  across the call graph, so the donated-handle rebind helpers can never be
  reached lock-free from either thread.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from . import Finding, RULES, _ignore_map, dotted_name as _dotted
from .rules_locks import (
    PROJECT_REGISTRY as _GUARDED_REGISTRY,
    _MUTATORS,
    _file_declarations as _guarded_declarations,
    _strip_subscripts,
)

LOOP = "loop"
WORKER = "worker"
_THREADS = (LOOP, WORKER)

# attr name -> (owning thread, receiver-basename filter or None), mirroring
# the __affine_to__ declarations at the definition sites the same way
# rules_locks.PROJECT_REGISTRY mirrors __guarded_by__ (test_analyze checks
# the two agree). Cross-module pokes of affine state are rare but real —
# keep names distinctive enough for a None filter.
AFFINITY_REGISTRY: Dict[str, Tuple[str, Optional[Tuple[str, ...]]]] = {
    # engine.LLMEngineCore pipeline/quarantine/chain state
    # (docs/pipelined_decode.md): owned by the event-loop thread; dispatch
    # workers receive snapshots (prep dicts), never these attrs
    "_inflight": (LOOP, ("self", "engine")),
    "_quarantine": (LOOP, ("self", "engine")),
    "_dispatching": (LOOP, ("self", "engine")),
    "_slot_req": (LOOP, None),
    "_admitting": (LOOP, None),
    "_next_token": (LOOP, ("self", "engine")),
    "_gstate": (LOOP, ("self", "engine")),
    "_slot_overrides": (LOOP, None),
    # ragged scheduler job list (docs/ragged_attention.md): the loop opens,
    # shares out, and retires jobs; dispatch workers only read plan dicts
    "_prefill_jobs": (LOOP, ("self", "engine")),
    # multi-step / spec-as-row per-launch chain state
    # (docs/ragged_attention.md): window planning and retire-side
    # acceptance land these counters/histograms on the loop thread only
    "_step_rows": (LOOP, ("self", "engine")),
    "_hist_launch_tokens": (LOOP, ("self", "engine")),
    "_hist_spec_accept": (LOOP, ("self", "engine")),
    # host-tier promotion reap counters (docs/kv_tiering.md): bumped only
    # at loop-thread retire boundaries
    "_tier_counters": (LOOP, ("self", "engine")),
    # draft-tree verify rows (docs/spec_decode_trees.md): proposer hit
    # counters and the accept-depth histogram are planned/retired on the
    # loop thread; per-slot draft-ahead shipping watermarks advance at
    # loop-thread retire chunk boundaries
    "_spec_proposer": (LOOP, ("self", "engine")),
    "_hist_spec_tree_depth": (LOOP, ("self", "engine")),
    "_kv_draft_ahead": (LOOP, ("self", "engine")),
    # device-resident cross-chunk chains: written by the dispatch worker
    # (the only stage that runs device programs); the loop resets them only
    # at protocol-serialized points (annotated at the definition site)
    "_next_token_dev": (WORKER, None),
    "_gstate_dev": (WORKER, None),
    # replica-router ring membership (serving/replica_router.py,
    # docs/replication.md): sweeps/picks rebind an immutable frozenset on
    # the serving loop; the scrape thread reads snapshots by reference
    "_ring_members": (LOOP, ("self", "router", "_router")),
    # process-replica supervision (serving/process_replica.py): the
    # heartbeat miss counter is owned by the dedicated supervisor thread —
    # loop-side code reads liveness through is_ready snapshots only
    "_hb_misses": (WORKER, ("self", "replica")),
    # model_request_processor daemon-shared registries: read on the serving
    # event loop; the sync daemon swaps them only through the zero-downtime
    # drain protocol (annotated at the definition sites)
    "_endpoints": (LOOP, ("self", "processor")),
    "_model_monitoring": (LOOP, ("self", "processor")),
    "_model_monitoring_endpoints": (LOOP, ("self", "processor")),
    "_model_monitoring_versions": (LOOP, ("self", "processor")),
    "_canary_endpoints": (LOOP, ("self", "processor")),
    "_canary_route": (LOOP, ("self", "processor")),
    "_metric_logging": (LOOP, ("self", "processor")),
    "_engine_processor_lookup": (LOOP, ("self", "processor")),
    "_telemetry": (LOOP, ("self", "processor")),
}

# call shapes that move a callable onto a worker thread
_TO_THREAD_TAILS = ("to_thread",)          # asyncio.to_thread(f, ...)
_THREAD_CTORS = ("Thread",)                # threading.Thread(target=f)
_EXECUTOR_TAILS = ("run_in_executor",)     # loop.run_in_executor(None, f)

# host->device upload entry points whose zero-copy aliasing TPU502 polices:
# `jnp.asarray` and the spelled-out `jax.numpy.asarray` (matched on the last
# two dotted components). Deliberately NOT plain `np.asarray` — that is the
# standard device->host readback idiom (`np.asarray(entry.chunk)` on an
# immutable device buffer), and flagging it would drown the rule; a worker
# handoff built from `np.asarray` views is a documented blind spot.
_ASARRAY_TAILS = (("jnp", "asarray"), ("numpy", "asarray"))

_LOCKISH = ("lock", "mutex")


def _is_lock_name(name: Optional[str]) -> bool:
    if not name:
        return False
    leaf = name.split(".")[-1].lower()
    return any(marker in leaf for marker in _LOCKISH)


class _Fn:
    """One function/method in the module, with its lexical position and the
    thread contexts the call-graph pass assigns."""

    __slots__ = (
        "node", "name", "cls", "parent", "children", "contexts", "is_async",
    )

    def __init__(self, node, cls: Optional[str], parent: Optional["_Fn"]):
        self.node = node
        self.name = node.name
        self.cls = cls
        self.parent = parent
        self.children: Dict[str, "_Fn"] = {}
        self.contexts: Set[str] = set()
        self.is_async = isinstance(node, ast.AsyncFunctionDef)


def _collect_functions(tree: ast.AST) -> List[_Fn]:
    out: List[_Fn] = []

    def visit(node: ast.AST, cls: Optional[str], parent: Optional[_Fn]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = _Fn(child, cls, parent)
                out.append(fn)
                if parent is not None:
                    parent.children[fn.name] = fn
                visit(child, cls, fn)
            elif isinstance(child, ast.ClassDef):
                visit(child, child.name, None)
            else:
                visit(child, cls, parent)

    visit(tree, None, None)
    return out


def _own_statements(fn: _Fn):
    """Walk fn's body WITHOUT descending into nested function definitions
    (those are separate _Fn entries with their own contexts)."""
    stack = list(fn.node.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


class _Index:
    def __init__(self, fns: Sequence[_Fn]):
        self.methods: Dict[Tuple[str, str], _Fn] = {}
        self.module_fns: Dict[str, _Fn] = {}
        method_names: Dict[str, List[_Fn]] = {}
        for fn in fns:
            if fn.cls is not None and fn.parent is None:
                self.methods[(fn.cls, fn.name)] = fn
                method_names.setdefault(fn.name, []).append(fn)
            elif fn.cls is None and fn.parent is None:
                self.module_fns[fn.name] = fn
        # unambiguous method-name lookup for `x.m()` style calls
        self.unique_methods: Dict[str, _Fn] = {
            name: cands[0]
            for name, cands in method_names.items()
            if len(cands) == 1
        }

    def resolve(self, caller: _Fn, name: Optional[str]) -> Optional[_Fn]:
        if not name:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            # lexical chain: nested defs of enclosing functions, then module
            scope = caller
            while scope is not None:
                if parts[0] in scope.children:
                    return scope.children[parts[0]]
                scope = scope.parent
            if parts[0] in self.module_fns:
                return self.module_fns[parts[0]]
            return None
        if parts[0] == "self" and len(parts) == 2 and caller.cls:
            hit = self.methods.get((caller.cls, parts[1]))
            if hit is not None:
                return hit
        # x.y.m(): fall back to the unambiguous method-name table
        return self.unique_methods.get(parts[-1])


def _worker_target(node: ast.Call) -> Optional[ast.AST]:
    """The callable expression a call moves onto a worker thread, if any."""
    name = _dotted(node.func)
    if name is None:
        return None
    leaf = name.split(".")[-1]
    if leaf in _TO_THREAD_TAILS and node.args:
        return node.args[0]
    if leaf in _THREAD_CTORS:
        for kw in node.keywords:
            if kw.arg == "target":
                return kw.value
    if leaf in _EXECUTOR_TAILS and len(node.args) >= 2:
        return node.args[1]
    return None


def _assign_contexts(fns: List[_Fn]) -> _Index:
    index = _Index(fns)
    edges: Dict[int, List[_Fn]] = {}
    for fn in fns:
        if fn.is_async:
            fn.contexts.add(LOOP)
        callees: List[_Fn] = []
        for node in _own_statements(fn):
            if not isinstance(node, ast.Call):
                continue
            target = _worker_target(node)
            if target is not None:
                worker_fn = index.resolve(fn, _dotted(target))
                if worker_fn is not None:
                    worker_fn.contexts.add(WORKER)
            callee = index.resolve(fn, _dotted(node.func))
            if callee is not None and callee is not fn:
                callees.append(callee)
        edges[id(fn)] = callees
    # propagate to a fixpoint (contexts only grow; bounded by 2 per fn)
    changed = True
    while changed:
        changed = False
        for fn in fns:
            if not fn.contexts:
                continue
            for callee in edges[id(fn)]:
                if not fn.contexts <= callee.contexts:
                    callee.contexts |= fn.contexts
                    changed = True
    return index


def _affine_declarations(
    tree: ast.AST,
) -> Dict[str, Tuple[str, Optional[Tuple[str, ...]]]]:
    """``__affine_to__`` class declarations: attr -> (thread, None)."""
    out: Dict[str, Tuple[str, Optional[Tuple[str, ...]]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "__affine_to__"
                for t in stmt.targets
            ):
                continue
            try:
                decl = ast.literal_eval(stmt.value)
            except (ValueError, SyntaxError):
                continue
            if not isinstance(decl, dict):
                continue
            for thread, attrs in decl.items():
                if str(thread) not in _THREADS:
                    continue
                for attr in attrs:
                    out[str(attr)] = (str(thread), None)
    return out


def _affine_split(node: ast.AST, registry):
    node = _strip_subscripts(node)
    if not isinstance(node, ast.Attribute):
        return None
    entry = registry.get(node.attr)
    if entry is None:
        return None
    thread, receivers = entry
    recv = _dotted(node.value)
    if recv is None:
        return None
    if receivers is not None and recv.split(".")[-1] not in receivers:
        return None
    return recv, node.attr, thread


def _iter_mutations(fn: _Fn):
    """(target_expr, stmt_node) pairs for every mutation in fn's own body —
    the same mutation surface rules_locks checks (assign/augassign/del +
    mutating method calls)."""
    for node in _own_statements(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                list(node.targets) if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Tuple):
                    for elt in t.elts:
                        yield elt, node
                else:
                    yield t, node
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                yield t, node
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                yield node.func.value, node


def _emit(findings: List[Finding], code: str, path: str, node: ast.AST,
          detail: str) -> None:
    summary, hint = RULES[code]
    findings.append(
        Finding(
            code, path, node.lineno, node.col_offset,
            "{} ({})".format(summary, detail), hint,
        )
    )


# -- TPU501 -------------------------------------------------------------------


def _check_tpu501(fn: _Fn, registry, path: str,
                  findings: List[Finding]) -> None:
    if fn.name == "__init__":
        return  # object under construction is not yet shared
    for target, stmt in _iter_mutations(fn):
        hit = _affine_split(target, registry)
        if hit is None:
            continue
        recv, attr, thread = hit
        off_thread = fn.contexts - {thread}
        if not off_thread:
            continue
        _emit(
            findings, "TPU501", path, stmt,
            "{}.{} is {}-thread-affine but `{}` is reachable from the "
            "{} thread".format(
                recv, attr, thread, fn.name, "/".join(sorted(off_thread))
            ),
        )


# -- TPU502 -------------------------------------------------------------------


def _check_tpu502(fn: _Fn, path: str, findings: List[Finding]) -> None:
    for node in _own_statements(fn):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        name = _dotted(node.func)
        if name is None:
            continue
        parts = name.split(".")
        pair = tuple(parts[-2:]) if len(parts) >= 2 else None
        if pair not in _ASARRAY_TAILS:
            continue
        arg = _strip_subscripts(node.args[0])
        if not isinstance(arg, ast.Attribute):
            continue  # locals and fresh call results can't be shared mirrors
        buf = _dotted(arg)
        if buf is None:
            continue
        _emit(
            findings, "TPU502", path, node,
            "{}({}) aliases a shared host buffer across the thread "
            "handoff".format(name, buf),
        )


# -- TPU503 -------------------------------------------------------------------


class _AwaitUnderLockVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self._fn: List[bool] = []         # innermost function kind
        self._locks: List[str] = []       # sync locks lexically held

    def _visit_fn(self, node, is_async: bool):
        # a nested def inside a `with lock:` body runs LATER, without the
        # lock — its awaits are not under this lock scope
        self._fn.append(is_async)
        saved, self._locks = self._locks, []
        self.generic_visit(node)
        self._locks = saved
        self._fn.pop()

    def visit_AsyncFunctionDef(self, node):
        self._visit_fn(node, True)

    def visit_FunctionDef(self, node):
        self._visit_fn(node, False)

    def visit_Lambda(self, node):
        self._visit_fn(node, False)

    def visit_With(self, node: ast.With):
        names = [
            _dotted(item.context_expr)
            for item in node.items
            if _is_lock_name(_dotted(item.context_expr))
        ]
        self._locks.extend(n for n in names if n)
        self.generic_visit(node)
        for _ in names:
            if _:
                self._locks.pop()

    # async with takes asyncio locks, which are await-safe by design

    def visit_Await(self, node: ast.Await):
        if self._fn and self._fn[-1] and self._locks:
            _emit(
                self.findings, "TPU503", self.path, node,
                "await while holding `{}`".format(self._locks[-1]),
            )
        self.generic_visit(node)


# -- TPU504 -------------------------------------------------------------------


def _is_tpu301_scoped(fn: _Fn, ignores) -> bool:
    """Does fn's def (or decorator) line carry a TPU301 scope ignore — the
    'lock held by caller' marker? One predicate shared by helper detection
    and the caller exemption so the two can never diverge."""
    decl_lines = [fn.node.lineno] + [d.lineno for d in fn.node.decorator_list]
    return any(
        line in ignores
        and (ignores[line] is None or "TPU301" in (ignores[line] or ()))
        for line in decl_lines
    )


def _lock_helpers(fns: Sequence[_Fn], guarded,
                  ignores) -> Dict[int, FrozenSet[str]]:
    """fn-id -> lock attr names, for every method whose def line carries a
    TPU301 scope ignore AND whose body mutates guarded state — the "lock
    held by caller" helpers whose callers TPU504 audits."""
    out: Dict[int, FrozenSet[str]] = {}
    for fn in fns:
        if not _is_tpu301_scoped(fn, ignores):
            continue
        locks: Set[str] = set()
        for target, _stmt in _iter_mutations(fn):
            node = _strip_subscripts(target)
            if not isinstance(node, ast.Attribute):
                continue
            entry = guarded.get(node.attr)
            if entry is not None:
                locks.add(entry[0])
        if locks:
            out[id(fn)] = frozenset(locks)
    return out


def _check_tpu504(fn: _Fn, index: _Index, helpers, ignores, path: str,
                  findings: List[Finding]) -> None:
    if _is_tpu301_scoped(fn, ignores):
        return  # the fn is itself a lock-held context; the annotation covers it

    def walk(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            now = set(held)
            for item in node.items:
                text = _dotted(item.context_expr)
                if text:
                    now.add(text)
            for child in node.body:
                walk(child, frozenset(now))
            for item in node.items:
                walk(item.context_expr, held)
            return
        if isinstance(node, ast.Call):
            callee = index.resolve(fn, _dotted(node.func))
            if callee is not None and id(callee) in helpers:
                prefix = "self"
                if isinstance(node.func, ast.Attribute):
                    prefix = _dotted(node.func.value) or "self"
                required = {
                    "{}.{}".format(prefix, lock)
                    for lock in helpers[id(callee)]
                }
                if not required <= held:
                    _emit(
                        findings, "TPU504", path, node,
                        "`{}` mutates lock-guarded state for its caller, "
                        "but `{}` does not hold {}".format(
                            callee.name, fn.name, ", ".join(sorted(required))
                        ),
                    )
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in fn.node.body:
        walk(stmt, frozenset())


# -- entry --------------------------------------------------------------------


def check(tree: ast.AST, path: str, source: str) -> List[Finding]:
    fns = _collect_functions(tree)
    index = _assign_contexts(fns)
    affine = dict(AFFINITY_REGISTRY)
    affine.update(_affine_declarations(tree))
    guarded = dict(_GUARDED_REGISTRY)
    guarded.update(_guarded_declarations(tree))
    ignores = _ignore_map(source)
    helpers = _lock_helpers(fns, guarded, ignores)
    has_worker = any(WORKER in fn.contexts for fn in fns)

    findings: List[Finding] = []
    for fn in fns:
        if not fn.contexts:
            continue  # not reachable from a thread root: blind spot, fail open
        _check_tpu501(fn, affine, path, findings)
        if has_worker:
            _check_tpu502(fn, path, findings)
        _check_tpu504(fn, index, helpers, ignores, path, findings)
    lock_visitor = _AwaitUnderLockVisitor(path)
    lock_visitor.visit(tree)
    findings.extend(lock_visitor.findings)
    return findings
