"""Dynamic request batcher — the Triton `dynamic_batching` equivalent.

Requests for one model queue up; a batch fires when it reaches
``preferred_batch_size`` or the oldest request has waited
``max_queue_delay_us`` (same two knobs the reference exposes through aux-pbtxt,
SURVEY.md §2.9). The batch is concatenated on the leading axis, padded up to
the model's bucket (so arbitrary traffic shapes hit a small set of compiled
signatures — no XLA recompilation storms), executed once, and split back.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class DynamicBatcher:
    def __init__(
        self,
        run_batch: Callable[[List[np.ndarray]], List[np.ndarray]],
        preferred_batch_size: int = 8,
        max_queue_delay_us: int = 2000,
        max_batch_size: int = 64,
        bucket_for: Optional[Callable[[int], int]] = None,
    ):
        self._run_batch = run_batch  # takes list of input arrays (batch-concat'd)
        self.preferred = int(preferred_batch_size)
        self.max_delay_s = float(max_queue_delay_us) / 1e6
        self.max_batch = int(max_batch_size)
        # rows -> executed bucket rows (repo.CompiledModel's bucket set);
        # lets the batcher account the padding waste of the bucket-padding
        # path it feeds without knowing the model's buckets itself
        self.bucket_for = bucket_for
        # items: (inputs, future, rows, enqueue_time)
        self._queue: "asyncio.Queue[Tuple[List[np.ndarray], asyncio.Future, int, float]]" = (
            asyncio.Queue()
        )
        self._task: Optional[asyncio.Task] = None
        # observability
        self.batches_executed = 0
        self.requests_served = 0
        self.batch_size_sum = 0
        # padding efficiency: rows the bucket-padding path executed beyond
        # the real request rows (pure XLA-shape waste; high values mean the
        # bucket set or batching knobs are mis-tuned for the traffic)
        self.padded_rows_sum = 0
        # queue-time hook (enqueue -> batch execution start), feeding the
        # engine server's queue-delay histogram (Triton exports the
        # equivalent nv_inference_queue_duration)
        self.on_queue_delay = None  # optional callable(seconds)
        # padding hook: callable(real_rows, padded_rows) per executed batch
        self.on_padding = None

    async def infer(self, inputs: List[np.ndarray]) -> List[np.ndarray]:
        """Submit one request's input list; rows = inputs[i].shape[0]."""
        rows = int(inputs[0].shape[0]) if inputs and inputs[0].ndim > 0 else 1
        if rows > self.max_batch:
            raise ValueError(
                "request batch {} exceeds max_batch_size {}".format(rows, self.max_batch)
            )
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((inputs, future, rows, time.monotonic()))
        self._ensure_task()
        return await future

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        carry = None  # item popped but deferred to the next batch (row cap)
        while True:
            if carry is not None:
                first, carry = carry, None
            else:
                try:
                    first = await asyncio.wait_for(self._queue.get(), timeout=5.0)
                except asyncio.TimeoutError:
                    # Idle shutdown without stranding: no awaits between the
                    # emptiness check and clearing _task, so (single-threaded
                    # loop) any infer() either enqueued before this check or
                    # will see _task None and start a fresh task.
                    if self._queue.empty():
                        self._task = None
                        return
                    continue
            batch = [first]
            total_rows = first[2]
            deadline = time.monotonic() + self.max_delay_s
            while total_rows < self.preferred:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._queue.get(), timeout=timeout)
                except asyncio.TimeoutError:
                    break
                if total_rows + item[2] > self.max_batch:
                    carry = item  # keep the row cap honest; execute next round
                    break
                batch.append(item)
                total_rows += item[2]
                if total_rows >= self.preferred:
                    break
            await self._execute(batch)

    async def _execute(self, batch) -> None:
        inputs_list = [b[0] for b in batch]
        futures = [b[1] for b in batch]
        rows = [b[2] for b in batch]
        if self.on_queue_delay is not None:
            now = time.monotonic()
            for b in batch:
                self.on_queue_delay(now - b[3])
        try:
            n_inputs = len(inputs_list[0])
            concat = [
                np.concatenate([req[i] for req in inputs_list], axis=0)
                for i in range(n_inputs)
            ]
            outputs = await asyncio.to_thread(self._run_batch, concat)
            self.batches_executed += 1
            self.requests_served += len(batch)
            total_rows = sum(rows)
            self.batch_size_sum += total_rows
            padded = 0
            if self.bucket_for is not None:
                padded = max(0, int(self.bucket_for(total_rows)) - total_rows)
            self.padded_rows_sum += padded
            if self.on_padding is not None:
                self.on_padding(total_rows, padded)
            # split each output back per-request along the leading axis
            offset = 0
            for fut, n in zip(futures, rows):
                per_request = [out[offset: offset + n] for out in outputs]
                if not fut.done():
                    fut.set_result(per_request)
                offset += n
        except Exception as ex:
            for fut in futures:
                if not fut.done():
                    fut.set_exception(ex)
