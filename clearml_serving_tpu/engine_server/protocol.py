"""Tensor-infer wire protocol for router <-> engine-server gRPC.

The reference speaks Triton's ModelInfer protobuf (SURVEY.md §2.7). This server
keeps the same shape — named, typed, dense tensors in / out, model name +
version addressing — but encodes with msgpack over gRPC generic methods, so no
protoc codegen step and no .proto drift; numpy buffers ride as raw bytes.

Methods (full method names on the wire):
    /tpuserve.Engine/Infer   InferRequest -> InferResponse
    /tpuserve.Engine/Status  {} -> {models: {name: {...}}, devices: [...]}
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np

INFER_METHOD = "/tpuserve.Engine/Infer"
STATUS_METHOD = "/tpuserve.Engine/Status"


def encode_tensor(name: str, array: np.ndarray) -> Dict[str, Any]:
    array = np.ascontiguousarray(array)
    return {
        "name": name,
        "dtype": array.dtype.str,  # endianness-qualified, e.g. '<f4'
        "shape": list(array.shape),
        "data": array.tobytes(),
    }


def decode_tensor(t: Dict[str, Any]) -> Tuple[str, np.ndarray]:
    array = np.frombuffer(t["data"], dtype=np.dtype(t["dtype"])).reshape(t["shape"])
    return t["name"], array


def encode_infer_request(
    model: str,
    inputs: Dict[str, np.ndarray],
    version: Optional[str] = None,
    output_names: Optional[List[str]] = None,
) -> bytes:
    return msgpack.packb(
        {
            "model": model,
            "version": version or "",
            "inputs": [encode_tensor(k, v) for k, v in inputs.items()],
            "outputs": list(output_names or []),
        },
        use_bin_type=True,
    )


def decode_infer_request(data: bytes) -> Dict[str, Any]:
    req = msgpack.unpackb(data, raw=False)
    req["inputs"] = dict(decode_tensor(t) for t in req.get("inputs", []))
    return req


def encode_infer_response(outputs: Dict[str, np.ndarray]) -> bytes:
    return msgpack.packb(
        {"outputs": [encode_tensor(k, v) for k, v in outputs.items()]},
        use_bin_type=True,
    )


def decode_infer_response(data: bytes) -> Dict[str, np.ndarray]:
    resp = msgpack.unpackb(data, raw=False)
    return dict(decode_tensor(t) for t in resp.get("outputs", []))


def encode_obj(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def decode_obj(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)
