"""Engine-server model repository: control-plane reconciler + compiled models.

The reference's Triton sidecar materializes a filesystem model repo from the
stored control-plane state and lets tritonserver poll it
(engines/triton/triton_helper.py:91-224). Here the reconciler loads **jax
bundles** directly: every endpoint with engine type ``jax_grpc`` becomes a
CompiledModel — bucket-compiled XLA executables behind a DynamicBatcher — and
config changes hot-swap the entry atomically while in-flight requests finish on
the old one (it stays alive until the last reference drops).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .batcher import DynamicBatcher
from ..serving.endpoints import ModelEndpoint


class CompiledModel:
    """One endpoint's executable: jit-per-bucket + dynamic batcher.

    On a multi-host slice the host-0 instance broadcasts each batch to the
    secondary controllers (``dispatcher``) before dispatching locally, so
    every host enters the same executable (parallel/multihost.py)."""

    def __init__(self, endpoint: ModelEndpoint, bundle, params, *, key: str = "",
                 dispatcher=None):
        import jax

        self.endpoint = endpoint
        self.bundle = bundle
        self.params = params
        self.key = key or endpoint.serving_url
        aux = endpoint.auxiliary_cfg if isinstance(endpoint.auxiliary_cfg, dict) else {}
        batching = aux.get("batching") or {}
        self.buckets = sorted(int(b) for b in batching.get("buckets", [1, 2, 4, 8, 16, 32, 64]))
        self._jit = jax.jit(lambda params, *xs: bundle.apply(params, *xs))
        entry = (
            self.run_batch
            if dispatcher is None
            else lambda inputs: dispatcher.run(self.key, self.run_batch, inputs)
        )
        self.batcher = DynamicBatcher(
            entry,
            preferred_batch_size=int(batching.get("preferred_batch_size", 8)),
            max_queue_delay_us=int(batching.get("max_queue_delay_us", 2000)),
            max_batch_size=int(batching.get("max_batch_size", 64)),
            # padding-efficiency accounting: the batcher reports how many
            # rows run_batch's bucket padding wastes per executed batch
            bucket_for=lambda rows: next(
                (b for b in self.buckets if rows <= b), rows
            ),
        )
        self.input_names = endpoint.input_name or []
        self.input_types = endpoint.input_type or []
        self.output_names = endpoint.output_name or ["output_0"]

    def run_batch(self, concat_inputs: List[np.ndarray]) -> List[np.ndarray]:
        """Batch-concat'd inputs -> list of outputs (leading axis = batch)."""
        import jax

        batch = int(concat_inputs[0].shape[0])
        bucket = next((b for b in self.buckets if batch <= b), batch)
        padded = []
        for a in concat_inputs:
            if a.shape[0] != bucket:
                pad = [(0, bucket - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, pad)
            padded.append(a)
        out = self._jit(self.params, *padded)
        leaves = out if isinstance(out, (list, tuple)) else [out]
        return [np.asarray(o)[:batch] for o in leaves]

    def warmup(self) -> None:
        """Compile the smallest bucket ahead of traffic."""
        if not self.input_names:
            return
        try:
            shapes = self.endpoint.input_size or []
            inputs = []
            for i in range(len(self.input_names)):
                shape = [self.buckets[0]] + [int(d) for d in (shapes[i] if i < len(shapes) else [1])]
                dtype = np.dtype(self.input_types[i]) if i < len(self.input_types) else np.float32
                inputs.append(np.zeros(shape, dtype))
            self.run_batch(inputs)
        except Exception as ex:
            # warmup is best-effort (the first request compiles instead),
            # but a failure here usually means the endpoint I/O spec is
            # wrong — say so instead of deferring the surprise
            print("warmup of {!r} failed: {}".format(self.key, ex))


class EngineModelRepo:
    """Reconciles the control-plane endpoint set into compiled models."""

    ENGINE_TYPES = ("jax_grpc",)

    def __init__(self, processor, dispatcher=None):
        # processor: ModelRequestProcessor (control-plane reader + registry);
        # dispatcher: parallel/multihost HostZeroDispatcher on host 0 of a
        # multi-host slice (None on single host and on followers)
        self._processor = processor
        self._dispatcher = dispatcher
        self._models: Dict[str, CompiledModel] = {}
        self._hashes: Dict[str, str] = {}
        self._lock = threading.Lock()

    def get_by_key(self, key: str) -> Optional[CompiledModel]:
        return self._models.get(key)

    @staticmethod
    def model_key(serving_url: str, version: Optional[str] = None) -> str:
        key = serving_url.strip("/")
        if version:
            key = "{}/{}".format(key, version)
        return key

    def get(self, model: str, version: Optional[str] = None) -> Optional[CompiledModel]:
        return self._models.get(self.model_key(model, version))

    def list_models(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for key, cm in self._models.items():
            out[key] = {
                "engine": cm.endpoint.engine_type,
                "model_id": cm.endpoint.model_id,
                "buckets": cm.buckets,
                "requests_served": cm.batcher.requests_served,
                "batches_executed": cm.batcher.batches_executed,
                "rows_executed": cm.batcher.batch_size_sum,
                "padded_rows": cm.batcher.padded_rows_sum,
            }
        return out

    def sync(self) -> int:
        """One reconcile pass; returns number of (re)loaded models."""
        from ..engines.jax_engine import load_bundle

        self._processor.deserialize(skip_sync=True)
        wanted: Dict[str, ModelEndpoint] = {}
        for url, ep in {
            **self._processor._model_monitoring_endpoints,
            **self._processor.list_endpoints(),
        }.items():
            if ep.engine_type in self.ENGINE_TYPES:
                wanted[url] = ep

        loaded = 0
        registry = self._processor.registry
        for url, ep in wanted.items():
            record = registry.get(ep.model_id) if ep.model_id else None
            content_hash = "{}:{}".format(
                hash(str(sorted(ep.as_dict().items()))),
                (record.as_dict().get("hash") if record else None),
            )
            if self._hashes.get(url) == content_hash and url in self._models:
                continue
            if record is None:
                continue
            try:
                bundle, params = load_bundle(record.get_local_copy(), endpoint=ep)
            except Exception as ex:
                print("engine-server: failed loading {}: {}".format(url, ex))
                continue
            model = CompiledModel(
                ep, bundle, params, key=url, dispatcher=self._dispatcher
            )
            import jax

            if jax.process_count() == 1:
                # multi-host: warmup would enter the executable on THIS host
                # alone, outside the broadcast order — an executable with
                # cross-host collectives would deadlock the slice. First
                # dispatched batch compiles on all hosts in step instead.
                model.warmup()
            with self._lock:
                self._models[url] = model  # atomic swap; old entry GC'd
                self._hashes[url] = content_hash
            loaded += 1

        stale = set(self._models) - set(wanted)
        for url in stale:
            with self._lock:
                self._models.pop(url, None)
                self._hashes.pop(url, None)
        return loaded
