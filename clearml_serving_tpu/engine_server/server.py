"""JAX engine server: gRPC tensor-infer service over the model repo.

Replaces the tritonserver C++ process in the reference topology (SURVEY.md
§2.9 row 1): the router's ``jax_grpc`` client engine sends named typed tensors;
this process owns the TPU devices, runs bucket-compiled XLA executables behind
per-model dynamic batchers, polls the control plane for model changes (hot
swap), and exports Prometheus metrics (request/batch counters + per-chip HBM
gauges) on a sidecar port — the same scrape surface tritonserver exposes
on :8002.

Run: ``python -m clearml_serving_tpu.engine_server.server`` with
``TPUSERVE_SERVICE_ID`` (and optionally ``TPUSERVE_ENGINE_PORT``,
``TPUSERVE_ENGINE_METRICS_PORT``, ``TPUSERVE_POLL_FREQ``).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Optional

import grpc
import numpy as np

from . import protocol
from .repo import EngineModelRepo


class _EngineHandler(grpc.GenericRpcHandler):
    """Generic byte-level handler — no protoc codegen (protocol.py docs)."""

    def __init__(self, servicer: "EngineServer"):
        self._servicer = servicer

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == protocol.INFER_METHOD:
            return grpc.unary_unary_rpc_method_handler(
                self._servicer.infer,
                request_deserializer=None,
                response_serializer=None,
            )
        if method == protocol.STATUS_METHOD:
            return grpc.unary_unary_rpc_method_handler(
                self._servicer.status,
                request_deserializer=None,
                response_serializer=None,
            )
        return None


class EngineMetrics:
    """Per-model gRPC-path observability: latency + queue-delay histograms and
    outcome-labelled request counters (the Triton server exports the
    equivalent nv_inference_{request_duration,queue_duration,count} series —
    triton_helper.py relays them; gauges alone lose rate()/quantile query
    power)."""

    def __init__(self, registry=None):
        from prometheus_client import REGISTRY, Counter, Histogram

        registry = registry if registry is not None else REGISTRY
        self.latency = Histogram(
            "engine_infer_latency_seconds",
            "end-to-end gRPC infer latency",
            ["model"],
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
            registry=registry,
        )
        self.queue_delay = Histogram(
            "engine_queue_delay_seconds",
            "dynamic-batcher queue wait (enqueue to batch start)",
            ["model"],
            buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25),
            registry=registry,
        )
        self.requests = Counter(
            "engine_infer_requests_total",
            "infer RPCs by outcome",
            ["model", "outcome"],
            registry=registry,
        )
        # padding efficiency of the bucket-padding path: real request rows
        # vs rows added purely to reach the compiled bucket shape. A high
        # padded/real ratio means the bucket set or dynamic-batching knobs
        # are mis-tuned for the traffic (rate() these two against each other)
        self.batch_rows = Counter(
            "engine_batch_rows_total",
            "rows entering executed batches, by kind (real request rows vs "
            "bucket-padding waste)",
            ["model", "kind"],
            registry=registry,
        )

    def wire_batcher(self, name: str, batcher) -> None:
        if batcher.on_queue_delay is None:
            observe = self.queue_delay.labels(model=name).observe
            batcher.on_queue_delay = observe
        if batcher.on_padding is None:
            real_c = self.batch_rows.labels(model=name, kind="real")
            pad_c = self.batch_rows.labels(model=name, kind="padded")

            def on_padding(real_rows: int, padded_rows: int) -> None:
                if real_rows:
                    real_c.inc(real_rows)
                if padded_rows:
                    pad_c.inc(padded_rows)

            batcher.on_padding = on_padding


class EngineServer:
    def __init__(self, repo: EngineModelRepo, metrics: Optional[EngineMetrics] = None):
        self.repo = repo
        self.metrics = metrics

    def _count(self, model_name: str, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.requests.labels(model=model_name, outcome=outcome).inc()

    async def infer(self, request_bytes: bytes, context) -> bytes:
        tic = time.monotonic()
        try:
            request = protocol.decode_infer_request(request_bytes)
        except Exception as ex:
            self._count("_undecodable", "bad_request")
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "bad request encoding: {}".format(ex)
            )
        model_name = request["model"]
        model = self.repo.get(model_name, request.get("version") or None)
        if model is None:
            self._count(model_name, "not_found")
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                "model {!r} version {!r} not loaded (have: {})".format(
                    model_name, request.get("version"), sorted(self.repo.list_models())
                ),
            )
        # metric label = the repo's canonical key, not the client-supplied
        # name: a model reachable under several names (with/without version
        # suffix) must not split or mis-attribute its series
        label = model.key
        if self.metrics is not None:
            self.metrics.wire_batcher(label, model.batcher)
        inputs_by_name = request["inputs"]
        # order inputs per the endpoint spec; single-input models accept any name
        if model.input_names:
            try:
                ordered = [inputs_by_name[name] for name in model.input_names]
            except KeyError as ex:
                self._count(label, "bad_request")
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "missing input {} (expected {})".format(ex, model.input_names),
                )
        else:
            ordered = list(inputs_by_name.values())
        try:
            outputs = await model.batcher.infer(ordered)
        except Exception as ex:
            self._count(label, "error")
            await context.abort(
                grpc.StatusCode.INTERNAL, "inference failed: {}".format(ex)
            )
        names = model.output_names
        named = {
            (names[i] if i < len(names) else "output_{}".format(i)): np.asarray(out)
            for i, out in enumerate(outputs)
        }
        self._count(label, "ok")
        if self.metrics is not None:
            self.metrics.latency.labels(model=label).observe(
                time.monotonic() - tic
            )
        return protocol.encode_infer_response(named)

    async def status(self, request_bytes: bytes, context) -> bytes:
        import jax

        return protocol.encode_obj(
            {
                "models": self.repo.list_models(),
                "devices": [str(d) for d in jax.devices()],
                "time": time.time(),
            }
        )


def make_server(
    repo: EngineModelRepo, port: int = 0, metrics: Optional[EngineMetrics] = None
) -> "tuple[grpc.aio.Server, int]":
    server = grpc.aio.server(
        options=[
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ]
    )
    server.add_generic_rpc_handlers((_EngineHandler(EngineServer(repo, metrics)),))
    bound_port = server.add_insecure_port("[::]:{}".format(port))
    return server, bound_port


async def serve(service_id: Optional[str] = None) -> None:
    from prometheus_client import Counter, Gauge, start_http_server

    from ..serving.model_request_processor import ModelRequestProcessor
    from ..statistics.metrics import StatisticsController

    from ..serving.main import maybe_start_profiler

    maybe_start_profiler()
    import jax

    dispatcher = None
    if jax.process_count() > 1:
        from ..parallel.multihost import HostZeroDispatcher

        dispatcher = HostZeroDispatcher()
    processor = ModelRequestProcessor(service_id=service_id)
    repo = EngineModelRepo(processor, dispatcher=dispatcher)
    repo.sync()

    port = int(os.environ.get("TPUSERVE_ENGINE_PORT", 8001))
    metrics_port = int(os.environ.get("TPUSERVE_ENGINE_METRICS_PORT", 8002))
    poll_freq_sec = float(os.environ.get("TPUSERVE_POLL_FREQ", 1.0)) * 60.0

    try:
        start_http_server(metrics_port)
        requests_g = Gauge("engine_requests_served", "requests served", ["model"])
        batches_g = Gauge("engine_batches_executed", "batches executed", ["model"])
        metrics = EngineMetrics()
        hbm = StatisticsController("", processor=None)
    except OSError:
        requests_g = batches_g = hbm = metrics = None

    server, bound = make_server(repo, port, metrics)
    await server.start()
    print("engine server: gRPC on :{} ({} models)".format(bound, len(repo.list_models())))

    async def reconcile_loop():
        while True:
            await asyncio.sleep(poll_freq_sec)
            try:
                try:
                    await asyncio.to_thread(repo.sync)
                finally:
                    if dispatcher is not None:
                        # heartbeat: lets followers leave recv() and re-sync.
                        # Sent even when this host's sync flaked — follower
                        # liveness must not depend on host-0 sync success.
                        # Via the dispatcher so it serializes with in-flight
                        # RUN broadcasts (ordering contract in multihost.py)
                        await asyncio.to_thread(dispatcher.noop)
                if requests_g is not None:
                    for name, info in repo.list_models().items():
                        requests_g.labels(model=name).set(info["requests_served"])
                        batches_g.labels(model=name).set(info["batches_executed"])
                    hbm.update_device_gauges()
            except Exception as ex:
                print("engine server reconcile error: {}".format(ex))

    asyncio.get_running_loop().create_task(reconcile_loop())
    try:
        await server.wait_for_termination()
    finally:
        if dispatcher is not None:
            dispatcher.stop()


def serve_follower(service_id: Optional[str] = None) -> None:
    """Secondary-controller main: replay host-0's dispatch steps.

    Binds NO service ports. The follower syncs the same model repo from the
    control plane, then enters the broadcast loop; a NOOP heartbeat from
    host 0's reconcile loop gives it windows to re-sync (hot swaps land on
    all hosts within one poll period)."""
    import jax

    from ..parallel.multihost import follower_loop

    from ..serving.model_request_processor import ModelRequestProcessor

    processor = ModelRequestProcessor(service_id=service_id)
    repo = EngineModelRepo(processor)
    repo.sync()
    print(
        "engine server follower: process {} of {} ({} models)".format(
            jax.process_index(), jax.process_count(), len(repo.list_models())
        )
    )

    def resolve(key: str):
        model = repo.get_by_key(key)
        if model is None:
            # host 0 may have loaded it after our last sync. Retry the sync
            # a few times so one dropped control-plane packet isn't
            # slice-fatal; only after retries is this a real desync, and
            # follower_loop then fails LOUDLY (crash + supervisor restart)
            # rather than silently skipping a broadcast step the rest of
            # the slice is already inside (silent skip = undiagnosable
            # collective deadlock).
            for attempt in range(3):
                try:
                    repo.sync()
                except Exception as ex:
                    print("follower sync error (try {}): {}".format(attempt + 1, ex))
                    time.sleep(0.5 * (attempt + 1))
                    continue
                model = repo.get_by_key(key)
                if model is not None:
                    break
        return model.run_batch if model is not None else None

    from ..parallel import multihost

    class _SyncingChannel(multihost.BroadcastChannel):
        def recv(self):
            op, payload = super().recv()
            if op == multihost.OP_NOOP:
                try:
                    repo.sync()
                except Exception as ex:
                    print("follower sync error: {}".format(ex))
            return op, payload

    follower_loop(
        resolve,
        channel=_SyncingChannel(),
        on_error=lambda key, ex: print(
            "follower: replay of {!r} failed: {}".format(key, ex)
        ),
    )


def main() -> None:
    from ..parallel.distributed import initialize_distributed, is_primary_host

    initialize_distributed()  # no-op single-host; TPUSERVE_COORDINATOR multi-host
    service_id = os.environ.get("TPUSERVE_SERVICE_ID") or None
    if not is_primary_host():
        # Secondary hosts bind NO service ports: they replay host-0's
        # broadcast dispatch steps so every controller of the slice enters
        # the same executables in the same order (multi-controller SPMD,
        # SURVEY.md §7 hard part 6).
        serve_follower(service_id)
        return
    asyncio.run(serve(service_id))


if __name__ == "__main__":
    main()
