from .base import BaseEngineRequest, get_engine_cls, load_engine_modules, register_engine

# Import engine implementations so they self-register.
from . import cpu_engines  # noqa: F401
from . import jax_engine  # noqa: F401
from . import grpc_client  # noqa: F401
from ..llm import openai_api as _llm_engine  # noqa: F401

__all__ = [
    "BaseEngineRequest",
    "get_engine_cls",
    "load_engine_modules",
    "register_engine",
]
