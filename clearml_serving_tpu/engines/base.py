"""Engine framework: registry + hot-loaded user code + per-phase async dispatch.

Capability parity with the reference's BasePreprocessRequest
(clearml_serving/serving/preprocess_service.py:25-264):

- one engine-request instance **per endpoint per process** (thread-safety is the
  user code's responsibility — per-request scratch goes in the ``state`` dict);
- the user's preprocess artifact is downloaded from the control plane, cached
  locally, re-loaded when its content hash changes, and imported either as a
  single module file or an extracted zip package with ``__init__.py``;
- a ``send_request`` callable is injected into user code for pipeline
  composition (HTTP POST back to this serving service);
- async-ness is declared per phase via class flags the orchestrator branches on;
- engines self-register under a string name with optional heavy modules that
  are imported once pre-fork via :func:`load_engine_modules`.
"""

from __future__ import annotations

import asyncio
import importlib
import importlib.util
import os
import shutil
import sys
import threading
import zipfile
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Type

import requests

from ..serving.endpoints import ModelEndpoint, register_engine_name
from ..utils.files import read_json

_ENGINE_REGISTRY: Dict[str, Type["BaseEngineRequest"]] = {}
_ENGINE_MODULES: Dict[str, List[str]] = {}


def register_engine(name: str, modules: Optional[List[str]] = None):
    """Class decorator registering an engine implementation under ``name``."""

    def _decorator(cls):
        _ENGINE_REGISTRY[name] = cls
        _ENGINE_MODULES[name] = list(modules or [])
        register_engine_name(name)
        cls.engine_name = name
        return cls

    return _decorator


def get_engine_cls(name: str) -> Type["BaseEngineRequest"]:
    try:
        return _ENGINE_REGISTRY[name]
    except KeyError:
        raise ValueError(
            "unknown engine {!r}; registered: {}".format(name, sorted(_ENGINE_REGISTRY))
        ) from None


def load_engine_modules() -> None:
    """Pre-fork import of every engine's heavy dependencies (reference
    preprocess_service.py:245-253): call once in the parent so forked workers
    share the pages."""
    for name, modules in _ENGINE_MODULES.items():
        for mod in modules:
            try:
                importlib.import_module(mod)
            except ImportError:
                pass


class EndpointModelError(RuntimeError):
    """Model payload missing/unloadable (maps to HTTP 422 in the router)."""


class BaseEngineRequest:
    """Per-endpoint engine instance. Subclasses implement the three phases."""

    engine_name = "base"
    is_preprocess_async = False
    is_process_async = False
    is_postprocess_async = False

    # Server-wide config pushed by the orchestrator on every sync
    # (reference BasePreprocessRequest.set_server_config).
    _server_config: Dict[str, Any] = {}

    def __init__(
        self,
        endpoint: ModelEndpoint,
        service=None,          # state.ServingService (artifact source), optional
        registry=None,         # state.ModelRegistry (model payloads), optional
        cache_dir: Optional[str] = None,
    ):
        self.endpoint = endpoint
        self._service = service
        self._registry = registry
        self._cache_dir = Path(
            cache_dir
            or os.environ.get("TPUSERVE_CACHE_DIR")
            or (Path.home() / ".tpu-serving" / "cache")
        )
        self._preprocess = None          # user Preprocess instance
        self._preprocess_hash = None     # artifact content hash when loaded
        self._model: Any = None
        self._model_local_path: Optional[str] = None

        if endpoint.preprocess_artifact:
            self._load_user_code()
        self._load_model()

    # -- server config -----------------------------------------------------

    @classmethod
    def set_server_config(cls, config: Dict[str, Any]) -> None:
        BaseEngineRequest._server_config = dict(config or {})

    @classmethod
    def get_server_config(cls) -> Dict[str, Any]:
        return BaseEngineRequest._server_config

    # -- user code hot-loading ---------------------------------------------

    def _artifact_cache_path(self, name: str) -> Path:
        return self._cache_dir / "artifacts" / self.endpoint.serving_url / name

    def _fetch_artifact(self, name: str) -> Optional[Path]:
        """Local copy of the artifact; re-copied when the stored hash changed
        (reference preprocess_service.py:68-82)."""
        if self._service is None:
            return None
        src = self._service.get_artifact(name)
        if src is None:
            return None
        new_hash = self._service.artifact_hash(name)
        dest_dir = self._artifact_cache_path(name)
        meta_path = dest_dir / ".hash.json"
        meta = read_json(meta_path) or {}
        dest = dest_dir / src.name
        if meta.get("hash") != new_hash or not dest.exists():
            if dest_dir.exists():
                shutil.rmtree(dest_dir)
            dest_dir.mkdir(parents=True)
            shutil.copyfile(str(src), str(dest))
            from ..utils.files import atomic_write_json
            atomic_write_json(meta_path, {"hash": new_hash})
        return dest

    def _load_user_code(self) -> None:
        name = self.endpoint.preprocess_artifact
        path = self._fetch_artifact(name)
        if path is None:
            raise EndpointModelError(
                "preprocess artifact {!r} not found for endpoint {!r}".format(
                    name, self.endpoint.serving_url
                )
            )
        new_hash = self._service.artifact_hash(name)
        if self._preprocess is not None and new_hash == self._preprocess_hash:
            return
        module = self._import_user_module(path)
        user_cls = getattr(module, "Preprocess", None)
        if user_cls is None:
            raise EndpointModelError(
                "artifact {!r} does not define a Preprocess class".format(name)
            )
        instance = user_cls()
        instance.serving_config = self.endpoint.as_dict(remove_null_entries=True)
        # Inject pipeline-composition hook unless user code provides its own.
        if not hasattr(instance, "send_request"):
            instance.send_request = self._make_send_request()
        old = self._preprocess
        self._preprocess = instance
        self._preprocess_hash = new_hash
        if old is not None and hasattr(old, "unload"):
            try:
                old.unload()
            except Exception as ex:
                # hot swap proceeds — the NEW code is already installed —
                # but a throwing unload leaks whatever it held; leave a trace
                print("unload of replaced preprocess failed: {}".format(ex))

    def _import_user_module(self, path: Path):
        """Import a single .py file, or a zip package (extracted; must contain
        ``__init__.py`` at its root)."""
        mod_name = "tpuserve_user_{}".format(
            self.endpoint.serving_url.replace("/", "_").replace("-", "_")
        )
        if path.suffix == ".zip":
            extract_dir = path.parent / "package"
            if extract_dir.exists():
                shutil.rmtree(extract_dir)
            with zipfile.ZipFile(path) as zf:
                zf.extractall(str(extract_dir))
            if not (extract_dir / "__init__.py").is_file():
                raise EndpointModelError(
                    "preprocess package zip must contain a top-level __init__.py"
                )
            spec = importlib.util.spec_from_file_location(
                mod_name, str(extract_dir / "__init__.py"),
                submodule_search_locations=[str(extract_dir)],
            )
        else:
            spec = importlib.util.spec_from_file_location(mod_name, str(path))
        module = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = module
        spec.loader.exec_module(module)
        return module

    def _make_send_request(self) -> Callable:
        def send_request(endpoint: str, version: Optional[str] = None, data: Any = None):
            base = self.get_server_config().get("serving_base_url") or ""
            url = "/".join(p.strip("/") for p in (base, endpoint, version or "") if p)
            r = requests.post(url, json=data, timeout=self.request_timeout())
            return r.json() if r.ok else None

        return send_request

    @staticmethod
    def request_timeout() -> float:
        # 0.8 x serving timeout (reference preprocess_service.py:48-49).
        return 0.8 * float(os.environ.get("TPUSERVE_SERVING_TIMEOUT", 600))

    # -- model loading ------------------------------------------------------

    def _load_model(self) -> None:
        """Resolve the model payload to a local path, then let user ``load()``
        or the engine's native loader build the model object."""
        if self.endpoint.model_id and self._registry is not None:
            record = self._registry.get(self.endpoint.model_id)
            if record is None:
                raise EndpointModelError(
                    "model {!r} not found in registry".format(self.endpoint.model_id)
                )
            self._model_local_path = record.get_local_copy()
        if self._preprocess is not None and hasattr(self._preprocess, "load"):
            loaded = self._preprocess.load(self._model_local_path)
            if loaded is not None:
                self._model = loaded
                return
        if self._model is None:
            self._model = self._native_load()

    def _native_load(self) -> Any:
        """Engine-specific default model loader (no-op for pure-custom)."""
        return None

    # -- request phases ------------------------------------------------------

    def preprocess(self, body: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "preprocess"):
            return self._preprocess.preprocess(body, state, collect_fn)
        return body

    def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "process"):
            return self._preprocess.process(data, state, collect_fn)
        return data

    def postprocess(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "postprocess"):
            return self._preprocess.postprocess(data, state, collect_fn)
        return data

    def unload(self) -> None:
        if self._preprocess is not None and hasattr(self._preprocess, "unload"):
            try:
                self._preprocess.unload()
            except Exception as ex:
                print("preprocess unload failed: {}".format(ex))
        self._preprocess = None
        self._model = None

    def __del__(self):
        try:
            self.unload()
        except Exception:  # tpuserve: ignore[TPU401] finalizer: exceptions here are unraisable by design
            pass
