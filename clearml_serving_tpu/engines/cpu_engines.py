"""CPU engines: sklearn / xgboost / lightgbm / custom / custom_async.

Capability parity with the reference's CPU engine set
(clearml_serving/serving/preprocess_service.py:449-616). These are
engine-agnostic Python paths carried over conceptually: joblib/booster loading +
``predict``, user-code-only ``custom``, and a fully-async ``custom_async``
variant whose injected ``send_request`` is awaitable.

xgboost / lightgbm are gated on import availability (not baked into every
image); constructing an endpoint for a missing engine raises a clear
EndpointModelError instead of an ImportError at call time.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from .base import BaseEngineRequest, EndpointModelError, register_engine


@register_engine("sklearn", modules=["joblib", "sklearn"])
class SklearnEngineRequest(BaseEngineRequest):
    def _native_load(self) -> Any:
        if not self._model_local_path:
            raise EndpointModelError(
                "sklearn endpoint {!r} has no model payload".format(
                    self.endpoint.serving_url
                )
            )
        import joblib

        return joblib.load(self._model_local_path)

    def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "process"):
            return self._preprocess.process(data, state, collect_fn)
        return self._model.predict(data)


@register_engine("xgboost", modules=["xgboost"])
class XGBoostEngineRequest(BaseEngineRequest):
    def _native_load(self) -> Any:
        try:
            import xgboost  # noqa
        except ImportError:
            raise EndpointModelError(
                "xgboost is not installed in this serving image"
            ) from None
        if not self._model_local_path:
            raise EndpointModelError("xgboost endpoint has no model payload")
        booster = xgboost.Booster()
        booster.load_model(self._model_local_path)
        return booster

    def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "process"):
            return self._preprocess.process(data, state, collect_fn)
        import xgboost

        return self._model.predict(xgboost.DMatrix(data))


@register_engine("lightgbm", modules=["lightgbm"])
class LightGBMEngineRequest(BaseEngineRequest):
    def _native_load(self) -> Any:
        try:
            import lightgbm  # noqa
        except ImportError:
            raise EndpointModelError(
                "lightgbm is not installed in this serving image"
            ) from None
        if not self._model_local_path:
            raise EndpointModelError("lightgbm endpoint has no model payload")
        return lightgbm.Booster(model_file=self._model_local_path)

    def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "process"):
            return self._preprocess.process(data, state, collect_fn)
        return self._model.predict(data)


@register_engine("custom")
class CustomEngineRequest(BaseEngineRequest):
    """Inference entirely in user code: ``Preprocess.process`` is the model."""

    def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is None or not hasattr(self._preprocess, "process"):
            raise EndpointModelError(
                "custom endpoint {!r} requires a Preprocess.process()".format(
                    self.endpoint.serving_url
                )
            )
        return self._preprocess.process(data, state, collect_fn)


@register_engine("custom_async")
class CustomAsyncEngineRequest(BaseEngineRequest):
    """All three phases async; injected ``send_request`` is awaitable
    (reference preprocess_service.py:520-616)."""

    is_preprocess_async = True
    is_process_async = True
    is_postprocess_async = True

    def _make_send_request(self):
        async def send_request(
            endpoint: str, version: Optional[str] = None, data: Any = None
        ):
            import aiohttp

            base = self.get_server_config().get("serving_base_url") or ""
            url = "/".join(p.strip("/") for p in (base, endpoint, version or "") if p)
            timeout = aiohttp.ClientTimeout(total=self.request_timeout())
            async with aiohttp.ClientSession(timeout=timeout) as session:
                async with session.post(url, json=data) as resp:
                    if resp.status != 200:
                        return None
                    return await resp.json()

        return send_request

    async def _maybe_await(self, value):
        if asyncio.iscoroutine(value):
            return await value
        return value

    async def preprocess(self, body: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "preprocess"):
            return await self._maybe_await(
                self._preprocess.preprocess(body, state, collect_fn)
            )
        return body

    async def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is None or not hasattr(self._preprocess, "process"):
            raise EndpointModelError(
                "custom_async endpoint {!r} requires a Preprocess.process()".format(
                    self.endpoint.serving_url
                )
            )
        return await self._maybe_await(self._preprocess.process(data, state, collect_fn))

    async def postprocess(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "postprocess"):
            return await self._maybe_await(
                self._preprocess.postprocess(data, state, collect_fn)
            )
        return data
