"""`jax_grpc` engine: router-side client to the JAX engine server.

Capability parity with the reference's Triton client engine
(clearml_serving/serving/preprocess_service.py:267-446): async gRPC with a
per-event-loop channel cache, env-tunable channel options
(``TPUSERVE_GRPC_<OPTION>`` → ``grpc.<option>``), optional gzip compression,
model addressed as ``{serving_url}`` + version, numpy marshalling per the
endpoint I/O spec, single-output unwrap.
"""

from __future__ import annotations

import asyncio
import os
import random
import time
import weakref
from typing import Any, Dict, List, Optional

import numpy as np

from .base import BaseEngineRequest, EndpointModelError, register_engine
from ..errors import UpstreamTimeoutError, UpstreamUnavailableError
from ..llm import faults

# NOTE: ..engine_server.protocol (msgpack) and grpc are imported lazily inside
# methods so importing the engine registry never requires optional deps.

# upstream statuses worth retrying: the engine server restarting
# (UNAVAILABLE) or a transient per-call deadline (DEADLINE_EXCEEDED)
_TRANSIENT_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED")

# scrape-time retry counters (statistics.metrics register_engine_lifecycle
# can export them; plain dict so no prometheus dependency here)
RETRY_STATS: Dict[str, int] = {"attempts": 0, "retries": 0, "exhausted": 0}


def grpc_lifecycle_stats() -> Dict[str, Any]:
    """Provider for the statistics lifecycle collector."""
    return {"grpc": dict(RETRY_STATS)}


def _grpc_code_name(ex: BaseException) -> Optional[str]:
    """Status-code name for a failed attempt: real AioRpcError or an
    injected fault carrying grpc_code (chaos tests run without a server)."""
    injected = getattr(ex, "grpc_code", None)
    if injected:
        return str(injected)
    code = getattr(ex, "code", None)
    if callable(code):
        try:
            return code().name
        except Exception:
            return None
    return None


def _channel_options() -> List:
    options = [
        ("grpc.max_receive_message_length", 256 * 1024 * 1024),
        ("grpc.max_send_message_length", 256 * 1024 * 1024),
    ]
    for key, value in os.environ.items():
        if key.startswith("TPUSERVE_GRPC_"):
            opt = "grpc." + key[len("TPUSERVE_GRPC_"):].lower()
            try:
                value = int(value)
            except ValueError:
                pass
            options.append((opt, value))
    return options


@register_engine("jax_grpc", modules=["grpc"])
class JaxGrpcEngineRequest(BaseEngineRequest):
    is_process_async = True

    def __init__(self, *args, **kwargs):
        self._channels: Dict[int, Any] = {}  # per-event-loop aio channels
        super().__init__(*args, **kwargs)

    def _native_load(self) -> Any:
        # model lives in the engine-server process; nothing to load here.
        # Expose the module-wide retry counters on the serving registry
        # (idempotent; keyed once for all jax_grpc endpoints).
        try:
            from ..statistics.metrics import register_engine_lifecycle

            register_engine_lifecycle(grpc_lifecycle_stats, key="grpc_client")
        except Exception:  # tpuserve: ignore[TPU401] metrics registry is optional observability, never load-bearing
            pass
        return self.endpoint.model_id or True

    def _address(self) -> str:
        addr = self.get_server_config().get("engine_grpc_server") or os.environ.get(
            "TPUSERVE_DEFAULT_ENGINE_GRPC_ADDR", "127.0.0.1:8001"
        )
        return addr

    def _get_channel(self):
        import grpc

        loop = asyncio.get_running_loop()
        entry = self._channels.get(id(loop))
        if entry is not None:
            loop_ref, channel = entry
            if loop_ref() is loop:  # id() reuse after a dead loop is detected
                return channel
            self._channels.pop(id(loop), None)
        # drop channels whose loops died (fd hygiene)
        for key in [k for k, (ref, _) in self._channels.items() if ref() is None]:
            self._channels.pop(key, None)
        compression = None
        if str(self.get_server_config().get("engine_grpc_compression", "")).lower() in (
            "1", "true", "gzip",
        ):
            compression = grpc.Compression.Gzip
        channel = grpc.aio.insecure_channel(
            self._address(), options=_channel_options(), compression=compression
        )
        self._channels[id(loop)] = (weakref.ref(loop), channel)
        return channel

    def _body_to_inputs(self, data: Any) -> Dict[str, np.ndarray]:
        names = self.endpoint.input_name or []
        types = self.endpoint.input_type or []
        if isinstance(data, dict) and names:
            raw = {}
            for i, name in enumerate(names):
                if name not in data:
                    raise ValueError("missing input {!r}".format(name))
                dtype = np.dtype(types[i]) if i < len(types) else np.float32
                raw[name] = np.asarray(data[name], dtype=dtype)
            return raw
        if isinstance(data, dict):
            return {k: np.asarray(v) for k, v in data.items()}
        dtype = np.dtype(types[0]) if types else np.float32
        name = names[0] if names else "input_0"
        return {name: np.asarray(data, dtype=dtype)}

    def _retry_config(self) -> Dict[str, float]:
        """Retry policy for transient upstream failures. Env-tunable:
        TPUSERVE_GRPC_RETRIES (attempt ceiling, default 3),
        TPUSERVE_GRPC_RETRY_BACKOFF (first delay seconds, default 0.05),
        TPUSERVE_GRPC_RETRY_BACKOFF_MAX (per-delay cap, default 2.0),
        TPUSERVE_GRPC_RETRY_BUDGET (total seconds across attempts,
        default 10). Server config keys of the same lowercase names win."""
        cfg = self.get_server_config()

        def knob(name: str, default: float) -> float:
            v = cfg.get(name.lower(), os.environ.get(name.upper()))
            return float(v) if v is not None else default

        return {
            "attempts": knob("tpuserve_grpc_retries", 3),
            "backoff": knob("tpuserve_grpc_retry_backoff", 0.05),
            "backoff_max": knob("tpuserve_grpc_retry_backoff_max", 2.0),
            "budget": knob("tpuserve_grpc_retry_budget", 10.0),
        }

    async def _call_with_retry(self, call, payload, timeout: float):
        """One logical inference call with jittered exponential backoff on
        transient upstream codes, bounded by an attempt ceiling AND a total
        time budget. After exhaustion the last transient failure maps to a
        structured 503 (UNAVAILABLE) / 504 (DEADLINE_EXCEEDED) instead of a
        raw AioRpcError traceback; NOT_FOUND keeps its 422 mapping."""
        policy = self._retry_config()
        attempts = max(1, int(policy["attempts"]))
        t0 = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            RETRY_STATS["attempts"] += 1
            try:
                if faults.active():
                    faults.fire("grpc.call", attempt=attempt)
                return await call(payload, timeout=timeout)
            except Exception as ex:
                code = _grpc_code_name(ex)
                if code == "NOT_FOUND":
                    detail = getattr(ex, "details", None)
                    raise EndpointModelError(
                        str(detail() if callable(detail) else ex)
                    ) from None
                if code not in _TRANSIENT_CODES:
                    raise
                delay = min(
                    policy["backoff_max"],
                    policy["backoff"] * (2 ** (attempt - 1)),
                ) * (0.5 + random.random())  # full jitter in [0.5x, 1.5x)
                out_of_budget = (
                    time.monotonic() - t0 + delay > policy["budget"]
                )
                if attempt >= attempts or out_of_budget:
                    RETRY_STATS["exhausted"] += 1
                    msg = (
                        "engine upstream {} after {} attempt(s): {}".format(
                            code, attempt, ex
                        )
                    )
                    if code == "DEADLINE_EXCEEDED":
                        raise UpstreamTimeoutError(msg) from ex
                    raise UpstreamUnavailableError(msg) from ex
                RETRY_STATS["retries"] += 1
                await asyncio.sleep(delay)

    async def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "process"):
            out = self._preprocess.process(data, state, collect_fn)
            if asyncio.iscoroutine(out):
                out = await out
            return out
        if isinstance(data, np.ndarray):
            inputs = self._body_to_inputs(data)
        elif isinstance(data, dict) and all(isinstance(v, np.ndarray) for v in data.values()):
            inputs = data
        else:
            inputs = self._body_to_inputs(data)

        import grpc

        from ..engine_server import protocol

        channel = self._get_channel()
        call = channel.unary_unary(
            protocol.INFER_METHOD,
            request_serializer=None,
            response_deserializer=None,
        )
        payload = protocol.encode_infer_request(
            model=self.endpoint.serving_url,
            version=self.endpoint.version,
            inputs=inputs,
            output_names=self.endpoint.output_name,
        )
        response = await self._call_with_retry(
            call, payload, timeout=self.request_timeout()
        )
        outputs = protocol.decode_infer_response(response)
        if len(outputs) == 1:
            return next(iter(outputs.values()))
        return outputs

    def postprocess(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "postprocess"):
            return self._preprocess.postprocess(data, state, collect_fn)
        if isinstance(data, np.ndarray):
            return data.tolist()
        if isinstance(data, dict):
            return {
                k: (v.tolist() if isinstance(v, np.ndarray) else v) for k, v in data.items()
            }
        return data
