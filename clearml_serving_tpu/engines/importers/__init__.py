"""Model importers: foreign formats -> servable JAX bundles.

Replaces the reference's reliance on Triton's multi-backend model repository
(reference engines/triton/triton_helper.py:159-183 materializes savedmodel /
model.pt / onnx dirs / graphdef / plan files for the C++ server): here each
foreign graph is converted into a JAX function + params tree that jit/pjit
compiles for TPU.

- onnx_import: stock ``.onnx`` files -> JAX interpreter bundle (zero-dep
  protobuf parsing in onnx_proto).
- torchscript_import: TorchScript ``model.pt`` -> ONNX (in-memory, classic
  exporter) -> the same JAX bundle.
"""

# late import in load helpers to keep the package importable mid-build
try:
    from .onnx_import import load_onnx_bundle  # noqa: F401
except ImportError:  # onnx_import not present yet during incremental builds
    pass
