"""Model importers: foreign formats -> servable JAX bundles.

Replaces the reference's reliance on Triton's multi-backend model repository
(reference engines/triton/triton_helper.py:159-183 materializes savedmodel /
model.pt / onnx dirs / graphdef / plan files for the C++ server): here each
foreign graph is converted into a JAX function + params tree that jit/pjit
compiles for TPU.

- onnx_import: stock ``.onnx`` files -> JAX interpreter bundle (zero-dep
  protobuf parsing in onnx_proto).
- torchscript_import: TorchScript ``model.pt`` -> ONNX (in-memory, classic
  exporter) -> the same JAX bundle.
"""

# Tolerate only the file-absent case (incremental builds); an ImportError
# raised INSIDE onnx_import (broken transitive dep) must propagate, not
# silently strip the symbol from the package.
import importlib.util as _ilu

if _ilu.find_spec(__name__ + ".onnx_import") is not None:
    from .onnx_import import load_onnx_bundle  # noqa: F401
