"""HF Whisper checkpoint -> whisper jax bundle.

Maps transformers.WhisperForConditionalGeneration state dicts onto
models/whisper.py's tree (fidelity pinned in tests/test_whisper.py), and
captures everything serving needs beside the weights:

- the mel filterbank (from the checkpoint's WhisperFeatureExtractor — saved
  into the bundle so serving never re-derives slaney filters),
- the decoder prompt ids (<|startoftranscript|> [lang] <|transcribe|> /
  <|translate|> <|notimestamps|>) for both audio tasks,
- eot/eos ids.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def convert_state_dict(sd: Dict[str, Any], cfg: dict) -> Dict[str, Any]:
    """torch state dict (or numpy mapping) -> whisper param tree."""

    def t(name):  # tensor by HF name -> numpy
        v = sd[name]
        return v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)

    def lin(prefix, bias=True):
        out = {"w": t(prefix + ".weight").T}  # torch [out,in] -> [in,out]
        if bias:
            out["b"] = t(prefix + ".bias")
        return out

    def ln(prefix):
        return {"scale": t(prefix + ".weight"), "bias": t(prefix + ".bias")}

    def attn(prefix):
        return {
            "q": lin(prefix + ".q_proj"),
            "k": lin(prefix + ".k_proj", bias=False),  # whisper: k has no bias
            "v": lin(prefix + ".v_proj"),
            "o": lin(prefix + ".out_proj"),
        }

    enc = "model.encoder." if "model.encoder.conv1.weight" in sd else "encoder."
    dec = "model.decoder." if "model.decoder.embed_tokens.weight" in sd else "decoder."

    params: Dict[str, Any] = {
        # torch conv1d weight [out, in, k] -> lax NWC/WIO [k, in, out]
        "conv1": {
            "w": t(enc + "conv1.weight").transpose(2, 1, 0),
            "b": t(enc + "conv1.bias"),
        },
        "conv2": {
            "w": t(enc + "conv2.weight").transpose(2, 1, 0),
            "b": t(enc + "conv2.bias"),
        },
        "enc_pos": t(enc + "embed_positions.weight"),
        "enc_final_norm": ln(enc + "layer_norm"),
        "embed": t(dec + "embed_tokens.weight"),
        "dec_pos": t(dec + "embed_positions.weight"),
        "dec_final_norm": ln(dec + "layer_norm"),
        "enc_layers": [],
        "dec_layers": [],
    }
    for i in range(int(cfg["n_audio_layers"])):
        p = "{}layers.{}.".format(enc, i)
        params["enc_layers"].append(
            {
                "attn_norm": ln(p + "self_attn_layer_norm"),
                "attn": attn(p + "self_attn"),
                "ffn_norm": ln(p + "final_layer_norm"),
                "fc1": lin(p + "fc1"),
                "fc2": lin(p + "fc2"),
            }
        )
    for i in range(int(cfg["n_text_layers"])):
        p = "{}layers.{}.".format(dec, i)
        params["dec_layers"].append(
            {
                "attn_norm": ln(p + "self_attn_layer_norm"),
                "attn": attn(p + "self_attn"),
                "cross_norm": ln(p + "encoder_attn_layer_norm"),
                "cross": attn(p + "encoder_attn"),
                "ffn_norm": ln(p + "final_layer_norm"),
                "fc1": lin(p + "fc1"),
                "fc2": lin(p + "fc2"),
            }
        )
    return params


def config_from_hf(hf_config) -> dict:
    return dict(
        vocab_size=int(hf_config.vocab_size),
        d_model=int(hf_config.d_model),
        n_audio_layers=int(hf_config.encoder_layers),
        n_text_layers=int(hf_config.decoder_layers),
        n_heads=int(hf_config.encoder_attention_heads),
        ffn_dim=int(hf_config.encoder_ffn_dim),
        n_mels=int(hf_config.num_mel_bins),
        max_source_positions=int(hf_config.max_source_positions),
        max_target_positions=int(hf_config.max_target_positions),
    )


def prompt_ids_from_tokenizer(tok, language: Optional[str] = None) -> dict:
    """Decoder prompt + stop ids for both audio tasks."""

    def tid(token):
        i = tok.convert_tokens_to_ids(token)
        return int(i) if i is not None and i >= 0 else None

    sot = tid("<|startoftranscript|>")
    notimestamps = tid("<|notimestamps|>")
    lang = tid("<|{}|>".format(language)) if language else None
    out = {"eos_token_id": int(tok.eos_token_id)}
    if notimestamps is not None:
        # timestamp vocabulary starts right after <|notimestamps|>; each id
        # encodes (id - begin) * 0.02 s — enables verbose_json segments
        out["notimestamps_token_id"] = notimestamps
        out["timestamp_begin"] = notimestamps + 1
        out["time_precision"] = 0.02
    for task in ("transcribe", "translate"):
        task_id = tid("<|{}|>".format(task))
        ids = [x for x in (sot, lang, task_id, notimestamps) if x is not None]
        out["{}_prompt_ids".format(task)] = ids
    return out


def convert(model_dir: str, out_dir: str, language: Optional[str] = None) -> None:
    """Local HF Whisper checkpoint dir -> servable whisper bundle dir."""
    import shutil
    from pathlib import Path

    import transformers

    from ..jax_engine import save_bundle

    hf = transformers.WhisperForConditionalGeneration.from_pretrained(
        model_dir, local_files_only=True
    )
    cfg = config_from_hf(hf.config)
    params = convert_state_dict(hf.state_dict(), cfg)

    fe = transformers.WhisperFeatureExtractor.from_pretrained(
        model_dir, local_files_only=True
    )
    # mel filters ride the param tree: serving never re-derives slaney banks
    params["mel_filters"] = np.asarray(fe.mel_filters, np.float32)
    cfg["sampling_rate"] = int(fe.sampling_rate)
    cfg["hop_length"] = int(fe.hop_length)
    cfg["n_fft"] = int(fe.n_fft)
    cfg["chunk_length"] = int(fe.chunk_length)

    tok = transformers.WhisperTokenizer.from_pretrained(model_dir, local_files_only=True)
    cfg.update(prompt_ids_from_tokenizer(tok, language=language))

    # curated word-alignment heads (openai ships them per released model;
    # HF stores them on generation_config). Without them the word-timestamp
    # DTW falls back to the noisier top-half-of-decoder heuristic
    # (llm/audio.py _alignment_heads).
    gen_cfg = getattr(hf, "generation_config", None)
    heads = getattr(gen_cfg, "alignment_heads", None)
    if heads:
        cfg["alignment_heads"] = [[int(l), int(h)] for l, h in heads]

    save_bundle(out_dir, "whisper", cfg, params)
    for f in Path(model_dir).glob("*token*"):
        shutil.copy(f, Path(out_dir) / f.name)
    for name in ("vocab.json", "merges.txt", "normalizer.json"):
        src = Path(model_dir) / name
        if src.exists():
            shutil.copy(src, Path(out_dir) / name)
    print("whisper bundle written to {}".format(out_dir))


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("model_dir")
    ap.add_argument("out_dir")
    ap.add_argument("--language", default=None)
    convert(**vars(ap.parse_args()))
