"""TF GraphDef -> JAX importer (zero TF dependency).

The reference serves ``tensorflow_savedmodel`` / ``tensorflow_graphdef``
models by handing the files to Triton's TF backend
(reference engines/triton/triton_helper.py:159-183, platform auto-detect
:378-385). This image has no tensorflow, so this importer reads the frozen
graph directly: GraphDef is plain protobuf (parsed with the same
schema-driven decoder as ONNX, onnx_proto._parse_message) and the node ops
evaluate as a topological JAX interpreter — the resulting function
jit/pjit-compiles for TPU exactly like the ONNX path.

Scope: FROZEN inference graphs (constants folded into the graph) — the
``model.graphdef`` flavor, plus TF1-style SavedModel ``saved_model.pb``
whose MetaGraphDef embeds a frozen GraphDef. TF2 SavedModels with external
variable shards are out of scope; convert those offline with tf2onnx
(examples/tensorflow/readme.md) and serve the .onnx.

Schema reference: tensorflow/core/framework/{graph,node_def,attr_value,
tensor,tensor_shape,types}.proto (public spec).
"""

from __future__ import annotations

from pathlib import Path
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .onnx_proto import _parse_message

# -- TF protobuf schemas ------------------------------------------------------

_TENSOR_SHAPE_DIM = {1: ("size", "svarint", False), 2: ("name", "string", False)}
_TENSOR_SHAPE = {
    2: ("dim", ("message", _TENSOR_SHAPE_DIM), True),
    3: ("unknown_rank", "varint", False),
}
_TENSOR = {
    1: ("dtype", "varint", False),
    2: ("tensor_shape", ("message", _TENSOR_SHAPE), False),
    4: ("tensor_content", "bytes", False),
    5: ("float_val", "float", True),
    6: ("double_val", "double", True),
    7: ("int_val", "svarint", True),
    8: ("string_val", "bytes", True),
    10: ("int64_val", "svarint", True),
    11: ("bool_val", "varint", True),
}
_ATTR_LIST = {
    2: ("s", "bytes", True),
    3: ("i", "svarint", True),
    4: ("f", "float", True),
    5: ("b", "varint", True),
    6: ("type", "varint", True),
    7: ("shape", ("message", _TENSOR_SHAPE), True),
    8: ("tensor", ("message", _TENSOR), True),
}
_ATTR_VALUE = {
    1: ("list", ("message", _ATTR_LIST), False),
    2: ("s", "bytes", False),
    3: ("i", "svarint", False),
    4: ("f", "float32", False),
    5: ("b", "varint", False),
    6: ("type", "varint", False),
    7: ("shape", ("message", _TENSOR_SHAPE), False),
    8: ("tensor", ("message", _TENSOR), False),
}
_ATTR_ENTRY = {
    1: ("key", "string", False),
    2: ("value", ("message", _ATTR_VALUE), False),
}
_NODE_DEF = {
    1: ("name", "string", False),
    2: ("op", "string", False),
    3: ("input", "string", True),
    5: ("attr", ("message", _ATTR_ENTRY), True),
}
_GRAPH_DEF = {1: ("node", ("message", _NODE_DEF), True)}
# TF1 SavedModel wrapper: SavedModel.meta_graphs[0].graph_def
_META_GRAPH = {2: ("graph_def", ("message", _GRAPH_DEF), False)}
_SAVED_MODEL = {2: ("meta_graphs", ("message", _META_GRAPH), True)}

# tensorflow DataType enum -> numpy
_DTYPES = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 5: np.int16,
    6: np.int8, 9: np.int64, 10: np.bool_, 14: "bfloat16", 17: np.uint16,
    19: np.float16, 22: np.uint32, 23: np.uint64,
}


def _np_dtype(enum: int):
    dt = _DTYPES.get(int(enum))
    if dt is None:
        raise ValueError("unsupported TF dtype enum {}".format(enum))
    if dt == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return dt


def _tensor_to_np(t: Dict[str, Any]) -> np.ndarray:
    enum = int(t.get("dtype", 1))
    dims = [int(d.get("size", -1)) for d in (t.get("tensor_shape") or {}).get("dim", [])]
    content = t.get("tensor_content")
    if enum == 14:  # DT_BFLOAT16: reinterpret the bit patterns, not cast
        if content:
            bits = np.frombuffer(content, np.uint16).astype(np.uint32) << 16
            arr = bits.view(np.float32)
            return arr.reshape(dims) if dims else arr.reshape(())
        return np.zeros(dims or (), np.float32)
    dtype = _np_dtype(enum)
    if content:
        arr = np.frombuffer(content, dtype=np.dtype(dtype))
        return arr.reshape(dims) if dims else arr.reshape(())
    for key, cast in (
        ("float_val", np.float32), ("double_val", np.float64),
        ("int_val", np.int32), ("int64_val", np.int64), ("bool_val", np.bool_),
    ):
        vals = t.get(key)
        if vals:
            arr = np.asarray(vals, cast).astype(dtype)
            if not dims:
                return arr.reshape(()) if arr.size == 1 else arr
            if arr.size == 1 and int(np.prod(dims)) != 1:
                arr = np.full(dims, arr.reshape(())[()])  # splat encoding
            return arr.reshape(dims)
    # empty tensor
    return np.zeros(dims or (), np.dtype(dtype))


def _attrs(node: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    return {e["key"]: e.get("value", {}) for e in node.get("attr", []) if "key" in e}


def parse_graphdef(data: bytes) -> List[Dict[str, Any]]:
    """GraphDef bytes (or a TF1 SavedModel wrapper) -> node list."""
    nodes: List[Dict[str, Any]] = []
    try:
        graph = _parse_message(data, _GRAPH_DEF)
        nodes = graph.get("node") or []
    except Exception:  # tpuserve: ignore[TPU401] format probe: fall through to the SavedModel parse
        pass  # not a bare GraphDef; try the SavedModel wrapper below
    # real SavedModel files lead with saved_model_schema_version (field 1,
    # varint), which the GraphDef probe skips -> zero nodes -> fall through
    if not nodes:
        try:
            saved = _parse_message(data, _SAVED_MODEL)
        except Exception:
            saved = {}
        metas = saved.get("meta_graphs") or []
        if metas and metas[0].get("graph_def"):
            nodes = metas[0]["graph_def"].get("node") or []
    if not nodes:
        raise ValueError("no nodes parsed: not a frozen GraphDef/SavedModel")
    return nodes


# -- interpreter --------------------------------------------------------------

def _pool_padding(padding: str):
    return padding  # "SAME"/"VALID" pass straight to lax


class _GraphInterpreter:
    """Topological evaluator over a frozen node list (NHWC convention)."""

    # training/serialization machinery that must never auto-detect as a
    # model output (frozen graphs often keep dead Saver/init leftovers)
    _NON_OUTPUT_OPS = {
        "Const", "NoOp", "Placeholder", "Assert", "SaveV2", "RestoreV2",
        "Assign", "AssignVariableOp", "VariableV2", "VarHandleOp",
        "MergeV2Checkpoints", "ShardedFilename",
    }

    def __init__(self, nodes: List[Dict[str, Any]], outputs: Optional[List[str]] = None):
        self.nodes = {n["name"]: n for n in nodes if n.get("name")}
        order_all = [n["name"] for n in nodes if n.get("name")]
        placeholders: List[str] = []
        self.input_shapes: Dict[str, List[int]] = {}
        consumed = set()
        for n in nodes:
            if n.get("op") in ("Placeholder", "PlaceholderWithDefault"):
                placeholders.append(n["name"])
                shape = _attrs(n).get("shape", {}).get("shape") or {}
                self.input_shapes[n["name"]] = [
                    int(d.get("size", -1)) for d in shape.get("dim", [])
                ]
            for ref in n.get("input", []):
                consumed.add(self._base(ref))
        if outputs:
            self.output_names = outputs
        else:
            self.output_names = [
                n["name"] for n in nodes
                if n["name"] not in consumed
                and n.get("op") not in self._NON_OUTPUT_OPS
            ] or [order_all[-1]]
        # evaluate ONLY the ancestors of the outputs: frozen graphs keep dead
        # Saver/init/label-map leftovers whose unsupported ops or dtypes
        # must not break import of the inference subgraph
        needed = set()
        stack = [self._base(o) for o in self.output_names]
        while stack:
            name = stack.pop()
            if name in needed:
                continue
            needed.add(name)
            node = self.nodes.get(name)
            if node is None:
                raise ValueError("output {!r} not in graph".format(name))
            stack.extend(self._base(r) for r in node.get("input", []))
        self.order = [n for n in order_all if n in needed]
        self.input_names = [p for p in placeholders if p in needed]
        self.consts: Dict[str, np.ndarray] = {}
        for name in self.order:
            n = self.nodes[name]
            if n.get("op") == "Const":
                self.consts[name] = _tensor_to_np(
                    _attrs(n)["value"].get("tensor", {})
                )
        # large consts become device params (weights); small ones stay host
        # (shape/axis operands that must be static for XLA)
        self.param_names = [k for k, v in self.consts.items() if v.size >= 64]

    @staticmethod
    def _base(ref: str) -> str:
        ref = ref.lstrip("^")
        return ref.split(":", 1)[0]

    def init_params(self) -> Dict[str, np.ndarray]:
        return {k: self.consts[k] for k in self.param_names}

    def run(self, params: Dict[str, Any], *inputs):
        import jax
        import jax.numpy as jnp

        if len(inputs) != len(self.input_names):
            raise ValueError(
                "graph expects {} inputs {} but got {}".format(
                    len(self.input_names), self.input_names, len(inputs)
                )
            )
        env: Dict[str, Any] = {}
        for name, value in zip(self.input_names, inputs):
            env[name] = value
        for name in self.order:
            if name in env:
                continue
            node = self.nodes[name]
            op = node.get("op")
            if op in ("NoOp", "Assert", "Placeholder"):
                continue
            if op == "Const":
                env[name] = (
                    params[name] if name in self.param_names else self.consts[name]
                )
                continue
            args = []
            for ref in node.get("input", []):
                if ref.startswith("^"):
                    continue  # control dependency
                base, _, idx = ref.partition(":")
                v = env.get(base)
                if v is None:
                    raise ValueError(
                        "node {!r} consumed before producer {!r}".format(name, base)
                    )
                if idx and int(idx) > 0:
                    v = v[int(idx)]  # multi-output producer (tuple)
                elif isinstance(v, tuple):
                    v = v[0]
                args.append(v)
            env[name] = self._eval(op, node, args)
        outs = []
        for ref in self.output_names:
            v = env[self._base(ref)]
            outs.append(v[0] if isinstance(v, tuple) else v)
        return outs

    @staticmethod
    def _static(x) -> np.ndarray:
        """Operand that must be host-static (shapes, axes, permutations)."""
        if isinstance(x, np.ndarray):
            return x
        return np.asarray(x)

    def _eval(self, op: str, node: Dict[str, Any], args: List[Any]):
        import jax
        import jax.numpy as jnp
        from jax import lax

        attrs = _attrs(node)

        def attr_i(key, default=0):
            return int(attrs.get(key, {}).get("i", default))

        def attr_f(key, default=0.0):
            return float(attrs.get(key, {}).get("f", default))

        def attr_s(key, default=b""):
            v = attrs.get(key, {}).get("s", default)
            return v.decode() if isinstance(v, (bytes, bytearray)) else v

        def attr_ilist(key):
            return [int(v) for v in (attrs.get(key, {}).get("list") or {}).get("i", [])]

        if op in ("Identity", "StopGradient", "PreventGradient", "Snapshot",
                  "CheckNumerics", "PlaceholderWithDefault"):
            return args[0]
        if op == "MatMul":
            a, b = args
            if attr_i("transpose_a"):
                a = jnp.swapaxes(a, -1, -2)
            if attr_i("transpose_b"):
                b = jnp.swapaxes(b, -1, -2)
            return jnp.matmul(a, b)
        if op in ("BatchMatMul", "BatchMatMulV2"):
            a, b = args
            if attr_i("adj_x"):
                a = jnp.swapaxes(a, -1, -2)
            if attr_i("adj_y"):
                b = jnp.swapaxes(b, -1, -2)
            return jnp.matmul(a, b)
        if op == "BiasAdd":
            x, bias = args
            if attr_s("data_format", b"NHWC") == "NCHW" and x.ndim == 4:
                return x + bias.reshape(1, -1, 1, 1)
            return x + bias
        if op in ("Add", "AddV2"):
            return args[0] + args[1]
        if op == "AddN":
            out = args[0]
            for a in args[1:]:
                out = out + a
            return out
        if op == "Sub":
            return args[0] - args[1]
        if op == "Mul":
            return args[0] * args[1]
        if op in ("RealDiv", "Div"):
            return args[0] / args[1]
        if op == "Maximum":
            return jnp.maximum(args[0], args[1])
        if op == "Minimum":
            return jnp.minimum(args[0], args[1])
        if op == "Rsqrt":
            return lax.rsqrt(args[0])
        if op == "Sqrt":
            return jnp.sqrt(args[0])
        if op == "Exp":
            return jnp.exp(args[0])
        if op == "Log":
            return jnp.log(args[0])
        if op == "Neg":
            return -args[0]
        if op == "Abs":
            return jnp.abs(args[0])
        if op == "Square":
            return jnp.square(args[0])
        if op == "Relu":
            return jax.nn.relu(args[0])
        if op == "Relu6":
            return jnp.clip(args[0], 0, 6)
        if op == "LeakyRelu":
            return jax.nn.leaky_relu(args[0], attr_f("alpha", 0.2))
        if op == "Elu":
            return jax.nn.elu(args[0])
        if op == "Sigmoid":
            return jax.nn.sigmoid(args[0])
        if op == "Tanh":
            return jnp.tanh(args[0])
        if op == "Softplus":
            return jax.nn.softplus(args[0])
        if op == "Softmax":
            return jax.nn.softmax(args[0], axis=-1)
        if op == "LogSoftmax":
            return jax.nn.log_softmax(args[0], axis=-1)
        if op == "Conv2D":
            x, w = args  # x NHWC, w HWIO (TF layouts)
            strides = attr_ilist("strides") or [1, 1, 1, 1]
            dilations = attr_ilist("dilations") or [1, 1, 1, 1]
            fmt = attr_s("data_format", b"NHWC")
            dn = lax.conv_dimension_numbers(
                x.shape, w.shape,
                ("NHWC", "HWIO", "NHWC") if fmt == "NHWC" else ("NCHW", "HWIO", "NCHW"),
            )
            sp = slice(1, 3) if fmt == "NHWC" else slice(2, 4)
            return lax.conv_general_dilated(
                x, w, window_strides=strides[sp], padding=attr_s("padding", b"VALID"),
                rhs_dilation=dilations[sp], dimension_numbers=dn,
            )
        if op == "DepthwiseConv2dNative":
            x, w = args  # w [H, W, C, M] -> grouped conv with C groups
            strides = attr_ilist("strides") or [1, 1, 1, 1]
            fmt = attr_s("data_format", b"NHWC")
            if fmt == "NCHW":  # normalize to NHWC, compute, restore
                x = jnp.transpose(x, (0, 2, 3, 1))
                strides = [strides[0], strides[2], strides[3], strides[1]]
            c = x.shape[-1]
            w = jnp.reshape(w, w.shape[:2] + (1, -1))  # HWIO with I=1, O=C*M
            dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
            out = lax.conv_general_dilated(
                x, w, window_strides=strides[1:3],
                padding=attr_s("padding", b"VALID"),
                dimension_numbers=dn, feature_group_count=c,
            )
            return jnp.transpose(out, (0, 3, 1, 2)) if fmt == "NCHW" else out
        if op in ("MaxPool", "AvgPool"):
            x = args[0]
            ksize = attr_ilist("ksize") or [1, 1, 1, 1]
            strides = attr_ilist("strides") or [1, 1, 1, 1]
            padding = attr_s("padding", b"VALID")
            if op == "MaxPool":
                return lax.reduce_window(
                    x, -jnp.inf, lax.max, ksize, strides, padding
                )
            ones = jnp.ones_like(x)
            summed = lax.reduce_window(x, 0.0, lax.add, ksize, strides, padding)
            counts = lax.reduce_window(ones, 0.0, lax.add, ksize, strides, padding)
            return summed / counts
        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            x, scale, offset, mean, var = args[:5]
            eps = attr_f("epsilon", 1e-4)
            inv = lax.rsqrt(var + eps) * scale
            bias = offset - mean * inv
            if attr_s("data_format", b"NHWC") == "NCHW" and x.ndim == 4:
                inv = inv.reshape(1, -1, 1, 1)
                bias = bias.reshape(1, -1, 1, 1)
            return (x * inv + bias,)  # tuple: output :0 is y
        if op == "Reshape":
            shape = [int(v) for v in self._static(args[1]).reshape(-1)]
            return jnp.reshape(args[0], shape)
        if op == "Squeeze":
            dims = attr_ilist("squeeze_dims") or attr_ilist("axis")
            return jnp.squeeze(args[0], axis=tuple(dims) if dims else None)
        if op == "ExpandDims":
            return jnp.expand_dims(args[0], int(self._static(args[1])))
        if op == "Transpose":
            perm = [int(v) for v in self._static(args[1]).reshape(-1)]
            return jnp.transpose(args[0], perm)
        if op == "ConcatV2":
            axis = int(self._static(args[-1]))
            return jnp.concatenate(args[:-1], axis=axis)
        if op == "Pack":
            return jnp.stack(args, axis=attr_i("axis"))
        if op in ("Mean", "Sum", "Max", "Min"):
            axes = tuple(int(v) for v in self._static(args[1]).reshape(-1))
            keep = bool(attr_i("keep_dims"))
            fn = {"Mean": jnp.mean, "Sum": jnp.sum, "Max": jnp.max, "Min": jnp.min}[op]
            return fn(args[0], axis=axes, keepdims=keep)
        if op in ("Pad", "PadV2"):
            pads = self._static(args[1]).astype(int).tolist()
            value = float(self._static(args[2])) if len(args) > 2 else 0.0
            return jnp.pad(args[0], pads, constant_values=value)
        if op == "ArgMax":
            axis = int(self._static(args[1])) if len(args) > 1 else -1
            return jnp.argmax(args[0], axis=axis).astype(
                _np_dtype(attr_i("output_type", 9))
            )
        if op == "Cast":
            return args[0].astype(_np_dtype(attr_i("DstT", 1)))
        if op == "Shape":
            return np.asarray(args[0].shape, np.int32)  # static under jit
        raise ValueError(
            "GraphDef op {!r} (node {!r}) is not supported by the native "
            "importer; convert the model offline with tf2onnx and serve the "
            ".onnx (examples/tensorflow/readme.md)".format(op, node.get("name"))
        )


def find_graphdef_file(path) -> Optional[Path]:
    path = Path(path)
    if path.is_file() and path.suffix in (".graphdef", ".pb"):
        return path
    if path.is_dir():
        cands = sorted(path.glob("*.graphdef")) + sorted(path.glob("*.pb"))
        if cands:
            return cands[0]
    return None


def load_graphdef_bundle(path, outputs: Optional[List[str]] = None):
    """Frozen GraphDef/TF1-SavedModel file -> (bundle, params), same surface
    as load_onnx_bundle."""
    import jax.numpy as jnp

    gd_file = find_graphdef_file(path)
    if gd_file is None:
        raise ValueError("no .graphdef/.pb file found at {}".format(path))
    interp = _GraphInterpreter(parse_graphdef(gd_file.read_bytes()), outputs)
    params = {k: jnp.asarray(v) for k, v in interp.init_params().items()}
    for name in interp.param_names:
        # run() reads weights from params; keeping the host numpy copies
        # alive would double per-model host memory for nothing
        del interp.consts[name]

    def apply(params, *inputs):
        outs = interp.run(params, *inputs)
        return outs[0] if len(outs) == 1 else tuple(outs)

    bundle = SimpleNamespace(
        apply=apply,
        config={
            "arch": "graphdef",
            "source": str(gd_file),
            "inputs": interp.input_names,
            "outputs": interp.output_names,
            "input_shapes": interp.input_shapes,
        },
        input_names=interp.input_names,
        output_names=interp.output_names,
    )
    return bundle, params
