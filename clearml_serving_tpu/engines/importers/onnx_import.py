"""ONNX -> JAX importer: stock ``.onnx`` graphs become jit-able bundles.

Replaces Triton's onnxruntime backend (reference triton_helper.py:159-183,
platform auto-detect :378-385): instead of handing the file to a C++ runtime,
the graph is interpreted into a pure JAX function over a params pytree, so the
whole model jit/pjit-compiles to one XLA executable on TPU — fused, bucketed,
and shardable like any native bundle.

Static/traced hybrid evaluation: ONNX exporters (notably pytorch's) emit
shape-metaprogram chains (Shape -> Gather -> Unsqueeze -> Concat -> Reshape).
Input shapes are static per batch bucket, so ``Shape`` yields a concrete
numpy array at trace time; any node all of whose inputs are concrete numpy
values is computed eagerly with numpy. The chain constant-folds away and
``Reshape`` sees a static shape — no dynamic shapes ever reach XLA.

Supported op set covers pytorch-exported MLP / CNN / transformer-encoder
graphs and common sklearn-onnx arithmetic; unsupported ops raise by name at
conversion time, not silently at runtime.
"""

from __future__ import annotations

from pathlib import Path
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import onnx_proto

_ATTR_KIND = {1: "f", 2: "i", 3: "s", 4: "t", 6: "floats", 7: "ints", 8: "strings"}


def _attrs(node: dict) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for a in node.get("attribute", []):
        t = int(a.get("type", 0))
        key = _ATTR_KIND.get(t)
        if key is None:  # graph/tensors attrs unsupported here
            if "t" in a:
                key = "t"
            else:
                continue
        val = a.get(key)
        if key == "s" and isinstance(val, bytes):
            val = val.decode("utf-8", "replace")
        elif key == "strings":
            val = [v.decode("utf-8", "replace") if isinstance(v, bytes) else v for v in val]
        elif key == "t":
            val = onnx_proto.tensor_to_numpy(val)
        out[a["name"]] = val
    return out


def _is_static(v) -> bool:
    return isinstance(v, np.ndarray) or np.isscalar(v)


def _xp(vals: Sequence[Any]):
    """numpy when every operand is concrete (constant-folds shape chains),
    jax.numpy as soon as anything is traced."""
    if all(_is_static(v) for v in vals):
        return np
    import jax.numpy as jnp

    return jnp


def _static_ints(v, what: str) -> List[int]:
    if not _is_static(v):
        raise ValueError(
            "ONNX import: {} must be statically resolvable (got traced value)".format(what)
        )
    return [int(x) for x in np.asarray(v).reshape(-1)]


_CAST_DTYPES = dict(onnx_proto._DTYPES)


class _Interpreter:
    """Walks a parsed GraphProto once per trace."""

    def __init__(self, graph: dict):
        self.graph = graph
        self.initializers: Dict[str, np.ndarray] = {
            t["name"]: onnx_proto.tensor_to_numpy(t)
            for t in graph.get("initializer", [])
        }
        init_names = set(self.initializers)
        self.input_names = [
            vi["name"] for vi in graph.get("input", []) if vi["name"] not in init_names
        ]
        self.output_names = [vi["name"] for vi in graph.get("output", [])]
        self.input_shapes = {
            vi["name"]: onnx_proto.value_info_shape(vi)
            for vi in graph.get("input", [])
            if vi["name"] not in init_names
        }
        # params: float-family initializers live on device (shardable,
        # donate-able); integer/small tensors stay static so meta ops
        # (Reshape shapes, Slice bounds, Gather indices) constant-fold.
        self.param_names = [
            n
            for n, arr in self.initializers.items()
            if arr.dtype.kind == "f" and arr.size > 64
        ]
        self._check_ops()

    def _check_ops(self) -> None:
        missing = sorted(
            {
                n.get("op_type", "?")
                for n in self.graph.get("node", [])
                if n.get("op_type") not in _OPS
            }
        )
        if missing:
            raise ValueError(
                "ONNX import: unsupported op(s): {} (supported: {})".format(
                    ", ".join(missing), ", ".join(sorted(_OPS))
                )
            )

    def init_params(self) -> Dict[str, Any]:
        return {n: self.initializers[n] for n in self.param_names}

    def run(self, params: Dict[str, Any], *inputs) -> Tuple:
        if len(inputs) != len(self.input_names):
            raise ValueError(
                "expected {} inputs {}, got {}".format(
                    len(self.input_names), self.input_names, len(inputs)
                )
            )
        env: Dict[str, Any] = {}
        for name, arr in self.initializers.items():
            env[name] = arr
        env.update(params)  # traced leaves shadow static copies
        env.update(zip(self.input_names, inputs))
        for node in self.graph.get("node", []):
            op = _OPS[node["op_type"]]
            ins = [env[n] if n else None for n in node.get("input", [])]
            outs = op(self, node, ins, _attrs(node))
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            for name, val in zip(node.get("output", []), outs):
                if name:
                    env[name] = val
        return tuple(env[n] for n in self.output_names)


# -- op implementations -------------------------------------------------------
# Each op: fn(interp, node, inputs, attrs) -> output(s)

_OPS: Dict[str, Callable] = {}


def _op(*names):
    def deco(fn):
        for n in names:
            _OPS[n] = fn
        return fn

    return deco


def _binary(fn_np):
    def impl(interp, node, ins, attrs):
        xp = _xp(ins)
        return fn_np(xp, ins[0], ins[1])

    return impl


_OPS["Add"] = _binary(lambda xp, a, b: xp.add(a, b))
_OPS["Sub"] = _binary(lambda xp, a, b: xp.subtract(a, b))
_OPS["Mul"] = _binary(lambda xp, a, b: xp.multiply(a, b))
_OPS["Div"] = _binary(lambda xp, a, b: xp.divide(a, b))
_OPS["Pow"] = _binary(lambda xp, a, b: xp.power(a, b))
_OPS["Equal"] = _binary(lambda xp, a, b: xp.equal(a, b))
_OPS["Greater"] = _binary(lambda xp, a, b: xp.greater(a, b))
_OPS["GreaterOrEqual"] = _binary(lambda xp, a, b: xp.greater_equal(a, b))
_OPS["Less"] = _binary(lambda xp, a, b: xp.less(a, b))
_OPS["LessOrEqual"] = _binary(lambda xp, a, b: xp.less_equal(a, b))
_OPS["And"] = _binary(lambda xp, a, b: xp.logical_and(a, b))
_OPS["Or"] = _binary(lambda xp, a, b: xp.logical_or(a, b))


def _unary(fn):
    def impl(interp, node, ins, attrs):
        return fn(_xp(ins), ins[0])

    return impl


_OPS["Relu"] = _unary(lambda xp, x: xp.maximum(x, 0))
_OPS["Neg"] = _unary(lambda xp, x: xp.negative(x))
_OPS["Abs"] = _unary(lambda xp, x: xp.abs(x))
_OPS["Exp"] = _unary(lambda xp, x: xp.exp(x))
_OPS["Log"] = _unary(lambda xp, x: xp.log(x))
_OPS["Sqrt"] = _unary(lambda xp, x: xp.sqrt(x))
_OPS["Tanh"] = _unary(lambda xp, x: xp.tanh(x))
_OPS["Floor"] = _unary(lambda xp, x: xp.floor(x))
_OPS["Ceil"] = _unary(lambda xp, x: xp.ceil(x))
_OPS["Reciprocal"] = _unary(lambda xp, x: xp.divide(1.0, x))
_OPS["Not"] = _unary(lambda xp, x: xp.logical_not(x))
_OPS["Identity"] = _unary(lambda xp, x: x)


@_op("Sigmoid")
def _sigmoid(interp, node, ins, attrs):
    if _is_static(ins[0]):
        return 1.0 / (1.0 + np.exp(-np.asarray(ins[0], np.float32)))
    import jax

    return jax.nn.sigmoid(ins[0])


@_op("Erf")
def _erf(interp, node, ins, attrs):
    import jax

    if _is_static(ins[0]):
        import math

        return np.vectorize(math.erf)(np.asarray(ins[0], np.float64)).astype(
            np.asarray(ins[0]).dtype
        )
    return jax.scipy.special.erf(ins[0])


@_op("Gelu")
def _gelu(interp, node, ins, attrs):
    import jax

    approx = attrs.get("approximate", "none") == "tanh"
    return jax.nn.gelu(ins[0], approximate=approx)


@_op("LeakyRelu")
def _leaky_relu(interp, node, ins, attrs):
    xp = _xp(ins)
    alpha = float(attrs.get("alpha", 0.01))
    return xp.where(ins[0] >= 0, ins[0], alpha * ins[0])


@_op("Elu")
def _elu(interp, node, ins, attrs):
    xp = _xp(ins)
    alpha = float(attrs.get("alpha", 1.0))
    return xp.where(ins[0] >= 0, ins[0], alpha * (xp.exp(ins[0]) - 1.0))


@_op("Clip")
def _clip(interp, node, ins, attrs):
    xp = _xp([ins[0]])
    lo = ins[1] if len(ins) > 1 and ins[1] is not None else attrs.get("min")
    hi = ins[2] if len(ins) > 2 and ins[2] is not None else attrs.get("max")
    out = ins[0]
    if lo is not None:
        out = xp.maximum(out, lo)
    if hi is not None:
        out = xp.minimum(out, hi)
    return out


@_op("Softmax")
def _softmax(interp, node, ins, attrs):
    import jax

    axis = int(attrs.get("axis", -1))
    return jax.nn.softmax(ins[0], axis=axis)


@_op("LogSoftmax")
def _log_softmax(interp, node, ins, attrs):
    import jax

    axis = int(attrs.get("axis", -1))
    return jax.nn.log_softmax(ins[0], axis=axis)


@_op("Softplus")
def _softplus(interp, node, ins, attrs):
    import jax

    return jax.nn.softplus(ins[0])


@_op("HardSigmoid")
def _hard_sigmoid(interp, node, ins, attrs):
    xp = _xp(ins)
    alpha = float(attrs.get("alpha", 0.2))
    beta = float(attrs.get("beta", 0.5))
    return xp.clip(alpha * ins[0] + beta, 0.0, 1.0)


@_op("Where")
def _where(interp, node, ins, attrs):
    return _xp(ins).where(ins[0], ins[1], ins[2])


@_op("Min")
def _min(interp, node, ins, attrs):
    xp = _xp(ins)
    out = ins[0]
    for v in ins[1:]:
        out = xp.minimum(out, v)
    return out


@_op("Max")
def _max(interp, node, ins, attrs):
    xp = _xp(ins)
    out = ins[0]
    for v in ins[1:]:
        out = xp.maximum(out, v)
    return out


@_op("Sum")
def _sum_nary(interp, node, ins, attrs):
    xp = _xp(ins)
    out = ins[0]
    for v in ins[1:]:
        out = xp.add(out, v)
    return out


@_op("MatMul")
def _matmul(interp, node, ins, attrs):
    return _xp(ins).matmul(ins[0], ins[1])


@_op("Gemm")
def _gemm(interp, node, ins, attrs):
    xp = _xp(ins)
    a, b = ins[0], ins[1]
    if int(attrs.get("transA", 0)):
        a = xp.swapaxes(a, -1, -2)
    if int(attrs.get("transB", 0)):
        b = xp.swapaxes(b, -1, -2)
    out = xp.matmul(a, b) * float(attrs.get("alpha", 1.0))
    if len(ins) > 2 and ins[2] is not None:
        out = out + float(attrs.get("beta", 1.0)) * ins[2]
    return out


@_op("Einsum")
def _einsum(interp, node, ins, attrs):
    return _xp(ins).einsum(attrs["equation"], *ins)


def _conv_pads(attrs, spatial: int, in_shape, k_shape, strides, dilations):
    pads = attrs.get("pads")
    auto = attrs.get("auto_pad", "NOTSET")
    if pads:
        p = [int(x) for x in pads]
        return [(p[i], p[i + spatial]) for i in range(spatial)]
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        out = []
        for i in range(spatial):
            eff_k = (k_shape[i] - 1) * dilations[i] + 1
            total = max(
                0,
                (-(in_shape[i] // -strides[i]) - 1) * strides[i] + eff_k - in_shape[i],
            )
            lo = total // 2
            hi = total - lo
            out.append((hi, lo) if auto == "SAME_LOWER" else (lo, hi))
        return out
    return [(0, 0)] * spatial


@_op("Conv")
def _conv(interp, node, ins, attrs):
    import jax

    x, w = ins[0], ins[1]
    spatial = w.ndim - 2  # tracers carry shape/ndim; never np.asarray a tracer
    strides = [int(s) for s in attrs.get("strides", [1] * spatial)]
    dilations = [int(d) for d in attrs.get("dilations", [1] * spatial)]
    groups = int(attrs.get("group", 1))
    k_shape = list(w.shape[2:])
    in_shape = list(x.shape[2:])
    pads = _conv_pads(attrs, spatial, in_shape, k_shape, strides, dilations)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, ("NCHW", "OIHW", "NCHW") if spatial == 2 else (
            "NCH", "OIH", "NCH") if spatial == 1 else ("NCDHW", "OIDHW", "NCDHW")
    )
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads,
        rhs_dilation=dilations, dimension_numbers=dn, feature_group_count=groups,
    )
    if len(ins) > 2 and ins[2] is not None:
        b = ins[2]
        out = out + b.reshape((1, -1) + (1,) * spatial)
    return out


def _pool(interp, node, ins, attrs, reducer, init, is_avg=False):
    import jax

    x = ins[0]
    kernel = [int(k) for k in attrs["kernel_shape"]]
    spatial = len(kernel)
    strides = [int(s) for s in attrs.get("strides", [1] * spatial)]
    dilations = [int(d) for d in attrs.get("dilations", [1] * spatial)]
    pads = _conv_pads(attrs, spatial, list(x.shape[2:]), kernel, strides, dilations)
    if int(attrs.get("ceil_mode", 0)):
        # ceil output size = extend the high pad so the last (partial) window
        # exists; pad cells are init values (-inf for max, masked out of the
        # average's count), matching ONNX ceil_mode semantics
        pads = list(pads)
        for i in range(spatial):
            eff_k = (kernel[i] - 1) * dilations[i] + 1
            span = x.shape[2 + i] + pads[i][0] + pads[i][1] - eff_k
            out_ceil = -(-span // strides[i]) + 1
            needed = (out_ceil - 1) * strides[i] + eff_k
            extra = needed - (x.shape[2 + i] + pads[i][0] + pads[i][1])
            if extra > 0:
                pads[i] = (pads[i][0], pads[i][1] + extra)
    window = (1, 1) + tuple(kernel)
    stride = (1, 1) + tuple(strides)
    dila = (1, 1) + tuple(dilations)
    padding = ((0, 0), (0, 0)) + tuple(pads)
    out = jax.lax.reduce_window(
        x, init, reducer, window, stride, padding, window_dilation=dila
    )
    if is_avg:
        count_include_pad = int(attrs.get("count_include_pad", 0))
        if count_include_pad:
            denom = float(np.prod(kernel))
            out = out / denom
        else:
            ones = jax.numpy.ones_like(x)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, window, stride, padding, window_dilation=dila
            )
            out = out / counts
    return out


@_op("MaxPool")
def _max_pool(interp, node, ins, attrs):
    import jax

    return _pool(interp, node, ins, attrs, jax.lax.max, -np.inf)


@_op("AveragePool")
def _avg_pool(interp, node, ins, attrs):
    import jax

    return _pool(interp, node, ins, attrs, jax.lax.add, 0.0, is_avg=True)


@_op("GlobalAveragePool")
def _global_avg_pool(interp, node, ins, attrs):
    xp = _xp(ins)
    x = ins[0]
    axes = tuple(range(2, x.ndim))
    return xp.mean(x, axis=axes, keepdims=True)


@_op("BatchNormalization")
def _batch_norm(interp, node, ins, attrs):
    x, scale, bias, mean, var = ins[:5]
    eps = float(attrs.get("epsilon", 1e-5))
    shape = (1, -1) + (1,) * (x.ndim - 2)
    xp = _xp(ins)
    inv = 1.0 / xp.sqrt(var + eps)
    return (x - mean.reshape(shape)) * (scale * inv).reshape(shape) + bias.reshape(shape)


@_op("LayerNormalization")
def _layer_norm(interp, node, ins, attrs):
    xp = _xp(ins)
    x = ins[0]
    axis = int(attrs.get("axis", -1))
    eps = float(attrs.get("epsilon", 1e-5))
    axes = tuple(range(axis % x.ndim, x.ndim))
    mean = xp.mean(x, axis=axes, keepdims=True)
    var = xp.mean((x - mean) ** 2, axis=axes, keepdims=True)
    out = (x - mean) / xp.sqrt(var + eps)
    if len(ins) > 1 and ins[1] is not None:
        out = out * ins[1]
    if len(ins) > 2 and ins[2] is not None:
        out = out + ins[2]
    return out


@_op("Flatten")
def _flatten(interp, node, ins, attrs):
    x = ins[0]
    axis = int(attrs.get("axis", 1))
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return _xp(ins).reshape(x, (lead, -1))


@_op("Shape")
def _shape(interp, node, ins, attrs):
    shape = np.asarray(np.shape(ins[0]), np.int64)
    start = int(attrs.get("start", 0))
    end = attrs.get("end")
    return shape[start : int(end) if end is not None else None]


@_op("Reshape")
def _reshape(interp, node, ins, attrs):
    target = _static_ints(ins[1], "Reshape shape")
    x = ins[0]
    shape = []
    for i, d in enumerate(target):
        if d == 0 and not int(attrs.get("allowzero", 0)):
            shape.append(x.shape[i])
        else:
            shape.append(d)
    return _xp([x]).reshape(x, tuple(shape))


@_op("Transpose")
def _transpose(interp, node, ins, attrs):
    perm = attrs.get("perm")
    x = ins[0]
    if perm is None:
        perm = list(reversed(range(x.ndim)))
    return _xp([x]).transpose(x, [int(p) for p in perm])


@_op("Squeeze")
def _squeeze(interp, node, ins, attrs):
    x = ins[0]
    axes = attrs.get("axes")
    if axes is None and len(ins) > 1 and ins[1] is not None:
        axes = _static_ints(ins[1], "Squeeze axes")
    xp = _xp([x])
    if axes is None:
        return xp.squeeze(x)
    return xp.squeeze(x, axis=tuple(int(a) for a in axes))


@_op("Unsqueeze")
def _unsqueeze(interp, node, ins, attrs):
    x = ins[0]
    axes = attrs.get("axes")
    if axes is None and len(ins) > 1 and ins[1] is not None:
        axes = _static_ints(ins[1], "Unsqueeze axes")
    xp = _xp([x])
    out = x
    for a in sorted(int(a) for a in axes):
        out = xp.expand_dims(out, a)
    return out


@_op("Concat")
def _concat(interp, node, ins, attrs):
    return _xp(ins).concatenate(ins, axis=int(attrs.get("axis", 0)))


@_op("Split")
def _split(interp, node, ins, attrs):
    x = ins[0]
    axis = int(attrs.get("axis", 0))
    xp = _xp([x])
    sizes = attrs.get("split")
    if sizes is None and len(ins) > 1 and ins[1] is not None:
        sizes = _static_ints(ins[1], "Split sizes")
    if sizes is None:
        n = int(attrs.get("num_outputs", len(node.get("output", []))))
        per = -(-x.shape[axis] // n)
        sizes = [per] * (n - 1) + [x.shape[axis] - per * (n - 1)]
    bounds = np.cumsum([int(s) for s in sizes])[:-1]
    return tuple(xp.split(x, [int(b) for b in bounds], axis=axis))


@_op("Slice")
def _slice(interp, node, ins, attrs):
    x = ins[0]
    if len(ins) > 1 and ins[1] is not None:  # opset >= 10: inputs
        starts = _static_ints(ins[1], "Slice starts")
        ends = _static_ints(ins[2], "Slice ends")
        axes = (
            _static_ints(ins[3], "Slice axes")
            if len(ins) > 3 and ins[3] is not None
            else list(range(len(starts)))
        )
        steps = (
            _static_ints(ins[4], "Slice steps")
            if len(ins) > 4 and ins[4] is not None
            else [1] * len(starts)
        )
    else:  # legacy attribute form
        starts = [int(v) for v in attrs["starts"]]
        ends = [int(v) for v in attrs["ends"]]
        axes = [int(v) for v in attrs.get("axes", range(len(starts)))]
        steps = [1] * len(starts)
    slicer: List[Any] = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        limit = x.shape[ax]
        # ONNX clamps INT_MAX/INT_MIN sentinels
        st = max(-limit, min(st, limit))
        en = max(-limit - 1, min(en, limit))
        slicer[ax] = slice(st, en, sp)
    return x[tuple(slicer)]


@_op("Gather")
def _gather(interp, node, ins, attrs):
    xp = _xp(ins)
    axis = int(attrs.get("axis", 0))
    return xp.take(ins[0], np.asarray(ins[1]) if _is_static(ins[1]) else ins[1], axis=axis)


@_op("Expand")
def _expand(interp, node, ins, attrs):
    shape = _static_ints(ins[1], "Expand shape")
    x = ins[0]
    # ONNX Expand uses bidirectional broadcast; dims of 1 in shape keep x's
    target = list(shape)
    if len(target) < x.ndim:
        target = [1] * (x.ndim - len(target)) + target
    xs = [1] * (len(target) - x.ndim) + list(x.shape)
    full = [max(t, s) for t, s in zip(target, xs)]
    return _xp([x]).broadcast_to(x, tuple(full))


@_op("Tile")
def _tile(interp, node, ins, attrs):
    reps = _static_ints(ins[1], "Tile repeats")
    return _xp([ins[0]]).tile(ins[0], tuple(reps))


@_op("Pad")
def _pad(interp, node, ins, attrs):
    x = ins[0]
    mode = attrs.get("mode", "constant")
    pads = (
        _static_ints(ins[1], "Pad pads")
        if len(ins) > 1 and ins[1] is not None
        else [int(v) for v in attrs["pads"]]
    )
    value = 0.0
    if len(ins) > 2 and ins[2] is not None:
        value = float(np.asarray(ins[2]).reshape(())) if _is_static(ins[2]) else ins[2]
    n = x.ndim
    pairs = [(pads[i], pads[i + n]) for i in range(n)]
    xp = _xp([x])
    if mode == "constant":
        return xp.pad(x, pairs, constant_values=value)
    return xp.pad(x, pairs, mode={"reflect": "reflect", "edge": "edge"}[mode])


@_op("Cast")
def _cast(interp, node, ins, attrs):
    to = int(attrs["to"])
    if to == onnx_proto._BFLOAT16:
        import jax.numpy as jnp

        return jnp.asarray(ins[0], jnp.bfloat16)
    dtype = _CAST_DTYPES[to]
    x = ins[0]
    if _is_static(x):
        return np.asarray(x).astype(dtype)
    return x.astype(dtype)


@_op("Constant")
def _constant(interp, node, ins, attrs):
    if "value" in attrs:
        return attrs["value"]
    for k, cast in (
        ("value_float", np.float32), ("value_int", np.int64),
        ("value_floats", np.float32), ("value_ints", np.int64),
    ):
        if k in attrs:
            return np.asarray(attrs[k], cast)
    raise ValueError("Constant node without value")


@_op("ConstantOfShape")
def _constant_of_shape(interp, node, ins, attrs):
    shape = _static_ints(ins[0], "ConstantOfShape shape")
    value = attrs.get("value")
    if value is None:
        return np.zeros(shape, np.float32)
    v = np.asarray(value).reshape(-1)[0]
    return np.full(shape, v, np.asarray(value).dtype)


@_op("Range")
def _range(interp, node, ins, attrs):
    xp = _xp(ins)
    if all(_is_static(v) for v in ins):
        s, l, d = (np.asarray(v).reshape(()) for v in ins)
        return np.arange(s, l, d)
    return xp.arange(ins[0], ins[1], ins[2])


def _reduce(fn_name):
    def impl(interp, node, ins, attrs):
        x = ins[0]
        axes = attrs.get("axes")
        if axes is None and len(ins) > 1 and ins[1] is not None:
            axes = _static_ints(ins[1], "Reduce axes")
        keepdims = bool(int(attrs.get("keepdims", 1)))
        xp = _xp([x])
        fn = getattr(xp, fn_name)
        if axes is None:
            if int(attrs.get("noop_with_empty_axes", 0)):
                return x
            return fn(x, axis=None, keepdims=keepdims)
        return fn(x, axis=tuple(int(a) for a in axes), keepdims=keepdims)

    return impl


_OPS["ReduceMean"] = _reduce("mean")
_OPS["ReduceSum"] = _reduce("sum")
_OPS["ReduceMax"] = _reduce("max")
_OPS["ReduceMin"] = _reduce("min")
_OPS["ReduceProd"] = _reduce("prod")


@_op("ArgMax")
def _argmax(interp, node, ins, attrs):
    xp = _xp(ins)
    axis = int(attrs.get("axis", 0))
    out = xp.argmax(ins[0], axis=axis)
    if int(attrs.get("keepdims", 1)):
        out = xp.expand_dims(out, axis)
    return out.astype(np.int64) if _is_static(out) else out


@_op("ArgMin")
def _argmin(interp, node, ins, attrs):
    xp = _xp(ins)
    axis = int(attrs.get("axis", 0))
    out = xp.argmin(ins[0], axis=axis)
    if int(attrs.get("keepdims", 1)):
        out = xp.expand_dims(out, axis)
    return out.astype(np.int64) if _is_static(out) else out


@_op("Dropout")
def _dropout(interp, node, ins, attrs):
    return ins[0]  # inference mode


@_op("Trilu")
def _trilu(interp, node, ins, attrs):
    xp = _xp([ins[0]])
    k = 0
    if len(ins) > 1 and ins[1] is not None:
        k = _static_ints(ins[1], "Trilu k")[0]
    if int(attrs.get("upper", 1)):
        return xp.triu(ins[0], k)
    return xp.tril(ins[0], k)


@_op("CumSum")
def _cumsum(interp, node, ins, attrs):
    axis = _static_ints(ins[1], "CumSum axis")[0]
    return _xp([ins[0]]).cumsum(ins[0], axis=axis)


# -- public API ---------------------------------------------------------------


def find_onnx_file(path) -> Optional[Path]:
    p = Path(path)
    if p.is_file() and p.suffix == ".onnx":
        return p
    if p.is_dir():
        cands = sorted(p.glob("*.onnx")) or sorted(p.glob("**/model.onnx"))
        if cands:
            return cands[0]
    return None


def load_onnx_bundle(path) -> Tuple[SimpleNamespace, Dict[str, Any]]:
    """Load a stock .onnx file as (bundle, params) with the same surface as
    native jax bundles (engines/jax_engine.py load_bundle): bundle.apply
    (params, *inputs) -> output (tuple if the graph has several)."""
    import jax.numpy as jnp

    onnx_file = find_onnx_file(path)
    if onnx_file is None:
        raise ValueError("no .onnx file found at {}".format(path))
    model = onnx_proto.parse_model(onnx_file.read_bytes())
    graph = model.get("graph") or {}
    interp = _Interpreter(graph)
    params = {k: jnp.asarray(v) for k, v in interp.init_params().items()}
    # the device copies shadow these in run(); keeping the host numpy copies
    # alive would double per-model host memory for nothing
    for name in interp.param_names:
        del interp.initializers[name]

    def apply(params, *inputs):
        outs = interp.run(params, *inputs)
        return outs[0] if len(outs) == 1 else outs

    bundle = SimpleNamespace(
        apply=apply,
        config={
            "arch": "onnx",
            "source": str(onnx_file),
            "inputs": interp.input_names,
            "outputs": interp.output_names,
            "input_shapes": interp.input_shapes,
            "opset": [o.get("version") for o in model.get("opset_import", [])],
            "producer": model.get("producer_name", ""),
        },
        input_names=interp.input_names,
        output_names=interp.output_names,
    )
    return bundle, params
