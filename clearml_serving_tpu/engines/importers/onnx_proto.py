"""Zero-dependency ONNX protobuf parser (wire format, schema-driven).

The serving image has no ``onnx`` package, and the ONNX file format is plain
protobuf — a generic tag/varint/length-delimited decoder plus the (stable,
public) ONNX message schema is all that is needed to read ModelProto files.
Only the fields the JAX importer consumes are mapped; unknown fields are
skipped per protobuf rules, so files from any exporter version parse.

Schema reference: onnx/onnx.proto3 (public spec). Wire format: protobuf
encoding spec (varint wire type 0, 64-bit 1, length-delimited 2, 32-bit 5).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = _read_varint(buf, pos)
    elif wire_type == 1:
        pos += 8
    elif wire_type == 2:
        n, pos = _read_varint(buf, pos)
        pos += n
    elif wire_type == 5:
        pos += 4
    else:
        raise ValueError("unsupported protobuf wire type {}".format(wire_type))
    return pos


def _zigzag_to_signed(v: int, bits: int = 64) -> int:
    # ONNX int64 fields use plain (two's complement) varints, not zigzag;
    # negative values arrive as 10-byte varints — wrap back to signed
    if v >= 1 << (bits - 1):
        v -= 1 << bits
    return v


# field kinds: "varint" | "svarint" | "bytes" | "string" | "float" |
#              ("message", schema) ; repeated=True collects lists, and
#              repeated varint/float fields also accept packed encoding.
Field = Tuple[str, Any, bool]

TENSOR_SHAPE_DIM = {1: ("dim_value", "svarint", False), 2: ("dim_param", "string", False)}
TENSOR_SHAPE = {1: ("dim", ("message", TENSOR_SHAPE_DIM), True)}
TENSOR_TYPE = {1: ("elem_type", "varint", False), 2: ("shape", ("message", TENSOR_SHAPE), False)}
TYPE_PROTO = {1: ("tensor_type", ("message", TENSOR_TYPE), False)}
VALUE_INFO = {1: ("name", "string", False), 2: ("type", ("message", TYPE_PROTO), False)}

TENSOR = {
    1: ("dims", "svarint", True),
    2: ("data_type", "varint", False),
    4: ("float_data", "float", True),
    5: ("int32_data", "svarint", True),
    6: ("string_data", "bytes", True),
    7: ("int64_data", "svarint", True),
    8: ("name", "string", False),
    9: ("raw_data", "bytes", False),
    10: ("double_data", "double", True),
    11: ("uint64_data", "varint", True),
}

ATTRIBUTE: Dict[int, Field] = {
    1: ("name", "string", False),
    2: ("f", "float32", False),
    3: ("i", "svarint", False),
    4: ("s", "bytes", False),
    5: ("t", ("message", TENSOR), False),
    7: ("floats", "float", True),
    8: ("ints", "svarint", True),
    9: ("strings", "bytes", True),
    10: ("tensors", ("message", TENSOR), True),
    20: ("type", "varint", False),
}

NODE = {
    1: ("input", "string", True),
    2: ("output", "string", True),
    3: ("name", "string", False),
    4: ("op_type", "string", False),
    5: ("attribute", ("message", ATTRIBUTE), True),
    7: ("domain", "string", False),
}

GRAPH = {
    1: ("node", ("message", NODE), True),
    2: ("name", "string", False),
    5: ("initializer", ("message", TENSOR), True),
    11: ("input", ("message", VALUE_INFO), True),
    12: ("output", ("message", VALUE_INFO), True),
    13: ("value_info", ("message", VALUE_INFO), True),
}

OPSET_ID = {1: ("domain", "string", False), 2: ("version", "svarint", False)}

MODEL = {
    1: ("ir_version", "svarint", False),
    2: ("producer_name", "string", False),
    5: ("model_version", "svarint", False),
    7: ("graph", ("message", GRAPH), False),
    8: ("opset_import", ("message", OPSET_ID), True),
}


def _parse_message(buf: bytes, schema: Dict[int, Field]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field_no, wire_type = tag >> 3, tag & 0x7
        spec = schema.get(field_no)
        if spec is None:
            pos = _skip_field(buf, pos, wire_type)
            continue
        name, kind, repeated = spec
        values: List[Any] = []
        if isinstance(kind, tuple):  # nested message
            if wire_type != 2:
                # same field number, wrong wire type: this buffer is a
                # DIFFERENT message type than the schema (e.g. probing a
                # SavedModel with the GraphDef schema, whose field 1 is the
                # varint schema_version) — skip instead of misreading the
                # varint as a length and walking off the buffer
                pos = _skip_field(buf, pos, wire_type)
                continue
            n, pos = _read_varint(buf, pos)
            values.append(_parse_message(buf[pos : pos + n], kind[1]))
            pos += n
        elif kind in ("varint", "svarint"):
            if wire_type == 2:  # packed repeated
                n, pos = _read_varint(buf, pos)
                stop = pos + n
                while pos < stop:
                    v, pos = _read_varint(buf, pos)
                    values.append(_zigzag_to_signed(v) if kind == "svarint" else v)
            else:
                v, pos = _read_varint(buf, pos)
                values.append(_zigzag_to_signed(v) if kind == "svarint" else v)
        elif kind in ("bytes", "string"):
            n, pos = _read_varint(buf, pos)
            raw = buf[pos : pos + n]
            pos += n
            values.append(raw.decode("utf-8", "replace") if kind == "string" else raw)
        elif kind == "float32":  # single fixed32
            values.append(struct.unpack_from("<f", buf, pos)[0])
            pos += 4
        elif kind == "float":  # repeated float (packed or not)
            if wire_type == 2:
                n, pos = _read_varint(buf, pos)
                values.extend(
                    struct.unpack_from("<{}f".format(n // 4), buf, pos)
                )
                pos += n
            else:
                values.append(struct.unpack_from("<f", buf, pos)[0])
                pos += 4
        elif kind == "double":
            if wire_type == 2:
                n, pos = _read_varint(buf, pos)
                values.extend(
                    struct.unpack_from("<{}d".format(n // 8), buf, pos)
                )
                pos += n
            else:
                values.append(struct.unpack_from("<d", buf, pos)[0])
                pos += 8
        else:
            raise ValueError("unknown field kind {!r}".format(kind))
        if repeated:
            out.setdefault(name, []).extend(values)
        else:
            out[name] = values[-1]
    return out


def parse_model(data: bytes) -> Dict[str, Any]:
    """ONNX ModelProto bytes -> nested dict of the mapped fields."""
    return _parse_message(data, MODEL)


# TensorProto.DataType -> numpy
_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}
_BFLOAT16 = 16


def tensor_to_numpy(t: Dict[str, Any]) -> np.ndarray:
    """Materialize a parsed TensorProto (raw_data or typed repeated fields)."""
    dims = [int(d) for d in t.get("dims", [])]
    dt = int(t.get("data_type", 1))
    if dt == _BFLOAT16:
        raw = t.get("raw_data", b"")
        # bfloat16 = top 16 bits of float32
        u16 = np.frombuffer(raw, np.uint16)
        arr = (u16.astype(np.uint32) << 16).view(np.float32)
        return arr.reshape(dims)
    if dt not in _DTYPES:
        raise ValueError("unsupported ONNX tensor data_type {}".format(dt))
    np_dtype = _DTYPES[dt]
    raw = t.get("raw_data")
    if raw:
        return np.frombuffer(raw, np_dtype).reshape(dims).copy()
    if dt == 10 and t.get("int32_data"):
        # FLOAT16 typed storage holds uint16 BIT PATTERNS in int32_data
        # (ONNX spec) — reinterpret, never numeric-cast
        return (
            np.asarray(t["int32_data"], np.int32)
            .astype(np.uint16)
            .view(np.float16)
            .reshape(dims)
        )
    for field, cast in (
        ("float_data", np.float32),
        ("int32_data", np.int32),
        ("int64_data", np.int64),
        ("double_data", np.float64),
        ("uint64_data", np.uint64),
    ):
        if t.get(field):
            return np.asarray(t[field], cast).astype(np_dtype).reshape(dims)
    return np.zeros(dims, np_dtype)


def value_info_shape(vi: Dict[str, Any]) -> List[Any]:
    """Static dims as ints; dynamic dims (dim_param / absent) as None."""
    tt = (vi.get("type") or {}).get("tensor_type") or {}
    dims = (tt.get("shape") or {}).get("dim") or []
    out: List[Any] = []
    for d in dims:
        if "dim_value" in d and int(d["dim_value"]) > 0:
            out.append(int(d["dim_value"]))
        else:
            out.append(None)
    return out
