"""TorchScript -> JAX importer.

The reference serves ``model.pt`` TorchScript files via Triton's libtorch
backend (triton_helper.py:165-167 materializes them; examples/pytorch).  The
TPU-native path converts instead of executing: the scripted module is run
through torch's classic (TorchScript-based) ONNX exporter in-memory — with
dynamic batch axes so shape chains stay symbolic — and the resulting graph is
interpreted into a JAX function (onnx_import), jit-compiling to one XLA
executable on TPU.

torch's exporter calls into the ``onnx`` python package only to inline
onnxscript functions, which classic-exported graphs do not use; that hook is
bypassed so the conversion works without ``onnx`` installed.
"""

from __future__ import annotations

import io
import tempfile
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple


def export_torch_to_onnx_bytes(
    module,
    example_shapes: Sequence[Sequence[int]],
    example_dtypes: Optional[Sequence[str]] = None,
) -> bytes:
    """torch.nn.Module / ScriptModule -> ONNX ModelProto bytes (classic
    exporter, dynamic batch dim on every input/output)."""
    import torch

    try:  # the onnxscript-inline hook needs `onnx`; classic graphs don't
        from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

        if getattr(onnx_proto_utils._add_onnxscript_fn, "__name__", "") != "_passthrough":
            _orig = onnx_proto_utils._add_onnxscript_fn

            def _passthrough(model_bytes, custom_opsets):
                return model_bytes

            onnx_proto_utils._add_onnxscript_fn = _passthrough
    except Exception:  # tpuserve: ignore[TPU401] private torch internals differ per version; export works without the patch
        pass

    dtypes = list(example_dtypes or [])
    args = tuple(
        torch.zeros(
            *shape,
            dtype=getattr(torch, dtypes[i]) if i < len(dtypes) else torch.float32,
        )
        for i, shape in enumerate(example_shapes)
    )
    input_names = ["input_{}".format(i) for i in range(len(args))]
    buf = io.BytesIO()
    module.eval()
    torch.onnx.export(
        module,
        args,
        buf,
        input_names=input_names,
        dynamic_axes={n: {0: "batch"} for n in input_names},
        dynamo=False,
    )
    return buf.getvalue()


def load_torchscript_bundle(
    path,
    example_shapes: Sequence[Sequence[int]],
    example_dtypes: Optional[Sequence[str]] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """TorchScript file -> (bundle, params), same surface as load_onnx_bundle.

    ``example_shapes`` supplies one concrete shape per model input (leading
    dim = any batch size; the export marks it dynamic), normally derived from
    the endpoint's input_size spec."""
    import torch

    from .onnx_import import load_onnx_bundle

    module = torch.jit.load(str(path), map_location="cpu")
    onnx_bytes = export_torch_to_onnx_bytes(module, example_shapes, example_dtypes)
    with tempfile.TemporaryDirectory() as td:
        f = Path(td) / "converted.onnx"
        f.write_bytes(onnx_bytes)
        bundle, params = load_onnx_bundle(f)
    bundle.config["arch"] = "torchscript"
    bundle.config["source"] = str(path)
    return bundle, params
