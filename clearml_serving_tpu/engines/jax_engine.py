"""In-process JAX/XLA engine — the TPU-native replacement for the reference's
Triton path (SURVEY.md §2.9 row 1), embedded directly in the serving process.

Model payloads are **jax bundles**: a directory with

    model_config.json   {"arch": "mlp"|"cnn"|"bert"|"llama", "config": {...}}
    params.msgpack      flax-serialized parameter pytree

(see save_bundle/load_bundle). The engine:

- builds the architecture from the models registry and restores params;
- jit-compiles ``apply`` once per **batch bucket** — incoming batches are padded
  up to the next bucket size so arbitrary client batch sizes cannot trigger an
  XLA recompilation storm (the TPU analog of Triton's dynamic batcher, and the
  #1 "hard part" in SURVEY.md §7);
- enables JAX's persistent compilation cache so container restart ≠ recompile
  (SURVEY.md §5.4);
- converts JSON bodies to typed arrays per the endpoint I/O spec and back.

A user ``Preprocess.load()`` returning a callable replaces the native loader:
the callable is treated as ``fn(*inputs) -> outputs`` and jitted the same way.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .base import BaseEngineRequest, EndpointModelError, register_engine
from ..utils.files import atomic_write_json, read_json

# NOTE: jax is imported lazily inside functions — engines/__init__ imports this
# module unconditionally, and CLI/statistics processes must not pay JAX/libtpu
# initialization (or contend for the TPU device lock) just to mutate config.

_DEFAULT_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128]
_compilation_cache_ready = False


def enable_persistent_compilation_cache() -> None:
    global _compilation_cache_ready
    if _compilation_cache_ready:
        return
    import jax

    cache_dir = os.environ.get("TPUSERVE_COMPILE_CACHE") or str(
        Path.home() / ".tpu-serving" / "xla-cache"
    )
    try:
        Path(cache_dir).mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _compilation_cache_ready = True
    except Exception:  # tpuserve: ignore[TPU401] cache dir may be read-only/unsupported; compile-per-process still works
        pass


# -- bundle IO ----------------------------------------------------------------

def save_bundle(path, arch: str, config: dict, params) -> None:
    """Write a jax model bundle directory."""
    import jax
    from flax import serialization

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    atomic_write_json(path / "model_config.json", {"arch": arch, "config": config})
    (path / "params.msgpack").write_bytes(serialization.msgpack_serialize(
        jax.tree.map(np.asarray, params)
    ))


def _endpoint_input_spec(endpoint) -> Tuple[List[List[int]], List[str]]:
    """Endpoint I/O spec -> per-input example shapes (batch dim 1) + dtypes."""
    sizes = endpoint.input_size or []
    types = endpoint.input_type or []
    if sizes and not isinstance(sizes[0], (list, tuple)):
        sizes = [sizes]  # single flat shape
    if isinstance(types, str):
        types = [types]
    shapes = [[1] + [int(d) for d in s] for s in sizes]
    torch_types = []
    for t in types:
        torch_types.append(
            {"float32": "float32", "float64": "float64", "int64": "int64",
             "int32": "int32", "uint8": "uint8", "bool": "bool"}.get(str(t), "float32")
        )
    return shapes, torch_types


def load_bundle(path, endpoint=None, config_overrides=None) -> Tuple[Any, Any]:
    """Returns (model_bundle namespace, params).

    ``config_overrides`` merges into the stored model config before the
    architecture builds (native jax bundles only) — used by the llm engine
    to enable serving-time features the checkpoint doesn't know about, e.g.
    LoRA stacks (lora_rank/max_loras) or scan_layers.

    Dispatches on payload format — the breadth Triton's multi-backend repo
    gives the reference (triton_helper.py:159-183):
    - ``*.onnx`` file (or dir containing one) -> ONNX->JAX importer
    - ``*.graphdef`` / ``*.pb`` frozen TF graph (or TF1 SavedModel wrapper)
      -> native GraphDef->JAX importer
    - ``*.pt`` / ``*.torchscript`` TorchScript -> ONNX (in-memory) -> JAX
      (needs the endpoint's input_size/input_type spec for example shapes)
    - otherwise: native jax bundle dir (model_config.json + params.msgpack)
    """
    import jax
    import jax.numpy as jnp
    from flax import serialization
    from .. import models
    from .importers.onnx_import import find_onnx_file, load_onnx_bundle

    path = Path(path)
    # a native bundle dir wins even if a stray .onnx sits next to it (e.g. a
    # converter that kept its source beside the output)
    is_native = path.is_dir() and (path / "model_config.json").exists()
    onnx_file = None if is_native else find_onnx_file(path)
    if onnx_file is not None:
        return load_onnx_bundle(onnx_file)
    if not is_native:
        from .importers.graphdef_import import (
            find_graphdef_file,
            load_graphdef_bundle,
        )

        gd_file = find_graphdef_file(path)
        if gd_file is not None:
            return load_graphdef_bundle(gd_file)
    ts_file = None
    if path.is_file() and path.suffix in (".pt", ".torchscript"):
        ts_file = path
    elif path.is_dir():
        cands = sorted(path.glob("*.pt")) + sorted(path.glob("*.torchscript"))
        if cands and not (path / "model_config.json").exists():
            ts_file = cands[0]
    if ts_file is not None:
        from .importers.torchscript_import import load_torchscript_bundle

        if endpoint is None or not endpoint.input_size:
            raise EndpointModelError(
                "TorchScript model {} needs the endpoint's input_size/"
                "input_type spec to derive export shapes".format(ts_file)
            )
        shapes, dtypes = _endpoint_input_spec(endpoint)
        return load_torchscript_bundle(ts_file, shapes, dtypes)

    if path.is_file():  # single-file bundles not supported; need the dir
        path = path.parent
    meta = read_json(path / "model_config.json")
    if not meta:
        raise EndpointModelError(
            "not a jax model bundle (missing model_config.json): {}".format(path)
        )
    model_cfg = dict(meta.get("config") or {})
    if config_overrides:
        model_cfg.update(config_overrides)
    bundle = models.build_model(meta["arch"], model_cfg)
    params_bytes = (path / "params.msgpack").read_bytes()
    params = serialization.msgpack_restore(bytearray(params_bytes))
    params = jax.tree.map(jnp.asarray, params)
    # architectures may adapt the stored layout to the build (e.g. stacking
    # per-layer dicts for scan_layers)
    prepare = getattr(bundle, "prepare_params", None)
    if prepare is not None:
        params = prepare(params)
    return bundle, params


# -- batching -----------------------------------------------------------------

def bucket_for(batch: int, buckets: List[int]) -> int:
    for b in buckets:
        if batch <= b:
            return b
    return batch  # beyond the largest bucket: compile exactly (rare)


@register_engine("jax", modules=["jax", "flax"])
class JaxEngineRequest(BaseEngineRequest):
    """Serve a jax bundle (or user-loaded callable) on the local TPU devices."""

    def __init__(self, *args, **kwargs):
        enable_persistent_compilation_cache()
        self._apply_fn: Optional[Callable] = None
        self._params = None
        self._jitted: Dict[int, Callable] = {}
        super().__init__(*args, **kwargs)
        aux = self.endpoint.auxiliary_cfg or {}
        if isinstance(aux, str):
            aux = {}
        batching = (aux.get("batching") or {}) if isinstance(aux, dict) else {}
        self._buckets = sorted(int(b) for b in batching.get("buckets", _DEFAULT_BUCKETS))
        self._warmup_done = False

    # -- loading ------------------------------------------------------------

    def _load_model(self) -> None:
        super()._load_model()
        if self._model is not None and callable(self._model):
            # user load() returned fn(*inputs)
            self._apply_fn = self._model
            self._params = None
        elif self._model_local_path:
            bundle, params = load_bundle(self._model_local_path, endpoint=self.endpoint)
            self._apply_fn = bundle.apply
            self._params = params
            self._model = bundle
        else:
            raise EndpointModelError(
                "jax endpoint {!r} has neither a model bundle nor a user load()".format(
                    self.endpoint.serving_url
                )
            )

    def _compiled(self, bucket: int) -> Callable:
        import jax

        fn = self._jitted.get(bucket)
        if fn is None:
            # bind the apply fn as a local: a lambda closing over self would
            # bake the attribute lookup's trace-time value in (TPU201)
            apply_fn = self._apply_fn
            if self._params is not None:
                fn = jax.jit(lambda params, *xs: apply_fn(params, *xs))
            else:
                fn = jax.jit(lambda *xs: apply_fn(*xs))
            self._jitted[bucket] = fn
        return fn

    # -- request IO ---------------------------------------------------------

    def _body_to_arrays(self, data: Any) -> List[np.ndarray]:
        """JSON body -> list of typed input arrays per the endpoint I/O spec.
        Accepts {"name": values, ...} or a bare array for single-input models."""
        names = self.endpoint.input_name or []
        types = self.endpoint.input_type or []
        if isinstance(data, dict) and names:
            raw = []
            for i, name in enumerate(names):
                if name not in data:
                    raise ValueError("missing input {!r}".format(name))
                raw.append(data[name])
        elif isinstance(data, dict) and len(data) == 1:
            raw = [next(iter(data.values()))]
        else:
            raw = [data]
        arrays = []
        for i, r in enumerate(raw):
            dt = np.dtype(types[i]) if i < len(types) else np.float32
            arrays.append(np.asarray(r, dtype=dt))
        return arrays

    def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "process"):
            # User process() is a full override of the compiled path (same
            # delegation contract as the CPU engines / reference triton engine).
            return self._preprocess.process(data, state, collect_fn)
        if isinstance(data, (list, dict)):
            arrays = self._body_to_arrays(data)
        elif isinstance(data, np.ndarray):
            arrays = [data]
        elif isinstance(data, (tuple,)):
            arrays = [np.asarray(a) for a in data]
        else:
            arrays = [np.asarray(data)]

        batch = arrays[0].shape[0] if arrays[0].ndim > 0 else 1
        bucket = bucket_for(batch, self._buckets)
        padded = []
        for a in arrays:
            if a.ndim == 0:
                a = a[None]
            if a.shape[0] != bucket:
                pad = [(0, bucket - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, pad)
            padded.append(a)
        import jax

        fn = self._compiled(bucket)
        if self._params is not None:
            out = fn(self._params, *padded)
        else:
            out = fn(*padded)
        out = jax.tree.map(lambda t: np.asarray(t)[:batch], out)
        return out

    def postprocess(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "postprocess"):
            return self._preprocess.postprocess(data, state, collect_fn)
        # numpy -> JSON-friendly (recursive; no jax needed here)
        def _to_list(x):
            if isinstance(x, np.ndarray):
                return x.tolist()
            if isinstance(x, dict):
                return {k: _to_list(v) for k, v in x.items()}
            if isinstance(x, (list, tuple)):
                return [_to_list(v) for v in x]
            return x
        if isinstance(data, (list, tuple)) and len(data) == 1:
            return _to_list(data[0])
        return _to_list(data)
