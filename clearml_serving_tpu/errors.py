"""Structured request-lifecycle errors shared by every serving layer.

The engine (llm/engine.py), the OpenAI/REST fronts (llm/openai_api.py,
serving/main.py) and the gRPC forwarding path (engines/grpc_client.py) all
raise these instead of bare RuntimeError/AioRpcError so the router can map a
failure to the correct HTTP status (408 deadline, 429/503 shed with
``Retry-After``, 503/504 upstream) and clients can branch on a stable
machine-readable ``code`` instead of parsing tracebacks.

This module is dependency-free on purpose: the router must import it without
pulling jax, and the engine without pulling aiohttp/grpc.
"""

from __future__ import annotations

from typing import Optional


def is_hbm_oom(ex: BaseException) -> bool:
    """Only XLA allocation failures qualify — never user-code error text (a
    user exception mentioning 'out of memory' must not kill the process).
    Shared by the router's crash-and-restart policy and the engine's
    step-failure handler, which must NOT wrap these in a RequestError (the
    wrap would route them away from the crash path)."""
    if type(ex).__name__ not in ("XlaRuntimeError", "RuntimeError"):
        return False
    text = str(ex)
    return "RESOURCE_EXHAUSTED" in text and (
        "hbm" in text.lower() or "allocat" in text.lower()
    )


class RequestError(Exception):
    """A request-scoped failure with an HTTP mapping.

    ``status``: the HTTP status the router returns. ``code``: stable
    machine-readable identifier carried in the JSON payload and SSE error
    events. ``retry_after``: seconds hint for the ``Retry-After`` header
    (None omits the header).
    """

    status: int = 500
    code: str = "internal"
    default_retry_after: Optional[float] = None

    def __init__(self, message: str, *, retry_after: Optional[float] = None):
        super().__init__(message)
        self.retry_after = (
            retry_after if retry_after is not None else self.default_retry_after
        )

    def payload(self) -> dict:
        return {"detail": str(self), "code": self.code}


class DeadlineExceededError(RequestError):
    """A per-request budget (queue-wait, TTFT, or total) elapsed."""

    status = 408
    code = "deadline_exceeded"

    def __init__(self, message: str, *, stage: str = "total",
                 retry_after: Optional[float] = None):
        super().__init__(message, retry_after=retry_after)
        self.stage = stage  # "queue" | "ttft" | "total"

    def payload(self) -> dict:
        out = super().payload()
        out["stage"] = self.stage
        return out


class EngineOverloadedError(RequestError):
    """Shed at admission: the pending queue or KV pool is saturated, or the
    class-aware scheduler / brownout controller dropped the request
    (docs/slo_scheduling.md).

    429 (not 503): the server is healthy, the CLIENT should back off and
    retry — the Retry-After hint sizes the backoff. The engine derives it
    from the observed admission drain rate, so deep queues advertise long
    backoffs instead of a constant. ``shed_class`` names the priority class
    the shed was booked under (surfaced in the JSON payload as ``class``).
    """

    status = 429
    code = "overloaded"
    default_retry_after = 1.0

    def __init__(self, message: str, *, retry_after: Optional[float] = None,
                 shed_class: Optional[str] = None):
        super().__init__(message, retry_after=retry_after)
        self.shed_class = shed_class

    def payload(self) -> dict:
        out = super().payload()
        if self.shed_class:
            out["class"] = self.shed_class
        return out


class EngineUnavailableError(RequestError):
    """The engine is stopped or the server is draining (SIGTERM)."""

    status = 503
    code = "unavailable"
    default_retry_after = 2.0


class EngineStepError(RequestError):
    """A device step (decode chunk / prefill) failed for this request.

    The engine recovered — only the affected request(s) carry this error;
    the process keeps serving.
    """

    status = 500
    code = "engine_step_failed"


class EngineStuckError(RequestError):
    """The watchdog detected a stalled decode loop and failed this request
    while recovering. Retryable once the engine reports ready again."""

    status = 503
    code = "engine_stalled"
    default_retry_after = 5.0


class HostTierAutoSizeError(ValueError):
    """``engine.prefix_cache_host_mb: "auto"`` could not size the host KV
    tier from /proc/meminfo (non-Linux platform or missing MemAvailable;
    docs/kv_tiering.md). Raised at engine CONSTRUCTION — endpoint load
    fails fast naming the knob instead of serving with a tier the operator
    believes is enabled."""


class UpstreamTimeoutError(RequestError):
    """gRPC upstream DEADLINE_EXCEEDED after the retry budget."""

    status = 504
    code = "upstream_timeout"


class UpstreamUnavailableError(RequestError):
    """gRPC upstream UNAVAILABLE after the retry budget."""

    status = 503
    code = "upstream_unavailable"
    default_retry_after = 2.0
