"""Speech-to-text serving core (v1/audio/transcriptions + translations).

Wraps a whisper bundle (models/whisper.py) the way EncoderCore wraps BERT:
host-side mel frontend (ops/audio.py), one jitted encoder executable per
fixed 30s chunk shape, and greedy decode as fused multi-step ``lax.scan``
chunks (the llm engine's dispatch-amortization trick — decode_steps tokens
per host round-trip). Long audio transcribes chunk-by-chunk, concatenating
text (OpenAI Whisper's sequential 30s windows, minus timestamp conditioning).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, List, Optional

import numpy as np

import jax
import jax.numpy as jnp


class AudioCore:
    def __init__(
        self,
        bundle,
        params,
        *,
        decode_steps: int = 16,
        max_new_tokens: Optional[int] = None,
        max_batch: int = 4,
        max_batch_delay_ms: float = 10.0,
    ):
        from ..ops.audio import mel_filter_bank

        if not hasattr(bundle, "encode") or not hasattr(bundle, "init_cache"):
            raise ValueError(
                "audio tasks need a speech encoder-decoder bundle (arch 'whisper')"
            )
        self.bundle = bundle
        cfg = bundle.config
        self.params = params
        self.sampling_rate = int(cfg.get("sampling_rate", 16000))
        self.hop_length = int(cfg.get("hop_length", 160))
        self.n_fft = int(cfg.get("n_fft", 400))
        self.chunk_length = int(cfg.get("chunk_length", 30))
        self.n_samples = self.sampling_rate * self.chunk_length
        self.n_mels = int(cfg["n_mels"])
        self.max_target = int(cfg["max_target_positions"])
        self.max_new_tokens = int(max_new_tokens or self.max_target - 8)
        self.decode_steps = max(1, int(decode_steps))
        self.eos_token_id = int(cfg.get("eos_token_id", 50257))
        self._prompts = {
            "transcribe": list(cfg.get("transcribe_prompt_ids") or []),
            "translate": list(cfg.get("translate_prompt_ids") or []),
        }
        # converted bundles carry the checkpoint's own filters in the tree
        filters = None
        if isinstance(params, dict) and "mel_filters" in params:
            filters = np.asarray(params["mel_filters"], np.float32)
            self.params = {k: v for k, v in params.items() if k != "mel_filters"}
        if filters is None:
            filters = mel_filter_bank(self.n_mels, self.n_fft, self.sampling_rate)
        self.mel_filters = filters
        # mel frames per chunk, bounded by the encoder's position table
        self._frames = min(
            self.n_samples // self.hop_length, 2 * int(cfg["max_source_positions"])
        )
        self._lock = threading.Lock()
        # cross-request micro-batching: concurrent utterances with the same
        # task batch into one encode + one greedy decode loop (batch-bucketed
        # executables), instead of serializing on the device lock
        self.max_batch = max(1, int(max_batch))
        self._batch_delay = max(0.0, float(max_batch_delay_ms)) / 1000.0
        self._pending: Optional[asyncio.Queue] = None
        self._loop = None
        self._batch_task = None
        self._carry = None  # deferred different-task item (runs first next round)

        self._encode_jit = jax.jit(bundle.encode)

        def _decode_chunk_batch(params, token, cache):
            def body(carry, _):
                token, cache = carry
                logits, cache = bundle.decode(params, token, cache)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), nxt

            (_, cache), toks = jax.lax.scan(
                body, (token, cache), None, length=self.decode_steps
            )
            return toks, cache  # [steps, B]

        self._decode_chunk_batch_jit = jax.jit(
            _decode_chunk_batch, donate_argnums=(2,)
        )

        def _prime(params, token, cache):
            # teacher-forced prompt token: extend the cache, ignore logits
            logits, cache = bundle.decode(params, token, cache)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        self._prime_jit = jax.jit(_prime, donate_argnums=(2,))

    def prompt_ids(self, task: str) -> List[int]:
        ids = self._prompts.get(task) or self._prompts.get("transcribe") or []
        if not ids:
            raise ValueError(
                "bundle carries no decoder prompt ids for task {!r} (convert "
                "with engines/importers/convert_hf_whisper.py)".format(task)
            )
        return ids

    def _transcribe_chunk(self, pcm: np.ndarray, prompt: List[int]) -> List[int]:
        return self._transcribe_batch([pcm], prompt)[0]

    def transcribe_ids(self, pcm: np.ndarray, task: str = "transcribe") -> List[int]:
        """Full utterance -> generated token ids (30s windows, concatenated)."""
        prompt = self.prompt_ids(task)
        pcm = np.asarray(pcm, np.float32).reshape(-1)
        if len(pcm) == 0:
            return []
        ids: List[int] = []
        for start in range(0, len(pcm), self.n_samples):
            ids.extend(self._transcribe_chunk(pcm[start : start + self.n_samples], prompt))
        return ids

    # -- cross-request batching ------------------------------------------------

    def _batch_bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_batch)

    def _transcribe_batch(
        self, pcms: List[np.ndarray], prompt: List[int]
    ) -> List[List[int]]:
        """N ≤ max_batch single-window utterances, one shared prompt -> per-
        utterance token ids. One encode + one greedy loop over the batch;
        finished sequences keep stepping (masked host-side) until all hit
        eos or the budget."""
        from ..ops.audio import log_mel_spectrogram

        n = len(pcms)
        bucket = self._batch_bucket(n)
        mels = np.zeros((bucket, self.n_mels, self._frames), np.float32)
        for i, pcm in enumerate(pcms):
            mels[i] = log_mel_spectrogram(
                pcm, self.mel_filters, n_fft=self.n_fft,
                hop_length=self.hop_length, n_samples=self.n_samples,
            )[:, : self._frames]
        with self._lock:
            enc = self._encode_jit(self.params, jnp.asarray(mels))
            cache = self.bundle.init_cache(self.params, enc, self.max_target)
            next_tok = jnp.full((bucket,), prompt[0], jnp.int32)
            for tok in prompt[1:]:
                _, cache = self._prime_jit(self.params, next_tok, cache)
                next_tok = jnp.full((bucket,), tok, jnp.int32)
            first, cache = self._prime_jit(self.params, next_tok, cache)
            outs: List[List[int]] = [[] for _ in range(bucket)]
            done = [False] * bucket
            budget = min(self.max_new_tokens, self.max_target - len(prompt) - 1)
            token = first
            while not all(done[:n]):
                step = np.asarray(token)
                for i in range(n):
                    if not done[i]:
                        if int(step[i]) == self.eos_token_id or len(outs[i]) >= budget:
                            done[i] = True
                        else:
                            outs[i].append(int(step[i]))
                if all(done[:n]):
                    break
                chunk, cache = self._decode_chunk_batch_jit(
                    self.params, token, cache
                )                                               # [steps, B]
                chunk_np = np.asarray(chunk)
                for s_i in range(chunk_np.shape[0] - 1):
                    for i in range(n):
                        if done[i]:
                            continue
                        t = int(chunk_np[s_i, i])
                        if t == self.eos_token_id or len(outs[i]) >= budget:
                            done[i] = True
                        else:
                            outs[i].append(t)
                token = jnp.asarray(chunk_np[-1], jnp.int32)
        return outs[:n]

    async def transcribe_ids_async(
        self, pcm: np.ndarray, task: str = "transcribe"
    ) -> List[int]:
        """Batching front door: concurrent same-task utterances share one
        encode/decode pass. Long audio submits each 30s window in order."""
        self.prompt_ids(task)  # surface config errors even for empty audio
        loop = asyncio.get_running_loop()
        if self._pending is None or getattr(self, "_loop", None) is not loop:
            # an asyncio.Queue is bound to its creating loop: rebind when the
            # serving loop changes (tests, process-model restarts) or a put
            # into the dead loop's queue would hang forever
            self._pending = asyncio.Queue()
            self._loop = loop
            self._batch_task = None
        pcm = np.asarray(pcm, np.float32).reshape(-1)
        if len(pcm) == 0:
            return []
        ids: List[int] = []
        for start in range(0, len(pcm), self.n_samples):
            fut = loop.create_future()
            await self._pending.put((pcm[start : start + self.n_samples], task, fut))
            self._ensure_batch_loop()
            ids.extend(await fut)
        return ids

    def _ensure_batch_loop(self) -> None:
        if self._batch_task is None or self._batch_task.done():
            self._batch_task = asyncio.get_running_loop().create_task(
                self._batch_loop()
            )

    async def _batch_loop(self) -> None:
        while True:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = await asyncio.wait_for(self._pending.get(), timeout=5.0)
                except asyncio.TimeoutError:
                    if self._pending.empty():
                        return  # idle; a new submit restarts the loop
                    continue
            batch = [first]
            deadline = (
                asyncio.get_running_loop().time() + self._batch_delay
            )
            while len(batch) < self.max_batch:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._pending.get(), timeout=remaining)
                except asyncio.TimeoutError:
                    break
                if item[1] != batch[0][1]:
                    # different task (prompt ids differ): carry it to the
                    # FRONT of the next round — re-queueing at the tail
                    # could starve it under sustained same-task load
                    self._carry = item
                    break
                batch.append(item)
            pcms = [b[0] for b in batch]
            futures = [b[2] for b in batch]
            task = batch[0][1]
            try:
                prompt = self.prompt_ids(task)
                outs = await asyncio.to_thread(self._transcribe_batch, pcms, prompt)
                for fut, out in zip(futures, outs):
                    if not fut.done():
                        fut.set_result(out)
            except Exception as ex:
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(ex)
