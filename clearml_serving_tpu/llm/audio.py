"""Speech-to-text serving core (v1/audio/transcriptions + translations).

Wraps a whisper bundle (models/whisper.py) the way EncoderCore wraps BERT:
host-side mel frontend (ops/audio.py), one jitted encoder executable per
fixed 30s chunk shape, and greedy decode as fused multi-step ``lax.scan``
chunks (the llm engine's dispatch-amortization trick — decode_steps tokens
per host round-trip). Long audio transcribes chunk-by-chunk, concatenating
text (OpenAI Whisper's sequential 30s windows, minus timestamp conditioning).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

import jax
import jax.numpy as jnp


class AudioCore:
    def __init__(
        self,
        bundle,
        params,
        *,
        decode_steps: int = 16,
        max_new_tokens: Optional[int] = None,
    ):
        from ..ops.audio import mel_filter_bank

        if not hasattr(bundle, "encode") or not hasattr(bundle, "init_cache"):
            raise ValueError(
                "audio tasks need a speech encoder-decoder bundle (arch 'whisper')"
            )
        self.bundle = bundle
        cfg = bundle.config
        self.params = params
        self.sampling_rate = int(cfg.get("sampling_rate", 16000))
        self.hop_length = int(cfg.get("hop_length", 160))
        self.n_fft = int(cfg.get("n_fft", 400))
        self.chunk_length = int(cfg.get("chunk_length", 30))
        self.n_samples = self.sampling_rate * self.chunk_length
        self.n_mels = int(cfg["n_mels"])
        self.max_target = int(cfg["max_target_positions"])
        self.max_new_tokens = int(max_new_tokens or self.max_target - 8)
        self.decode_steps = max(1, int(decode_steps))
        self.eos_token_id = int(cfg.get("eos_token_id", 50257))
        self._prompts = {
            "transcribe": list(cfg.get("transcribe_prompt_ids") or []),
            "translate": list(cfg.get("translate_prompt_ids") or []),
        }
        # converted bundles carry the checkpoint's own filters in the tree
        filters = None
        if isinstance(params, dict) and "mel_filters" in params:
            filters = np.asarray(params["mel_filters"], np.float32)
            self.params = {k: v for k, v in params.items() if k != "mel_filters"}
        if filters is None:
            filters = mel_filter_bank(self.n_mels, self.n_fft, self.sampling_rate)
        self.mel_filters = filters
        # mel frames per chunk, bounded by the encoder's position table
        self._frames = min(
            self.n_samples // self.hop_length, 2 * int(cfg["max_source_positions"])
        )
        self._lock = threading.Lock()

        self._encode_jit = jax.jit(bundle.encode)

        def _decode_chunk(params, token, cache):
            def body(carry, _):
                token, cache = carry
                logits, cache = bundle.decode(params, token, cache)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), nxt

            (_, cache), toks = jax.lax.scan(
                body, (token, cache), None, length=self.decode_steps
            )
            return toks[:, 0], cache  # [steps] for batch 1

        self._decode_chunk_jit = jax.jit(_decode_chunk, donate_argnums=(2,))

        def _prime(params, token, cache):
            # teacher-forced prompt token: extend the cache, ignore logits
            logits, cache = bundle.decode(params, token, cache)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        self._prime_jit = jax.jit(_prime, donate_argnums=(2,))

    def prompt_ids(self, task: str) -> List[int]:
        ids = self._prompts.get(task) or self._prompts.get("transcribe") or []
        if not ids:
            raise ValueError(
                "bundle carries no decoder prompt ids for task {!r} (convert "
                "with engines/importers/convert_hf_whisper.py)".format(task)
            )
        return ids

    def _transcribe_chunk(self, pcm: np.ndarray, prompt: List[int]) -> List[int]:
        from ..ops.audio import log_mel_spectrogram

        mel = log_mel_spectrogram(
            pcm,
            self.mel_filters,
            n_fft=self.n_fft,
            hop_length=self.hop_length,
            n_samples=self.n_samples,
        )[None, :, : self._frames]
        with self._lock:  # serialize per-core device decode state
            enc = self._encode_jit(self.params, jnp.asarray(mel))
            cache = self.bundle.init_cache(self.params, enc, self.max_target)
            next_tok = jnp.asarray([prompt[0]], jnp.int32)
            for tok in prompt[1:]:
                _, cache = self._prime_jit(self.params, next_tok, cache)
                next_tok = jnp.asarray([tok], jnp.int32)
            first, cache = self._prime_jit(self.params, next_tok, cache)
            out: List[int] = []
            token = first
            budget = min(self.max_new_tokens, self.max_target - len(prompt) - 1)
            while len(out) < budget:
                steps = np.asarray(token)
                if int(steps[0]) == self.eos_token_id:
                    break
                out.append(int(steps[0]))
                chunk, cache = self._decode_chunk_jit(self.params, token, cache)
                chunk_np = np.asarray(chunk)
                for t in chunk_np[:-1]:
                    if int(t) == self.eos_token_id or len(out) >= budget:
                        return out
                    out.append(int(t))
                token = jnp.asarray([chunk_np[-1]], jnp.int32)
        return out

    def transcribe_ids(self, pcm: np.ndarray, task: str = "transcribe") -> List[int]:
        """Full utterance -> generated token ids (30s windows, concatenated)."""
        prompt = self.prompt_ids(task)
        pcm = np.asarray(pcm, np.float32).reshape(-1)
        if len(pcm) == 0:
            return []
        ids: List[int] = []
        for start in range(0, len(pcm), self.n_samples):
            ids.extend(self._transcribe_chunk(pcm[start : start + self.n_samples], prompt))
        return ids
