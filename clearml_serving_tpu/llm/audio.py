"""Speech-to-text serving core (v1/audio/transcriptions + translations).

Wraps a whisper bundle (models/whisper.py) the way EncoderCore wraps BERT:
host-side mel frontend (ops/audio.py), one jitted encoder executable per
fixed 30s chunk shape, and greedy decode as fused multi-step ``lax.scan``
chunks (the llm engine's dispatch-amortization trick — decode_steps tokens
per host round-trip). Long audio transcribes chunk-by-chunk, concatenating
text (OpenAI Whisper's sequential 30s windows). verbose_json responses use
timestamp-conditioned decoding — the well-formedness rules run in-graph
inside the scan — and a host-side parser turns the marker tokens into
segments (reference preprocess_service.py:1031-1075 delegates this to vLLM).
"""

from __future__ import annotations

import asyncio
import threading
from functools import partial
from typing import Any, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _median_filter_time(x: np.ndarray, width: int = 7) -> np.ndarray:
    """Median filter along the LAST axis (edge-padded), openai-whisper's
    timing smoothing (medfilt_width=7)."""
    if width <= 1 or x.shape[-1] == 0:
        return x
    pad = width // 2
    padded = np.concatenate(
        [np.repeat(x[..., :1], pad, axis=-1), x,
         np.repeat(x[..., -1:], pad, axis=-1)],
        axis=-1,
    )
    windows = np.lib.stride_tricks.sliding_window_view(padded, width, axis=-1)
    return np.median(windows, axis=-1)


def _dtw_path(cost: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Monotonic alignment through a [N_tokens, M_frames] cost matrix
    (openai-whisper's dtw over -attention): returns (token_idx, frame_idx)
    index arrays of the optimal path.

    The DP runs over ANTI-DIAGONALS: every predecessor of a cell on
    diagonal d (match d-2, deletion d-1, insertion d-1) lies on an earlier
    diagonal, so each diagonal is one vectorized numpy step — a naive
    cell-by-cell Python loop is ~660k iterations for a full 30s window
    (~440 tokens x 1500 frames) of GIL-bound time per window
    (openai-whisper jits this same kernel with numba/triton)."""
    n, m = cost.shape
    trace = np.zeros((n + 1, m + 1), np.int8)
    # diag arrays indexed by i: entry i holds acc[i, d - i] (inf off-band)
    prev2 = np.full(n + 1, np.inf)   # diagonal d-2
    prev1 = np.full(n + 1, np.inf)   # diagonal d-1
    prev2[0] = 0.0                   # acc[0, 0]
    for d in range(2, n + m + 1):
        lo = max(1, d - m)   # never > hi for 2 <= d <= n+m with n,m >= 1
        hi = min(n, d - 1)
        i_arr = np.arange(lo, hi + 1)
        j_arr = d - i_arr
        c0 = prev2[i_arr - 1]        # match: acc[i-1, j-1]
        c1 = prev1[i_arr - 1]        # token advances: acc[i-1, j]
        c2 = prev1[i_arr]            # frame advances: acc[i, j-1]
        # tie-break priority matches the scalar formulation: 0, then 1
        choice = np.where(
            (c0 <= c1) & (c0 <= c2), 0, np.where(c1 <= c2, 1, 2)
        ).astype(np.int8)
        best = np.where(choice == 0, c0, np.where(choice == 1, c1, c2))
        cur = np.full(n + 1, np.inf)
        cur[i_arr] = best + cost[i_arr - 1, j_arr - 1]
        trace[i_arr, j_arr] = choice
        prev2, prev1 = prev1, cur
    i, j = n, m
    ti: List[int] = []
    fi: List[int] = []
    while i > 0 and j > 0:
        ti.append(i - 1)
        fi.append(j - 1)
        step = trace[i, j]
        if step == 0:
            i, j = i - 1, j - 1
        elif step == 1:
            i -= 1
        else:
            j -= 1
    return np.array(ti[::-1]), np.array(fi[::-1])


class AudioCore:
    def __init__(
        self,
        bundle,
        params,
        *,
        decode_steps: int = 16,
        max_new_tokens: Optional[int] = None,
        max_batch: int = 4,
        max_batch_delay_ms: float = 10.0,
    ):
        from ..ops.audio import mel_filter_bank

        if not hasattr(bundle, "encode") or not hasattr(bundle, "init_cache"):
            raise ValueError(
                "audio tasks need a speech encoder-decoder bundle (arch 'whisper')"
            )
        self.bundle = bundle
        cfg = bundle.config
        self.params = params
        self.sampling_rate = int(cfg.get("sampling_rate", 16000))
        self.hop_length = int(cfg.get("hop_length", 160))
        self.n_fft = int(cfg.get("n_fft", 400))
        self.chunk_length = int(cfg.get("chunk_length", 30))
        self.n_samples = self.sampling_rate * self.chunk_length
        self.n_mels = int(cfg["n_mels"])
        self.max_target = int(cfg["max_target_positions"])
        self.max_new_tokens = int(max_new_tokens or self.max_target - 8)
        # captured as a local below: jitted closures must not read self
        # (trace-time snapshot; tpuserve-analyze TPU201)
        self.decode_steps = decode_steps = max(1, int(decode_steps))
        self.eos_token_id = int(cfg.get("eos_token_id", 50257))
        self._prompts = {
            "transcribe": list(cfg.get("transcribe_prompt_ids") or []),
            "translate": list(cfg.get("translate_prompt_ids") or []),
        }
        # converted bundles carry the checkpoint's own filters in the tree
        filters = None
        if isinstance(params, dict) and "mel_filters" in params:
            filters = np.asarray(params["mel_filters"], np.float32)
            self.params = {k: v for k, v in params.items() if k != "mel_filters"}
        if filters is None:
            filters = mel_filter_bank(self.n_mels, self.n_fft, self.sampling_rate)
        self.mel_filters = filters
        # mel frames per chunk, bounded by the encoder's position table
        self._frames = min(
            self.n_samples // self.hop_length, 2 * int(cfg["max_source_positions"])
        )
        self._lock = threading.Lock()
        # cross-request micro-batching: concurrent utterances with the same
        # task batch into one encode + one greedy decode loop (batch-bucketed
        # executables), instead of serializing on the device lock
        self.max_batch = max(1, int(max_batch))
        self._batch_delay = max(0.0, float(max_batch_delay_ms)) / 1000.0
        self._pending: Optional[asyncio.Queue] = None
        self._loop = None
        self._batch_task = None
        self._carry = None  # deferred different-task item (runs first next round)

        self._encode_jit = jax.jit(bundle.encode)
        self._align_jit = None  # word-timestamp DTW pass; built on first use

        def _decode_chunk_batch(params, token, cache):
            def body(carry, _):
                token, cache = carry
                logits, cache = bundle.decode(params, token, cache)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), nxt

            (_, cache), toks = jax.lax.scan(
                body, (token, cache), None, length=decode_steps
            )
            return toks, cache  # [steps, B]

        self._decode_chunk_batch_jit = jax.jit(
            _decode_chunk_batch, donate_argnums=(2,)
        )

        def _prime(params, token, cache):
            # teacher-forced prompt token: extend the cache, ignore logits
            logits, cache = bundle.decode(params, token, cache)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        self._prime_jit = jax.jit(_prime, donate_argnums=(2,))

        # -- timestamp-conditioned decoding (verbose_json segments) ----------
        # Whisper emits time markers as vocabulary ids >= timestamp_begin
        # ((id - begin) * time_precision seconds) when the prompt OMITS
        # <|notimestamps|>. The well-formedness rules (OpenAI's decoding
        # constraints, mirrored by HF's WhisperTimeStampLogitsProcessor) run
        # IN-GRAPH inside the fused decode scan so segment structure is
        # guaranteed without per-token host round-trips.
        self.timestamp_begin = (
            int(cfg["timestamp_begin"]) if cfg.get("timestamp_begin") else None
        )
        self.notimestamps_id = (
            int(cfg["notimestamps_token_id"])
            if cfg.get("notimestamps_token_id") is not None
            else None
        )
        self.time_precision = float(cfg.get("time_precision", 0.02))
        self._decode_chunk_ts_jit = None
        if self.timestamp_begin is not None:
            ts_begin = self.timestamp_begin
            eos = self.eos_token_id
            vocab = int(cfg["vocab_size"])
            max_initial = int(cfg.get("max_initial_timestamp_index", 50))
            ids = jnp.arange(vocab)
            is_ts = ids >= ts_begin
            text_not_eos = (~is_ts) & (ids != eos)
            neg = jnp.float32(-1e30)

            def _ts_body(params, carry, step):
                # pen_is_ts is the pairing state of the SAMPLED sequence:
                # initialized True because with fewer than two sampled
                # tokens the "penultimate" defaults to timestamp (HF's
                # len<2 case) — so the forced initial marker is a COMPLETED
                # pair and text must follow, never a second marker
                token, pen_is_ts, max_ts, cache = carry
                logits, cache = bundle.decode(params, token, cache)
                lg = logits.astype(jnp.float32)
                last_was = (token >= ts_begin)[:, None]
                pen_was = pen_is_ts[:, None]
                # a completed <|t|><|t|> pair -> next must be text
                lg = jnp.where(last_was & pen_was & is_ts[None, :], neg, lg)
                # a single open timestamp -> next must be its pair or EOS
                lg = jnp.where(
                    last_was & (~pen_was) & text_not_eos[None, :], neg, lg
                )
                # monotonic: the pair's second element may repeat the value,
                # otherwise timestamps strictly increase
                bound = jnp.where(
                    (token >= ts_begin) & ~pen_is_ts, max_ts, max_ts + 1
                )
                lg = jnp.where(
                    is_ts[None, :] & (ids[None, :] < bound[:, None]), neg, lg
                )
                # first sampled token is a timestamp near the window start
                first = step == 0
                lg = jnp.where(first & (~is_ts)[None, :], neg, lg)
                lg = jnp.where(
                    first & (ids > ts_begin + max_initial)[None, :], neg, lg
                )
                # if total timestamp mass beats every text token, force a
                # timestamp (computed AFTER the structural masks, so a
                # forbidden timestamp can never be forced back in)
                lp = jax.nn.log_softmax(lg, axis=-1)
                ts_lse = jax.nn.logsumexp(
                    jnp.where(is_ts[None, :], lp, neg), axis=-1
                )
                max_text = jnp.max(jnp.where(is_ts[None, :], neg, lp), axis=-1)
                force = (ts_lse > max_text)[:, None]
                lg = jnp.where(force & (~is_ts)[None, :], neg, lg)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                new_max = jnp.where(nxt >= ts_begin, nxt, max_ts)
                # leaving step 0 the sampled length is 1, so the penultimate
                # stays "timestamp" (len<2 default); afterwards it tracks
                # the previously sampled token
                new_pen = jnp.where(step == 0, True, token >= ts_begin)
                return (nxt, new_pen, new_max, cache), nxt

            def _decode_chunk_ts(params, token, pen_is_ts, max_ts, cache, start_step):
                (token, pen_is_ts, max_ts, cache), toks = jax.lax.scan(
                    partial(_ts_body, params),
                    (token, pen_is_ts, max_ts, cache),
                    start_step + jnp.arange(decode_steps),
                )
                return toks, token, pen_is_ts, max_ts, cache

            self._decode_chunk_ts_jit = jax.jit(
                _decode_chunk_ts, donate_argnums=(4,)
            )

    def prompt_ids(self, task: str, timestamps: bool = False) -> List[int]:
        ids = self._prompts.get(task) or self._prompts.get("transcribe") or []
        if not ids:
            raise ValueError(
                "bundle carries no decoder prompt ids for task {!r} (convert "
                "with engines/importers/convert_hf_whisper.py)".format(task)
            )
        if timestamps:
            if self._decode_chunk_ts_jit is None:
                raise ValueError(
                    "bundle carries no timestamp vocabulary (re-convert with "
                    "a tokenizer that has <|notimestamps|> to enable "
                    "verbose_json segments)"
                )
            # timestamps flow when the prompt OMITS <|notimestamps|>
            ids = [i for i in ids if i != self.notimestamps_id]
        return ids

    def _transcribe_chunk(self, pcm: np.ndarray, prompt: List[int]) -> List[int]:
        return self._transcribe_batch([pcm], prompt)[0]

    def transcribe_ids(self, pcm: np.ndarray, task: str = "transcribe") -> List[int]:
        """Full utterance -> generated token ids (30s windows, concatenated)."""
        prompt = self.prompt_ids(task)
        pcm = np.asarray(pcm, np.float32).reshape(-1)
        if len(pcm) == 0:
            return []
        ids: List[int] = []
        for start in range(0, len(pcm), self.n_samples):
            ids.extend(self._transcribe_chunk(pcm[start : start + self.n_samples], prompt))
        return ids

    def parse_segments(
        self, window_ids: List[List[int]], duration: float
    ) -> List[dict]:
        """Timestamp-token streams (one per fixed window) -> verbose_json
        segments. A segment is text bracketed by <|t0|> ... <|t1|>; the
        closing/opening pair between segments shares the value. Windows
        advance by the fixed chunk length (the serving path transcribes
        fixed 30s windows rather than seek-to-last-timestamp)."""
        ts_begin = self.timestamp_begin
        precision = self.time_precision
        window_s = float(self.chunk_length)
        segments: List[dict] = []
        for w, ids in enumerate(window_ids):
            offset = w * window_s
            window_end = min(duration, offset + window_s)
            cur_start: Optional[float] = None
            cur_tokens: List[int] = []
            for t in ids:
                if ts_begin is not None and t >= ts_begin:
                    # markers emitted in the window's zero-padded region must
                    # not place segments past the end of the actual audio
                    mark = min((t - ts_begin) * precision + offset, window_end)
                    if cur_tokens:
                        segments.append(
                            {"start": cur_start, "end": mark, "tokens": cur_tokens}
                        )
                        cur_tokens = []
                    cur_start = mark
                else:
                    if cur_start is None:
                        cur_start = offset  # malformed head: anchor to window
                    cur_tokens.append(t)
            if cur_tokens:  # unterminated tail: close at the window edge
                segments.append(
                    {"start": cur_start, "end": window_end, "tokens": cur_tokens}
                )
        out = []
        for i, seg in enumerate(segments):
            out.append(
                {
                    "id": i,
                    "seek": int(seg["start"] // window_s * window_s * 100),
                    "start": round(float(seg["start"]), 2),
                    "end": round(float(seg["end"]), 2),
                    "tokens": seg["tokens"],
                }
            )
        return out

    @staticmethod
    def words_from_segments(segments: List[dict]) -> List[dict]:
        """OpenAI ``timestamp_granularities=["word"]`` payload from decoded
        segments (each must carry "text"/"start"/"end").

        Word times interpolate each segment's span proportionally to
        character length — the standard lightweight approximation (exact
        Whisper word timing needs DTW over cross-attention alignment heads,
        which the fused decode scan does not emit; segment boundaries remain
        model-exact timestamp tokens)."""
        words: List[dict] = []
        for seg in segments:
            text = seg.get("text") or ""
            tokens = text.split()
            if not tokens:
                continue
            span = max(float(seg["end"]) - float(seg["start"]), 0.0)
            total_chars = sum(len(w) for w in tokens) or 1
            cursor = float(seg["start"])
            for w in tokens:
                dur = span * (len(w) / total_chars)
                words.append(
                    {
                        "word": w,
                        "start": round(cursor, 2),
                        "end": round(min(cursor + dur, float(seg["end"])), 2),
                    }
                )
                cursor += dur
        return words

    # -- word timestamps: cross-attention DTW ------------------------------

    def _alignment_heads(self) -> tuple:
        """Per-model alignment heads (config "alignment_heads" as [layer,
        head] pairs, recorded by the HF converter when the checkpoint ships
        them), else openai-whisper's generic fallback: every head of the
        top half of the decoder."""
        cfg = self.bundle.config
        heads = cfg.get("alignment_heads")
        if heads:
            return tuple((int(l), int(h)) for l, h in heads)
        n_layers = int(cfg["n_text_layers"])
        n_heads = int(cfg["n_heads"])
        return tuple(
            (l, h) for l in range(n_layers // 2, n_layers)
            for h in range(n_heads)
        )

    def words_dtw(
        self, pcm: np.ndarray, windows: List[List[int]], tokenizer,
        task: str = "transcribe",
    ) -> Optional[List[dict]]:
        """Whisper-faithful word timestamps: one teacher-forced decoder pass
        per 30s window emitting the alignment heads' cross-attention maps
        (models/whisper.py cross_attention_alignment; padding frames masked
        pre-softmax), then openai-whisper's timing pipeline — per-head
        z-norm over tokens, median filter over time, head average, DTW over
        the negative map — and token->word grouping. Grouping is
        unicode-safe: consecutive tokens accumulate until they decode
        without a trailing replacement char (byte-level BPE splits non-ASCII
        codepoints across tokens), and words break at whitespace AND at
        timestamp markers (segment boundaries — bounds word length for
        unspaced scripts). Returns None when the bundle has no alignment
        surface (caller falls back to proportional interpolation).
        Reference surface: preprocess_service.py:1031-1075 (vLLM whisper
        verbose_json)."""
        align_fn = getattr(self.bundle, "cross_attention_alignment", None)
        if align_fn is None or self.timestamp_begin is None:
            return None
        from ..ops.audio import log_mel_spectrogram

        heads = self._alignment_heads()
        if self._align_jit is None:
            self._align_jit = jax.jit(
                lambda p, tok, enc, nf: align_fn(p, tok, enc, heads, nf)
            )
        prompt = self.prompt_ids(task, timestamps=True)
        ts_begin = self.timestamp_begin
        frame_s = 2.0 * self.hop_length / self.sampling_rate  # enc position
        pcm = np.asarray(pcm, np.float32).reshape(-1)
        # phase 1 — device passes only (the lock serializes against the
        # decode micro-batcher; the O(tokens*frames) DTW must not hold it)
        pending = []  # (w, ids, text_pos, mat [N, S_text, T], dur_w)
        with self._lock:
            for w, ids in enumerate(windows):
                text_pos = [
                    k for k, t in enumerate(ids)
                    if t < ts_begin and t != self.eos_token_id
                ]
                if not text_pos:
                    continue
                chunk = pcm[w * self.n_samples : (w + 1) * self.n_samples]
                dur_w = len(chunk) / self.sampling_rate
                mel = log_mel_spectrogram(
                    chunk, self.mel_filters, n_fft=self.n_fft,
                    hop_length=self.hop_length, n_samples=self.n_samples,
                )[None, :, : self._frames]
                enc = self._encode_jit(self.params, jnp.asarray(mel))
                seq = prompt + list(ids) + [self.eos_token_id]
                bucket = 1
                while bucket < len(seq):
                    bucket *= 2
                bucket = min(bucket, self.max_target)
                toks = np.full((1, bucket), self.eos_token_id, np.int32)
                toks[0, : len(seq)] = seq[:bucket]
                n_frames = max(
                    1,
                    min(self._frames // 2, int(round(dur_w / frame_s))),
                )
                attn = np.asarray(
                    self._align_jit(
                        self.params, jnp.asarray(toks), enc,
                        jnp.asarray(n_frames, jnp.int32),
                    ),
                    np.float64,
                )                                       # [N, 1, S, T]
                n_frames = min(n_frames, attn.shape[-1])
                text_pos = [
                    k for k in text_pos if len(prompt) + k < bucket
                ]
                if not text_pos:
                    continue
                rows = [len(prompt) + k for k in text_pos]
                pending.append(
                    (w, ids, text_pos, attn[:, 0, rows, :n_frames], dur_w)
                )
        # phase 2 — host-only timing + word grouping
        words: List[dict] = []
        for w, ids, text_pos, mat, dur_w in pending:
            offset = w * float(self.chunk_length)
            std = mat.std(axis=-2, keepdims=True)
            mean = mat.mean(axis=-2, keepdims=True)
            mat = (mat - mean) / np.maximum(std, 1e-8)
            mat = _median_filter_time(mat)
            mat = mat.mean(axis=0)                      # [S_text, T]
            ti, fi = _dtw_path(-mat)
            # first frame of each token's run on the path = its onset
            jumps = np.diff(ti, prepend=-1) > 0
            starts = fi[jumps] * frame_s
            bounds = np.concatenate([starts, [dur_w]])
            span = {
                k: (float(bounds[i]), float(min(bounds[i + 1], dur_w)))
                for i, k in enumerate(text_pos)
            }
            cur_text, cur_start, cur_end = "", None, None
            unit: List[int] = []  # token positions of a pending decode unit

            def flush_word():
                nonlocal cur_text, cur_start, cur_end
                if cur_text.strip():
                    words.append({
                        "word": cur_text.strip(),
                        "start": round(cur_start, 2),
                        "end": round(cur_end, 2),
                    })
                cur_text, cur_start, cur_end = "", None, None

            def emit_unit(text: str, toks: List[int]):
                nonlocal cur_text, cur_start, cur_end
                st = span[toks[0]][0] + offset
                en = span[toks[-1]][1] + offset
                if text[:1].isspace() and cur_text.strip():
                    flush_word()
                if not text.strip():
                    if cur_text.strip():
                        flush_word()
                    return
                if cur_start is None:
                    cur_start = st
                cur_text += text
                cur_end = en

            def force_unit(toks: List[int]):
                # a unit cut off mid-codepoint (segment boundary / window
                # end): drop the incomplete bytes' replacement chars rather
                # than hand clients mojibake
                text = tokenizer.decode([ids[i] for i in toks])
                text = text.replace("�", "")
                if text:
                    emit_unit(text, toks)

            for k, t in enumerate(ids):
                if t >= ts_begin or t == self.eos_token_id:
                    # segment boundary: close the open unit and word
                    if unit:
                        force_unit(unit)
                        unit = []
                    flush_word()
                    continue
                if k not in span:
                    continue
                unit.append(k)
                text = tokenizer.decode([ids[i] for i in unit])
                if text.endswith("�"):
                    continue  # split codepoint: extend the unit
                if not text:
                    unit = []  # special token: contributes no text or break
                    continue
                emit_unit(text, unit)
                unit = []
            if unit:
                force_unit(unit)
            flush_word()
        return words

    def _encode_and_prime(self, pcms: List[np.ndarray], prompt: List[int]):
        """Shared admission preamble (caller must hold self._lock): mel
        batch -> encoder -> cache primed with all but the LAST prompt token.
        Returns (bucket, last_prompt_token [B], cache)."""
        from ..ops.audio import log_mel_spectrogram

        bucket = self._batch_bucket(len(pcms))
        mels = np.zeros((bucket, self.n_mels, self._frames), np.float32)
        for i, pcm in enumerate(pcms):
            mels[i] = log_mel_spectrogram(
                pcm, self.mel_filters, n_fft=self.n_fft,
                hop_length=self.hop_length, n_samples=self.n_samples,
            )[:, : self._frames]
        enc = self._encode_jit(self.params, jnp.asarray(mels))
        cache = self.bundle.init_cache(self.params, enc, self.max_target)
        next_tok = jnp.full((bucket,), prompt[0], jnp.int32)
        for tok in prompt[1:]:
            _, cache = self._prime_jit(self.params, next_tok, cache)
            next_tok = jnp.full((bucket,), tok, jnp.int32)
        return bucket, next_tok, cache

    def _transcribe_batch_ts(
        self, pcms: List[np.ndarray], prompt: List[int]
    ) -> List[List[int]]:
        """Timestamp-conditioned variant of _transcribe_batch: the final
        prompt token feeds the rules-constrained scan directly (its very
        first sample must already obey the initial-timestamp rule), and the
        outputs KEEP timestamp tokens for the segment parser."""
        n = len(pcms)
        with self._lock:
            bucket, token, cache = self._encode_and_prime(pcms, prompt)
            # sampled-sequence pairing state; True = len<2 default (see
            # _ts_body)
            pen_is_ts = jnp.ones((bucket,), bool)
            max_ts = jnp.full((bucket,), self.timestamp_begin - 1, jnp.int32)
            outs: List[List[int]] = [[] for _ in range(bucket)]
            done = [False] * bucket
            budget = min(self.max_new_tokens, self.max_target - len(prompt) - 1)
            step = 0
            while not all(done[:n]) and step < budget:
                toks, token, pen_is_ts, max_ts, cache = self._decode_chunk_ts_jit(
                    self.params, token, pen_is_ts, max_ts, cache,
                    jnp.asarray(step, jnp.int32),
                )
                chunk_np = np.asarray(toks)  # [steps, B]
                for s_i in range(chunk_np.shape[0]):
                    if step + s_i >= budget:
                        break
                    for i in range(n):
                        if done[i]:
                            continue
                        t = int(chunk_np[s_i, i])
                        if t == self.eos_token_id:
                            done[i] = True
                        else:
                            outs[i].append(t)
                step += chunk_np.shape[0]
        return outs[:n]

    # -- cross-request batching ------------------------------------------------

    def _batch_bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_batch)

    def _transcribe_batch(
        self, pcms: List[np.ndarray], prompt: List[int]
    ) -> List[List[int]]:
        """N ≤ max_batch single-window utterances, one shared prompt -> per-
        utterance token ids. One encode + one greedy loop over the batch;
        finished sequences keep stepping (masked host-side) until all hit
        eos or the budget."""
        n = len(pcms)
        with self._lock:
            bucket, next_tok, cache = self._encode_and_prime(pcms, prompt)
            first, cache = self._prime_jit(self.params, next_tok, cache)
            outs: List[List[int]] = [[] for _ in range(bucket)]
            done = [False] * bucket
            budget = min(self.max_new_tokens, self.max_target - len(prompt) - 1)
            token = first
            while not all(done[:n]):
                step = np.asarray(token)
                for i in range(n):
                    if not done[i]:
                        if int(step[i]) == self.eos_token_id or len(outs[i]) >= budget:
                            done[i] = True
                        else:
                            outs[i].append(int(step[i]))
                if all(done[:n]):
                    break
                chunk, cache = self._decode_chunk_batch_jit(
                    self.params, token, cache
                )                                               # [steps, B]
                chunk_np = np.asarray(chunk)
                for s_i in range(chunk_np.shape[0] - 1):
                    for i in range(n):
                        if done[i]:
                            continue
                        t = int(chunk_np[s_i, i])
                        if t == self.eos_token_id or len(outs[i]) >= budget:
                            done[i] = True
                        else:
                            outs[i].append(t)
                token = jnp.asarray(chunk_np[-1], jnp.int32)
        return outs[:n]

    async def transcribe_windows_async(
        self, pcm: np.ndarray, task: str = "transcribe", timestamps: bool = False
    ) -> List[List[int]]:
        """Batching front door: concurrent utterances with the same
        (task, timestamps) key share one encode/decode pass. Long audio
        submits each 30s window in order; returns PER-WINDOW token lists
        (the segment parser needs window boundaries for time offsets)."""
        self.prompt_ids(task, timestamps)  # surface config errors early
        loop = asyncio.get_running_loop()
        if self._pending is None or getattr(self, "_loop", None) is not loop:
            # an asyncio.Queue is bound to its creating loop: rebind when the
            # serving loop changes (tests, process-model restarts) or a put
            # into the dead loop's queue would hang forever
            self._pending = asyncio.Queue()
            self._loop = loop
            self._batch_task = None
        pcm = np.asarray(pcm, np.float32).reshape(-1)
        if len(pcm) == 0:
            return []
        key: Tuple[str, bool] = (task, bool(timestamps))
        windows: List[List[int]] = []
        for start in range(0, len(pcm), self.n_samples):
            fut = loop.create_future()
            await self._pending.put((pcm[start : start + self.n_samples], key, fut))
            self._ensure_batch_loop()
            windows.append(await fut)
        return windows

    async def transcribe_ids_async(
        self, pcm: np.ndarray, task: str = "transcribe"
    ) -> List[int]:
        """Flattened-token front door (plain text responses)."""
        windows = await self.transcribe_windows_async(pcm, task)
        return [t for w in windows for t in w]

    def _ensure_batch_loop(self) -> None:
        if self._batch_task is None or self._batch_task.done():
            self._batch_task = asyncio.get_running_loop().create_task(
                self._batch_loop()
            )

    async def _batch_loop(self) -> None:
        while True:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = await asyncio.wait_for(self._pending.get(), timeout=5.0)
                except asyncio.TimeoutError:
                    if self._pending.empty():
                        return  # idle; a new submit restarts the loop
                    continue
            batch = [first]
            deadline = (
                asyncio.get_running_loop().time() + self._batch_delay
            )
            while len(batch) < self.max_batch:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._pending.get(), timeout=remaining)
                except asyncio.TimeoutError:
                    break
                if item[1] != batch[0][1]:
                    # different task (prompt ids differ): carry it to the
                    # FRONT of the next round — re-queueing at the tail
                    # could starve it under sustained same-task load
                    self._carry = item
                    break
                batch.append(item)
            pcms = [b[0] for b in batch]
            futures = [b[2] for b in batch]
            task, with_ts = batch[0][1]
            try:
                prompt = self.prompt_ids(task, with_ts)
                fn = self._transcribe_batch_ts if with_ts else self._transcribe_batch
                outs = await asyncio.to_thread(fn, pcms, prompt)
                for fut, out in zip(futures, outs):
                    if not fut.done():
                        fut.set_result(out)
            except Exception as ex:
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(ex)
