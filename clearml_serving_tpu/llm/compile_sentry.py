"""Runtime compile sentry: attribute every serve-time XLA compilation
(docs/static_analysis.md TPU6xx — the dynamic net behind the static rules).

The compile-surface invariant says the set of (function, shape, dtype)
keys the serve loop presents to XLA is finite and fully compiled before
serving starts. The static analyzer proves the bucketizer discipline the
invariant rests on; this sentry proves the INVARIANT ITSELF at runtime:
armed with ``TPUSERVE_COMPILE_SENTRY=1`` (count) or ``=strict`` (raise),
it hooks JAX's compile path, splits compilations at the warmup fence
(``llm/warmup.py`` sets it after the sweep), attributes each post-fence
compilation to the in-flight launch (phase, dispatch seq, pipeline depth —
the engine tags its dispatch workers through a thread-local context), and
feeds ``engine_xla_compiles_total{phase}`` / ``engine_xla_compile_ms``
(statistics/metrics.py). In strict mode a post-fence compilation records a
violation naming the jitted function and its argument avals; the engine
raises :class:`CompileSentryError` for it at the next loop boundary (the
same check-at-the-boundary shape as the KV sanitizer).

Hook mechanics (jax 0.4.x): the primary listener is a ``logging.Handler``
on the two loggers ``jax_log_compiles`` writes through —
``jax._src.interpreters.pxla`` emits ``Compiling <fn> with global shapes
and types [<avals>]`` at compile start and ``jax._src.dispatch`` emits
``Finished XLA compilation of jit(<fn>) in <s> sec`` — captured at DEBUG
without flipping the (stderr-spamming) ``jax_log_compiles`` flag;
``propagate`` is disabled on those loggers while installed so armed runs
stay quiet, and restored on uninstall. ``install()`` PROBES the hook with
a guaranteed-fresh jit compile; if the log records never arrive (jax
moved its internals), the sentry falls back to a
``jax.monitoring`` duration listener on the backend-compile event —
counts and durations survive, function/aval attribution degrades to the
thread context, and ``stats()["mode"]`` says which net is live.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

ENV = "TPUSERVE_COMPILE_SENTRY"

_LOGGER_NAMES = ("jax._src.interpreters.pxla", "jax._src.dispatch")
_COMPILING_RE = re.compile(
    r"Compiling (\S+) with global shapes and types (\[.*\])\. "
    r"Argument mapping"
)
_FINISHED_RE = re.compile(
    r"Finished XLA compilation of (?:jit\()?([^)]+)\)? in ([0-9.eE+-]+) sec"
)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# scrape-time histogram edges (ms): compile stalls live in the 10 ms (tiny
# eager op) .. multi-second (big fused graph) range
_BUCKETS_MS = (10.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 5000.0)

# keep full per-compile attribution for the most recent N events; counters
# and the histogram are unbounded
_MAX_EVENTS = 256


def enabled() -> bool:
    return os.environ.get(ENV, "") not in ("", "0")


def strict_enabled() -> bool:
    return os.environ.get(ENV, "") == "strict"


class CompileSentryError(RuntimeError):
    """A post-warmup-fence XLA compilation under strict mode: names the
    jitted function, its argument avals, and the launch context it was
    attributed to."""


class _SentryHandler(logging.Handler):
    def __init__(self, sentry: "CompileSentry"):
        super().__init__(level=logging.DEBUG)
        self._sentry = sentry

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._sentry._on_log(record.getMessage())
        except Exception:  # never let bookkeeping break a compile
            pass


class CompileSentry:
    """Process-wide compile listener (one per process: the hook surface is
    global). Thread-safe; attribution context is thread-local so worker
    threads tag the compiles their own dispatches trigger."""

    def __init__(self, strict: bool = False):
        self.strict = bool(strict)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._fence = False
        self._installed = False
        self._probing = False
        self._mode = "off"            # "log" | "monitoring" | "off"
        self._log_seen = False
        self._handler: Optional[_SentryHandler] = None
        self._saved: Dict[str, tuple] = {}
        self.counts = {"warmup": 0, "serve": 0}
        self._hist_counts = [0] * (len(_BUCKETS_MS) + 1)
        self._hist_sum_ms = 0.0
        self.events: List[Dict[str, Any]] = []
        self.violations: List[Dict[str, Any]] = []

    # -- install / uninstall ----------------------------------------------

    def install(self) -> "CompileSentry":
        if self._installed:
            return self
        for name in _LOGGER_NAMES:
            logger = logging.getLogger(name)
            self._saved[name] = (logger.level, logger.propagate)
        self._handler = _SentryHandler(self)
        for name in _LOGGER_NAMES:
            logger = logging.getLogger(name)
            logger.addHandler(self._handler)
            logger.setLevel(logging.DEBUG)
            logger.propagate = False
        self._installed = True
        if self._probe():
            self._mode = "log"
        else:
            self._mode = "monitoring"
            self._install_monitoring()
        return self

    def _probe(self) -> bool:
        """Force a guaranteed-fresh jit compile and report whether the log
        listener saw it (a fresh lambda object is a fresh jit cache, so
        this compiles no matter what ran before). Probe compiles are not
        counted."""
        self._probing = True
        try:
            import jax
            import jax.numpy as jnp

            jax.jit(lambda x: x + jnp.float32(1))(jnp.zeros((3,), jnp.float32))
        except Exception:
            return False
        finally:
            self._probing = False
        return self._log_seen

    def _install_monitoring(self) -> None:
        try:
            import jax.monitoring as monitoring

            def _on_event(event: str, duration: float, **_kw) -> None:
                # jax.monitoring has no per-listener unregister: gate on
                # the installed flag so an uninstalled sentry goes inert
                # instead of mutating counters forever
                if (
                    event == _BACKEND_COMPILE_EVENT
                    and self._installed
                    and not self._log_seen
                ):
                    self._record(
                        fn="<unknown>", avals="<unavailable>",
                        duration_ms=duration * 1e3,
                    )

            monitoring.register_event_duration_secs_listener(_on_event)
        except Exception:
            pass

    def uninstall(self) -> None:
        if not self._installed:
            return
        for name in _LOGGER_NAMES:
            logger = logging.getLogger(name)
            if self._handler is not None:
                logger.removeHandler(self._handler)
            level, propagate = self._saved.get(name, (logging.NOTSET, True))
            logger.setLevel(level)
            logger.propagate = propagate
        self._installed = False
        self._mode = "off"

    # -- attribution context ----------------------------------------------

    @contextlib.contextmanager
    def context(self, **ctx):
        """Tag compiles triggered on THIS thread (the engine wraps its
        dispatch/prefill workers: phase, dispatch seq, pipeline depth)."""
        prev = getattr(self._tls, "ctx", None)
        self._tls.ctx = dict(prev or {}, **ctx)
        try:
            yield
        finally:
            self._tls.ctx = prev

    # -- event intake ------------------------------------------------------

    def _on_log(self, message: str) -> None:
        m = _COMPILING_RE.search(message)
        if m is not None:
            self._log_seen = True
            if self._probing:
                return
            self._record(fn=m.group(1), avals=m.group(2), duration_ms=None)
            return
        m = _FINISHED_RE.search(message)
        if m is not None:
            self._log_seen = True
            if self._probing:
                return
            try:
                duration_ms = float(m.group(2)) * 1e3
            except ValueError:
                return
            self._attach_duration(m.group(1), duration_ms)

    def _record(self, fn: str, avals: str,
                duration_ms: Optional[float]) -> None:
        ctx = dict(getattr(self._tls, "ctx", None) or {})
        with self._lock:
            phase = "serve" if self._fence else "warmup"
            self.counts[phase] += 1
            event = {
                "fn": fn,
                "avals": avals,
                "phase": phase,
                "context": ctx,
                "t": time.time(),
                "duration_ms": duration_ms,
            }
            self.events.append(event)
            del self.events[:-_MAX_EVENTS]
            if duration_ms is not None:
                self._observe_locked(duration_ms)
            # a `lazy=True` context marks a __compile_keys__ "lazy"-role
            # entry (one bounded compile per variant on first use, by
            # declared design): counted and attributed, never a violation
            if phase == "serve" and self.strict and not ctx.get("lazy"):
                self.violations.append(event)

    def _attach_duration(self, fn: str, duration_ms: float) -> None:
        with self._lock:
            for event in reversed(self.events):
                if event["duration_ms"] is None and event["fn"] == fn:
                    event["duration_ms"] = duration_ms
                    break
            else:
                return
            self._observe_locked(duration_ms)

    def _observe_locked(self, ms: float) -> None:
        for i, edge in enumerate(_BUCKETS_MS):
            if ms <= edge:
                self._hist_counts[i] += 1
                break
        else:
            self._hist_counts[len(_BUCKETS_MS)] += 1
        self._hist_sum_ms += ms

    # -- fence / check / stats --------------------------------------------

    def fence(self) -> None:
        """Everything compiled so far was warmup; everything after is a
        serve-time compile (and, in strict mode, a violation)."""
        with self._lock:
            self._fence = True

    def reset(self, strict: Optional[bool] = None) -> None:
        """Drop the fence and all accumulated state (tests; a new engine's
        warmup phase starts clean)."""
        with self._lock:
            self._fence = False
            self.counts = {"warmup": 0, "serve": 0}
            self.events = []
            self.violations = []
            self._hist_counts = [0] * (len(_BUCKETS_MS) + 1)
            self._hist_sum_ms = 0.0
            if strict is not None:
                self.strict = bool(strict)

    def check(self, where: str = "") -> None:
        """Raise the first pending strict violation (engine loop
        boundaries call this the way they call the KV sanitizer)."""
        with self._lock:
            if not (self.strict and self.violations):
                return
            v = self.violations[0]
        raise CompileSentryError(
            "XLA compiled {} with avals {} AFTER the warmup fence{}{} — "
            "a serve-time compile stall; extend llm/warmup.py's sweep or "
            "bucketize the shape source (docs/static_analysis.md TPU6xx)"
            .format(
                v["fn"], v["avals"],
                " at {}".format(where) if where else "",
                " (context: {})".format(v["context"]) if v["context"] else "",
            )
        )

    @property
    def post_fence_compiles(self) -> int:
        with self._lock:
            return self.counts["serve"]

    def hist_snapshot(self) -> Dict[str, Any]:
        """engine._MsHistogram-shaped snapshot (buckets/counts/sum_ms) so
        the metrics collector reuses its histogram plumbing."""
        with self._lock:
            return {
                "buckets": list(_BUCKETS_MS),
                "counts": list(self._hist_counts),
                "sum_ms": self._hist_sum_ms,
            }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "mode": self._mode,
                "strict": self.strict,
                "fenced": self._fence,
                "compiles": dict(self.counts),
                "violations": len(self.violations),
                "events": [dict(e) for e in self.events],
            }

    def stats_brief(self) -> Dict[str, Any]:
        """The lifecycle_stats()/health() "compile" block (and what the
        metrics collector reads): counters + histogram, no event list."""
        with self._lock:
            return {
                "mode": self._mode,
                "strict": self.strict,
                "fenced": self._fence,
                "warmup": self.counts["warmup"],
                "serve": self.counts["serve"],
                "violations": len(self.violations),
                "compile_ms": {
                    "buckets": list(_BUCKETS_MS),
                    "counts": list(self._hist_counts),
                    "sum_ms": self._hist_sum_ms,
                },
            }


# -- module singleton ---------------------------------------------------------

_sentry: Optional[CompileSentry] = None
_sentry_lock = threading.Lock()


def get() -> CompileSentry:
    """The process-wide sentry, installed on first use (strictness from
    the env at creation; tests flip ``.strict`` / call ``.reset()``)."""
    global _sentry
    with _sentry_lock:
        if _sentry is None:
            _sentry = CompileSentry(strict=strict_enabled()).install()
        return _sentry
