"""Encoder-model core: embeddings / pooling / classification / scoring.

Backs the OpenAI-compatible encoder routes (v1/embeddings, v1/pooling,
v1/classify, v1/score, v1/rerank) the reference instantiates task-gated vLLM
handlers for (reference preprocess_service.py:711-808, route handlers
:836-1095).  TPU-first design instead of a vLLM port:

- **Bucketed static shapes**: inputs pad to (batch-bucket, seq-bucket); one
  jitted executable per bucket pair, cached — no recompilation storms, and
  every shape XLA sees tiles cleanly onto the MXU.
- **fp32 pooling over bf16 encode**: masked mean (or CLS) pooling accumulates
  in float32; optional L2 normalization matches OpenAI embedding semantics.
- **Pair scoring**: cross-encoder when the bundle's classifier head has one
  label ([CLS] a [SEP] b [SEP] -> sigmoid(logit)), bi-encoder cosine
  similarity otherwise — same fallback policy vLLM applies to score requests
  against embedding models.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_DEFAULT_SEQ_BUCKETS = [32, 64, 128, 256, 512]
_DEFAULT_BATCH_BUCKETS = [1, 2, 4, 8, 16, 32]


class EncoderCore:
    """Bucketed-jit wrapper over an encoder bundle (models/bert.py)."""

    def __init__(
        self,
        bundle,
        params,
        *,
        pooling: str = "mean",
        normalize: bool = True,
        seq_buckets: Optional[List[int]] = None,
        batch_buckets: Optional[List[int]] = None,
        sep_token_id: Optional[int] = None,
        cls_token_id: Optional[int] = None,
    ):
        if not hasattr(bundle, "hidden"):
            raise ValueError(
                "encoder tasks need a model bundle with a .hidden() surface "
                "(e.g. arch 'bert'); arch {!r} is decoder-only".format(
                    bundle.config.get("arch", "?")
                )
            )
        self.bundle = bundle
        self.params = params
        if pooling not in ("mean", "cls"):
            raise ValueError("pooling must be 'mean' or 'cls'")
        self.pooling = pooling
        self.normalize = bool(normalize)
        self.max_seq_len = int(bundle.config.get("max_seq_len", 512))
        self.dim = int(bundle.config.get("dim"))
        self.num_labels = int(bundle.config.get("num_labels", 0))
        self.sep_token_id = sep_token_id
        self.cls_token_id = cls_token_id
        self._seq_buckets = sorted(
            b for b in (seq_buckets or _DEFAULT_SEQ_BUCKETS) if b <= self.max_seq_len
        )
        # the terminal bucket is always max_seq_len, so any admissible length
        # (<= max_seq_len) has a bucket to land in
        if not self._seq_buckets or self._seq_buckets[-1] != self.max_seq_len:
            self._seq_buckets.append(self.max_seq_len)
        self._batch_buckets = sorted(batch_buckets or _DEFAULT_BATCH_BUCKETS)
        self._jit_lock = threading.Lock()

        # locals, not self: jitted closures snapshot attribute values at
        # trace time (tpuserve-analyze TPU201)
        pooling, normalize = self.pooling, self.normalize

        def _embed(params, input_ids, attention_mask):
            x = bundle.hidden(params, input_ids, attention_mask)  # [B,S,D]
            x32 = x.astype(jnp.float32)
            if pooling == "cls":
                pooled = x32[:, 0]
            else:
                mask = attention_mask.astype(jnp.float32)[:, :, None]
                pooled = (x32 * mask).sum(axis=1) / jnp.maximum(
                    mask.sum(axis=1), 1.0
                )
            if normalize:
                pooled = pooled / jnp.maximum(
                    jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12
                )
            return pooled  # [B, D] fp32

        def _tokens(params, input_ids, attention_mask):
            return bundle.hidden(params, input_ids, attention_mask).astype(
                jnp.float32
            )

        def _classify(params, input_ids, attention_mask):
            x = bundle.hidden(params, input_ids, attention_mask)
            cls = x[:, 0].astype(jnp.float32)
            w = params["classifier"]["w"].astype(jnp.float32)
            b = params["classifier"]["b"].astype(jnp.float32)
            return cls @ w + b  # [B, num_labels]

        self._embed_jit = jax.jit(_embed)
        self._tokens_jit = jax.jit(_tokens)
        self._classify_jit = jax.jit(_classify)

    # -- batching helpers ----------------------------------------------------

    def _bucket(self, n: int, buckets: List[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    def _pad_batch(self, id_lists: List[List[int]]) -> Tuple[np.ndarray, np.ndarray]:
        """Pad token-id lists to (batch-bucket, seq-bucket) static shapes."""
        longest = max(len(ids) for ids in id_lists)
        if longest > self.max_seq_len:
            raise ValueError(
                "input length {} exceeds model max_seq_len {}".format(
                    longest, self.max_seq_len
                )
            )
        s = self._bucket(longest, self._seq_buckets)
        b = self._bucket(len(id_lists), self._batch_buckets)
        input_ids = np.zeros((b, s), np.int32)
        mask = np.zeros((b, s), np.int32)
        for i, ids in enumerate(id_lists):
            input_ids[i, : len(ids)] = ids
            mask[i, : len(ids)] = 1
        return input_ids, mask

    def _run_chunks(self, fn, id_lists: List[List[int]]):
        """Yield (chunk_id_lists, result) per batch-bucket chunk — chunks may
        land in different seq buckets, so results are NOT concatenated here.
        Per-request device work; safe from worker threads."""
        max_b = self._batch_buckets[-1]
        for start in range(0, len(id_lists), max_b):
            chunk = id_lists[start : start + max_b]
            input_ids, mask = self._pad_batch(chunk)
            with self._jit_lock:  # serialize tracing, not execution
                result = fn(self.params, jnp.asarray(input_ids), jnp.asarray(mask))
            yield chunk, np.asarray(result)[: len(chunk)]

    def _run_batched(self, fn, id_lists: List[List[int]]) -> np.ndarray:
        """Run `fn` over arbitrarily many inputs; valid only for outputs with
        no seq axis ([B, ...] invariant across seq buckets)."""
        return np.concatenate(
            [out for _, out in self._run_chunks(fn, id_lists)], axis=0
        )

    # -- public surface ------------------------------------------------------

    def embed(self, id_lists: List[List[int]]) -> np.ndarray:
        """[N] token-id lists -> [N, dim] fp32 (L2-normalized if configured)."""
        return self._run_batched(self._embed_jit, id_lists)

    def token_states(self, id_lists: List[List[int]]) -> List[np.ndarray]:
        """Per-input final hidden states (unpadded): list of [len_i, dim]."""
        out: List[np.ndarray] = []
        for chunk, states in self._run_chunks(self._tokens_jit, id_lists):
            out.extend(states[i, : len(ids)] for i, ids in enumerate(chunk))
        return out

    def classify(self, id_lists: List[List[int]]) -> np.ndarray:
        """[N] inputs -> [N, num_labels] fp32 logits (CLS head)."""
        if self.num_labels <= 0:
            raise ValueError("model bundle has no classifier head")
        return self._run_batched(self._classify_jit, id_lists)

    @property
    def is_cross_encoder(self) -> bool:
        return self.num_labels == 1

    def _join_pair(self, a: List[int], b: List[int]) -> List[int]:
        """BERT text-pair assembly: [CLS] a [SEP] b [SEP]. `a`/`b` must be
        encoded WITHOUT special tokens; truncation keeps the final SEP."""
        cls = [self.cls_token_id] if self.cls_token_id is not None else []
        sep = [self.sep_token_id] if self.sep_token_id is not None else []
        ids = cls + list(a) + sep + list(b) + sep
        if len(ids) > self.max_seq_len:
            ids = ids[: self.max_seq_len]
            if sep:
                ids[-1] = sep[0]
        return ids

    def score_pairs(
        self,
        pairs: List[Tuple[List[int], List[int]]],
        *,
        with_specials: Optional[bool] = None,
    ) -> List[float]:
        """Relevance score per (text_1, text_2) token-id pair.

        Cross-encoder bundles (num_labels == 1): joint [CLS] a [SEP] b [SEP]
        encode -> sigmoid(logit); pairs must then be encoded WITHOUT special
        tokens. Otherwise: bi-encoder cosine similarity of the two pooled
        embeddings (pairs encoded with specials, as for embed())."""
        if self.is_cross_encoder:
            joined = [self._join_pair(a, b) for a, b in pairs]
            logits = self.classify(joined)[:, 0]
            return [float(s) for s in 1.0 / (1.0 + np.exp(-logits))]
        flat: List[List[int]] = []
        for a, b in pairs:
            flat.append(list(a))
            flat.append(list(b))
        vecs = self.embed(flat)
        return [
            self._cosine(vecs[2 * i], vecs[2 * i + 1]) for i in range(len(pairs))
        ]

    @staticmethod
    def _cosine(a: np.ndarray, b: np.ndarray) -> float:
        denom = float(np.linalg.norm(a) * np.linalg.norm(b)) or 1e-12
        return float(np.dot(a, b) / denom)

    def rerank(self, query_ids: List[int], doc_id_lists: List[List[int]]) -> List[float]:
        """Score each document against the query. Bi-encoder path embeds the
        query ONCE and dots it against the document embeddings (score_pairs
        would redundantly re-encode the query per document); cross-encoder
        path joint-encodes each (query, doc) pair."""
        if self.is_cross_encoder:
            return self.score_pairs([(query_ids, d) for d in doc_id_lists])
        vecs = self.embed([list(query_ids)] + [list(d) for d in doc_id_lists])
        q = vecs[0]
        return [self._cosine(q, v) for v in vecs[1:]]
