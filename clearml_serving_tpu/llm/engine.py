"""Continuous-batching LLM engine core (JetStream-style; replaces vLLM).

Design (TPU-first, SURVEY.md §7 step 6):

- **Slot-based decode batch**: a fixed ``max_batch`` of cache slots; the decode
  step is ONE jitted function over the full slot batch (static shapes — no
  recompilation as requests come and go). Inactive slots compute garbage that
  is never read; occupancy, not shapes, varies.
- **Bucketed prefill**: prompts pad to the next seq-len bucket; one compiled
  prefill per bucket. Prefill emits KV shaped [L,1,bucket,H,D] which a jitted
  donate-insert writes into the slot's region of the big cache — the cache
  lives in HBM across the whole request lifetime, is donated through every
  step, and is never copied host-side.
- **Continuous batching loop**: an asyncio task interleaves admissions
  (prefill) with decode steps; each step's sampled tokens fan out to
  per-request queues (SSE streaming sits directly on top).
- **Multi-step decode**: ``decode_steps`` tokens are generated per dispatch
  with an on-device ``lax.scan`` (sampling included). Host dispatch overhead
  is amortized over the whole chunk — measured ~90 ms per dispatch through a
  tunneled TPU vs 8 ms of device time per step, so chunking is the difference
  between ~160 tok/s and ~1500+ tok/s. Finished sequences inside a chunk are
  truncated host-side; their slots free at the chunk boundary.
- **Sampling as data**: per-slot temperature/top-k/top-p arrays — one compiled
  sampler for any mix of requests.
- Optional ``jax.sharding.Mesh``: params/cache get TP/DP shardings from
  parallel/sharding.py; GSPMD handles the collectives; the loop is unchanged.

The reference's equivalent surface is vLLM's AsyncLLM behind
VllmPreprocessRequest (reference preprocess_service.py:619-1348).
"""

from __future__ import annotations

import asyncio
import contextlib
import heapq
import itertools
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Deque, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import (
    compile_sentry,
    faults,
    kv_sanitizer,
    lifecycle_ledger,
    sharding_sentry,
)
from .shapes import decode_steps_bucket
from ..errors import (
    DeadlineExceededError,
    EngineOverloadedError,
    EngineStepError,
    EngineStuckError,
    EngineUnavailableError,
    is_hbm_oom,
)
from .sampling import (
    SamplingExtras,
    SamplingParams,
    greedy_tree_walk,
    penalize_logits,
    speculative_sample_chain,
    speculative_sample_tree,
    sample_tokens,
)

_DEFAULT_PREFILL_BUCKETS = [32, 64, 128, 256, 512, 1024, 2048]

# per-engine tag for the process-wide sharding sentry's spec table:
# co-hosted replica engines must not alias each other's array paths
_ENGINE_IDS = itertools.count()


@dataclass
class GenRequest:
    prompt_ids: List[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_token_ids: Optional[List[int]] = None
    # OpenAI/vLLM sampling-parameter parity (applied on-device as batch data)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    seed: Optional[int] = None
    logit_bias: Optional[Dict[int, float]] = None
    # number of top-alternative logprobs to record per emitted token
    # (None = no logprob tracking; 0 = chosen token's logprob only)
    logprobs: Optional[int] = None
    # named LoRA adapter to apply (None = base model); resolved against the
    # engine's adapter registry at validate/admission time
    adapter: Optional[str] = None
    # vLLM min_tokens: suppress EOS until this many tokens were generated
    min_tokens: int = 0
    # grammar constraint (llm/guided.py GuidedSpec); compiled at admission,
    # enforced on device inside the decode scan
    guided: Optional[Any] = None
    # SLO class (docs/slo_scheduling.md): "interactive" | "batch" |
    # "best_effort". Strict class order across the per-class pending queues,
    # EDF within a class; under overload best_effort sheds first, then
    # batch, and batch-lane slots are preemptible when interactive work is
    # queued. Endpoint-level default via aux engine.default_priority.
    priority: str = "interactive"
    # engine-internal: combined-table DFA state after the first token
    _gstate0: int = -1
    _guided_key: Optional[str] = None
    # filled by the engine:
    out_queue: "asyncio.Queue" = field(default_factory=asyncio.Queue)
    produced: int = 0
    prompt_len: int = 0
    submitted_at: float = field(default_factory=time.time)
    first_token_at: Optional[float] = None
    error: Optional[BaseException] = None
    # per emitted token (when logprobs is not None): {"id", "logprob",
    # "top_ids", "top_logprobs"}; entry i is appended BEFORE token i is
    # queued, so a consumer that just received token i may read entry i
    logprob_entries: List[dict] = field(default_factory=list)
    # set by the API layer when a stop STRING matched in the decoded text
    # (stop token ids are handled by the engine; strings need detokenization)
    stopped_on_string: bool = False
    # set by the consumer (e.g. an SSE wrapper on client disconnect); the
    # engine frees the slot and KV pages at the next emission point instead
    # of decoding the request to max_new_tokens for nobody
    cancelled: bool = False
    # engine-internal (paged prefix cache): pinned shared-page hit carried
    # from the admission worker to the loop-thread commit; every failure
    # path between the two must release it (engine._release_prefix_hit)
    _prefix_hit: Optional[Any] = None
    # per-request lifecycle budgets in seconds (None = engine defaults):
    # queue_timeout bounds the wait in _pending, ttft_timeout the time to
    # the first emitted token, total_timeout the whole request
    queue_timeout: Optional[float] = None
    ttft_timeout: Optional[float] = None
    total_timeout: Optional[float] = None
    # engine-internal monotonic deadlines resolved once at submission
    _queue_deadline: Optional[float] = None
    _ttft_deadline: Optional[float] = None
    _deadline: Optional[float] = None
    # engine-internal (preemptible batch lane): tokens emitted since the
    # last (re)admission — a preempted request's full token history is
    # prompt_ids + _gen_ids, which becomes the resume prompt so the radix
    # prefix cache replays the generated-so-far KV with near-zero prefill
    _gen_ids: List[int] = field(default_factory=list)
    # times this request was preempted (bounded by the engine's preemption
    # budget: an exhausted budget makes the request immune, so batch work
    # still finishes under sustained interactive pressure)
    _preempt_count: int = 0
    # engine-internal (paged prefix cache): eviction pin on the preempted
    # history's radix run, held from preemption until the resume admission's
    # lookup (prefix_cache.pin_run) — without it, pool pressure while the
    # request waits in the queue can evict exactly the KV the preemption
    # promised to replay. Every queue-exit path must release it
    # (engine._release_resume_pin)
    _resume_pin: Optional[Any] = None
    # disaggregated prefill/decode (docs/disaggregation.md): the replica
    # group sets _ship_to on the PREFILL leg's clone (destination decode
    # replica name — the engine exports the committed prefix pages into a
    # KV-transport shipment addressed there) and _shipped on the ORIGINAL
    # request once the leg ran (the decode replica's admission then books
    # the shipped prefix as a ship hit or a recompute)
    _ship_to: Optional[str] = None
    _shipped: bool = False

    def cancel(self) -> None:
        self.cancelled = True


logger = logging.getLogger(__name__)

_FINISHED = object()


class _ShipShim:
    """Carrier for fault matching on the ``engine.kv.receive`` seam: the
    receive path has no GenRequest in hand (the group calls it before the
    stream's admission), so the shim carries the prompt ids for
    ``match_token`` selection (the router's ``_ReplicaShim`` pattern)."""

    def __init__(self, prompt_ids):
        self.prompt_ids = list(prompt_ids)

# stop tokens honored by min_tokens suppression per request (requests with
# more stop ids than this keep finishing on all of them — only the floor's
# suppression is bounded)
_STOP_SLOTS = 8

# decode pipeline depth: in-flight decode-chunk dispatches the loop keeps
# enqueued ahead of retirement. 1 = the historical serial
# dispatch->sync->emit loop; 2 (default) overlaps chunk N's host readback +
# emission with chunk N+1's device compute (docs/pipelined_decode.md)
_DEFAULT_PIPELINE_DEPTH = 2

# host-tier auto-sizing clamps (aux engine.prefix_cache_host_mb: "auto",
# docs/kv_tiering.md): half of /proc/meminfo MemAvailable, bounded so a
# tiny CI box still gets a usable tier and a 1 TiB host does not
# preallocate absurd slabs
_AUTO_HOST_TIER_MIN_BYTES = 64 << 20
_AUTO_HOST_TIER_MAX_BYTES = 16 << 30


def _env_pipeline_depth() -> int:
    raw = os.environ.get("TPUSERVE_PIPELINE_DEPTH", "")
    try:
        return max(1, int(raw)) if raw else _DEFAULT_PIPELINE_DEPTH
    except ValueError:
        return _DEFAULT_PIPELINE_DEPTH


class _MsHistogram:
    """Host-side fixed-bucket histogram for scrape-time export
    (statistics.metrics turns snapshots into Prometheus histograms). One
    writer at a time (the dispatch worker / retire stage); snapshot()
    copies under the GIL so scrapes never see torn lists. The default
    bucket set is millisecond-scaled; callers may pass their own (the
    ragged scheduler's budget-utilization ratios use a [0, 1] grid)."""

    BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0)

    def __init__(self, buckets=None):
        self.buckets = tuple(buckets) if buckets is not None else self.BUCKETS
        self.counts = [0] * (len(self.buckets) + 1)
        self.total_ms = 0.0
        self.n = 0

    def observe(self, ms: float) -> None:
        for i, edge in enumerate(self.buckets):
            if ms <= edge:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total_ms += float(ms)
        self.n += 1

    def snapshot(self) -> dict:
        return {
            "buckets": list(self.buckets),
            "counts": list(self.counts),
            "sum_ms": self.total_ms,
            "count": self.n,
        }


@dataclass
class _InFlightChunk:
    """One dispatched-but-unretired decode chunk. ``chunk``/``gstate``/``lp``
    are DEVICE arrays (possibly still computing); retire syncs them to host.
    ``active_mask`` is the host snapshot the dispatch was built from — the
    retire stage emits exactly those slots and nothing newer."""

    seq: int
    epoch: int
    active_mask: "np.ndarray"
    chunk: Any
    gstate: Any = None
    lp: Any = None
    want_lp: bool = False
    dispatched_at: float = 0.0
    # paged backend: slots dropped from this chunk because the pool could
    # not hold their page extension (failed by the loop thread on landing)
    exhausted: List[int] = field(default_factory=list)


# ragged scheduler (docs/ragged_attention.md): stage-3 brownout shrinks the
# per-step admission share to roughly one minimal chunk instead of the
# legacy gate's one-segment-per-chunk budget
_RAGGED_BROWNOUT_CHUNK = 16


@dataclass(eq=False)  # identity semantics: jobs live in (and leave) lists
class _RaggedJob:
    """One admission riding the ragged scheduler (docs/ragged_attention.md):
    the request's prompt prefills in budget-bounded chunk rows of the
    loop's ragged launches, writing straight into its reserved slot's KV
    (no mini cache, no separate prefill dispatch). ``pos`` is the next
    unprefilled prompt index (a radix prefix hit starts it past the shared
    run); the slot stays reserved via ``engine._admitting`` until the final
    chunk's commit or a failure path frees it."""

    request: GenRequest
    slot: int
    pos: int = 0
    started_at: float = field(default_factory=time.monotonic)


class _PrefillGate:
    """Decode-first chunked-prefill scheduling policy.

    Admission prefills run in worker threads concurrently with decode
    chunks, but every dispatch lands in the SAME device queue — an unpaced
    long-prompt segment train would enqueue ahead of the next decode chunk
    and blow up the decoding requests' inter-token latency. The gate bounds
    the interleave: while decode is active, at most ``segments_per_chunk``
    prefill dispatches may enter the queue per decode chunk (the decode loop
    ``deposit()``s that many permits after each chunk; admission threads
    ``acquire()`` one per prefill dispatch).

    ``stall_timeout`` is the prefill-starvation bound in the other
    direction: if decode stops depositing (loop stalled on commits or
    emission), a waiting prefill proceeds anyway after this many seconds —
    admission can be slowed by decode, never parked indefinitely. The
    default must comfortably EXCEED one decode-chunk duration (~90 ms
    dispatch overhead alone on a tunneled TPU, plus device time), or
    permit-exhausted segments would time out past the gate mid-chunk and
    silently void the segments_per_chunk bound; it only ever bites when the
    loop is wedged, so seconds-scale is correct.
    """

    def __init__(self, segments_per_chunk: int = 2, stall_timeout: float = 2.0):
        self._spc_cfg = max(1, int(segments_per_chunk))
        self._spc = self._spc_cfg
        self._stall_timeout = float(stall_timeout)
        self._cond = threading.Condition()
        self._permits = self._spc
        self._active = False

    def set_budget(self, segments_per_chunk: Optional[int]) -> None:
        """Brownout override of the per-chunk prefill budget (stage >= 3
        shrinks it to 1 so decode slots drain ahead of new admissions);
        ``None`` restores the configured value."""
        with self._cond:
            self._spc = (
                max(1, int(segments_per_chunk))
                if segments_per_chunk
                else self._spc_cfg
            )
            self._permits = min(self._permits, self._spc)
            self._cond.notify_all()

    def set_active(self, active: bool) -> None:
        """Loop thread: decode has (in)active slots; inactive opens the gate."""
        with self._cond:
            self._active = bool(active)
            if not self._active:
                self._permits = self._spc
                self._cond.notify_all()

    def deposit(self) -> None:
        """Loop thread: a decode chunk completed — refresh the permit budget.

        Permits are SET, not accumulated: idle decode periods must not bank
        an unbounded burst allowance for a later admission."""
        with self._cond:
            self._permits = self._spc
            self._cond.notify_all()

    def acquire(self, bypass: bool = False) -> None:
        """Admission thread: blocks (boundedly) before one prefill dispatch.

        ``bypass`` (SINGLE-dispatch interactive admissions,
        docs/slo_scheduling.md): skip the pacing — the gate exists to keep
        multi-segment prefill trains from queueing ahead of decode chunks;
        a one-dispatch admission cannot train, and parking that
        first-token-critical enqueue behind a batch resume's permit is
        priority inversion at the device queue. Multi-segment interactive
        prefills stay paced: their segment train hurts co-resident
        inter-token latency exactly like a batch one."""
        if bypass:
            return
        with self._cond:
            if not self._active:
                return
            if self._permits <= 0:
                self._cond.wait_for(
                    lambda: self._permits > 0 or not self._active,
                    timeout=self._stall_timeout,
                )
            if self._permits > 0:
                self._permits -= 1
            # timed out with no permit: proceed — starvation bound


PRIORITY_CLASSES = ("interactive", "batch", "best_effort")
_CLASS_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}


class _ClassedPendingQueue:
    """Per-class pending queues replacing the single `_pending` FIFO
    (docs/slo_scheduling.md): strict class order across classes
    (interactive > batch > best_effort), earliest-deadline-first within a
    class (requests without a deadline order FIFO after every deadlined
    one), and a starvation floor — a lower class that waited through
    ``floor`` consecutive higher-class pops takes the next pop, so batch
    work keeps trickling through sustained interactive load.

    Production callers all run on the engine's event-loop thread, but the
    structure is internally locked (tests and the watchdog's deadline sweep
    may observe it from elsewhere)."""

    __guarded_by__ = {"_lock": ("_heaps", "_starve")}

    def __init__(self, starvation_floor: int = 8):
        self._heaps: Dict[str, list] = {c: [] for c in PRIORITY_CLASSES}
        self._seq = itertools.count()
        self._floor = max(1, int(starvation_floor))
        # consecutive higher-class pops each class sat through while
        # non-empty; reset when the class pops
        self._starve = {c: 0 for c in PRIORITY_CLASSES}
        self._lock = threading.Lock()

    @staticmethod
    def _key(request: "GenRequest") -> float:
        d = request._deadline
        return d if d is not None else float("inf")

    def put_nowait(self, request: "GenRequest") -> None:
        cls = getattr(request, "priority", None) or "interactive"
        if cls not in self._heaps:
            cls = "interactive"
        with self._lock:
            heapq.heappush(
                self._heaps[cls], (self._key(request), next(self._seq), request)
            )

    def _pop_class(self, cls: str) -> "GenRequest":  # tpuserve: ignore[TPU301] lock held by caller
        _, _, request = heapq.heappop(self._heaps[cls])
        self._starve[cls] = 0
        return request

    def get_nowait(self) -> "GenRequest":
        with self._lock:
            # starvation floor first: a class that waited through `floor`
            # higher-class pops gets this one (lowest starved class wins —
            # it has, by construction, waited the longest)
            for cls in reversed(PRIORITY_CLASSES):
                if self._heaps[cls] and self._starve[cls] >= self._floor:
                    return self._pop_class(cls)
            for i, cls in enumerate(PRIORITY_CLASSES):
                if self._heaps[cls]:
                    for lower in PRIORITY_CLASSES[i + 1:]:
                        if self._heaps[lower]:
                            self._starve[lower] += 1
                    return self._pop_class(cls)
        raise asyncio.QueueEmpty

    def qsize(self) -> int:
        with self._lock:
            return sum(len(h) for h in self._heaps.values())

    def empty(self) -> bool:
        return self.qsize() == 0

    def depths(self) -> Dict[str, int]:
        """Per-class queue depths (lifecycle_stats / Prometheus)."""
        with self._lock:
            return {c: len(h) for c, h in self._heaps.items()}

    def waiting(self, cls: str) -> int:
        """LIVE queued requests of ``cls`` — cancelled/failed entries stay
        heap-resident until a pop discards them, and preempting a batch
        slot for a dead interactive request would burn its preemption
        budget for nobody (the admission pop just drops the corpse)."""
        with self._lock:
            return sum(
                1
                for e in self._heaps.get(cls, ())
                if not e[2].cancelled and e[2].error is None
            )

    def requests(self) -> List["GenRequest"]:
        """Snapshot of every queued request (deadline sweeps)."""
        with self._lock:
            return [e[2] for h in self._heaps.values() for e in h]

    def shed_lowest(self, above: str) -> Optional["GenRequest"]:
        """Remove and return the lowest-class, latest-deadline queued
        request whose class is STRICTLY lower priority than ``above``
        (None when there is none): the class-aware shed path evicts it to
        make room for a higher-class arrival — best-effort sheds first,
        then batch."""
        above_rank = _CLASS_RANK.get(above, 0)
        with self._lock:
            for cls in reversed(PRIORITY_CLASSES):
                if _CLASS_RANK[cls] <= above_rank:
                    return None
                heap = self._heaps[cls]
                # mid-stream requests (preempted resumes: produced > 0,
                # consumer attached) are immune — shedding one turns an
                # in-progress 200/SSE response into a mid-stream 429 and
                # throws away its committed KV; with only resumes queued
                # the ARRIVAL sheds at the door instead
                live = [
                    e for e in heap
                    if not e[2].cancelled and e[2].error is None
                    and e[2].produced == 0
                ]
                if not live:
                    continue
                victim = max(live, key=lambda e: (e[0], e[1]))
                heap.remove(victim)
                heapq.heapify(heap)
                return victim[2]
        return None

    def pop_all(self) -> List["GenRequest"]:
        """Drain every queued request (engine stop)."""
        with self._lock:
            out = [e[2] for h in self._heaps.values() for e in h]
            for h in self._heaps.values():
                h.clear()
            return out


class _BrownoutController:
    """Staged overload degradation with hysteresis (docs/slo_scheduling.md).

    A pressure score in [0, ~2] (max over queue-depth, pool-headroom,
    deadline-hit and watchdog signals) drives the stage:

    - stage 0: normal operation;
    - stage 1: speculative decoding disabled (verify slack pressure off the
      pool, fewer wasted positions per dispatch);
    - stage 2: + batch-class ``max_new_tokens`` capped (long batch decodes
      release their slots early);
    - stage 3: + prefill admission budget shrunk to one segment per decode
      chunk and best-effort traffic shed at the door.

    Raising is immediate (the overload response must be fast). Lowering
    requires the score to fall below the stage's DOWN threshold — strictly
    below its UP threshold, the hysteresis band — AND a minimum dwell since
    the last change, so a score oscillating across a threshold cannot flap
    the stage."""

    UP = (0.70, 0.85, 0.95)
    DOWN = (0.50, 0.65, 0.80)

    def __init__(self, dwell: float = 2.0):
        self.dwell = float(dwell)
        self.stage = 0
        self.score = 0.0
        self.signals: Dict[str, float] = {}
        self.transitions = 0
        self._changed_at = float("-inf")

    def update(self, score: float, signals: Optional[dict] = None,
               now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        self.score = float(score)
        if signals is not None:
            self.signals = dict(signals)
        target_up = 0
        for i, threshold in enumerate(self.UP):
            if self.score >= threshold:
                target_up = i + 1
        if target_up > self.stage:
            self.stage = target_up
            self.transitions += 1
            self._changed_at = now
        elif (
            self.stage > 0
            and self.score < self.DOWN[self.stage - 1]
            and now - self._changed_at >= self.dwell
        ):
            self.stage -= 1
            self.transitions += 1
            self._changed_at = now
        return self.stage


class LLMEngineCore:
    """Slot-based continuous batching over a dense per-slot KV cache."""

    # thread-affinity registry (tpuserve-analyze TPU501,
    # docs/static_analysis.md): this state has NO lock on purpose — exactly
    # one thread owns it. "loop" = the asyncio event-loop thread (handlers,
    # the decode loop, the watchdog task); "worker" = asyncio.to_thread
    # dispatch/readback/prefill workers. The pipeline queue, quarantine
    # map, slot table, and host token/DFA mirrors are loop-owned (workers
    # receive snapshots via the prep dict and hand results back through the
    # retire stage); the device-resident chains are worker-owned (the
    # dispatch worker is the only stage running device programs; the loop
    # resets them only at protocol-serialized points, annotated at the
    # definition sites).
    __affine_to__ = {
        "loop": (
            "_inflight", "_quarantine", "_dispatching", "_slot_req",
            "_admitting", "_next_token", "_gstate", "_slot_overrides",
            "_prefill_jobs", "_tier_counters",
            # multi-step / spec-as-row chain observability
            # (docs/ragged_attention.md): per-launch window and acceptance
            # state is planned and retired on the loop thread only; the
            # dispatch worker reads plan snapshots, never these attrs
            "_step_rows", "_hist_launch_tokens", "_hist_spec_accept",
            # draft-tree verify rows (docs/spec_decode_trees.md): the
            # proposer's hit counters and the accept-depth histogram are
            # planned/retired on the loop thread; draft-ahead shipping
            # watermarks advance at retire chunk boundaries
            "_spec_proposer", "_hist_spec_tree_depth",
            "_kv_draft_ahead",
        ),
        "worker": ("_next_token_dev", "_gstate_dev"),
    }

    # compile-surface registry (tpuserve-analyze TPU603,
    # docs/static_analysis.md): every jit entry this class creates must be
    # declared here, and every "serve"-role entry must appear in the warmup
    # shape registry (llm/warmup.py WARMUP_COVERED) so its key space
    # compiles before the serve fence — a serve-time XLA compile is a
    # 100-1000 ms loop-thread stall that masquerades as scheduling tail.
    # "lazy" = request-path entries compiled on first use BY DESIGN (rare
    # features whose one-per-variant compile is bounded and attributed by
    # the compile sentry, not a per-request key).
    __compile_keys__ = {
        "serve": (
            "_prefill_jit", "_prefill_ring_jit", "_prefill_pipeline_jit",
            "_prefill_chunk_first_jit", "_prefill_chunk_jit",
            "_gather_pages_jit", "_assemble_prefix_jit", "_insert_jit",
            "_merge_rows_jit", "_decode_chunk_jit",
            "_decode_paged_chunk_jit", "_sample_jit", "_first_lp_jit",
            "_set_sampling_row_jit", "_spec_chunk_jit", "_spec_paged_jit",
            "_ragged_paged_jit", "_ragged_dense_jit", "_gather_finish_jit",
        ),
        # prompt scoring runs only for completions echo+logprobs requests:
        # one compile per prefill bucket on first use, sentry-attributed
        "lazy": ("_score_prompt_jit",),
    }

    # sharding registry (tpuserve-analyze TPU802, docs/static_analysis.md):
    # the sharding builder covering each donated/sharded operand family the
    # serve-path jit entries above consume. Every builder named here must be
    # in parallel/sharding.py's __sharding_builders__ closed world; the
    # runtime sharding sentry (llm/sharding_sentry.py) audits the live
    # arrays against what these builders declared at init.
    __shardings__ = {
        "params": "parallel.sharding.llama_param_sharding",
        "params_quantized": "parallel.sharding.llama_quantized_param_sharding",
        "kv_cache": "parallel.sharding.llama_cache_sharding",
        "tokens": "parallel.sharding.batch_sharding",
        "host_state": "parallel.sharding.replicated",
    }

    # ownership-discipline registry (tpuserve-analyze TPU7xx,
    # docs/static_analysis.md): the engine's two cross-function protocols.
    # Quarantined slots release at the barrier retire (or the pipeline-
    # discard paths); grammar refs release at slot teardown / admission
    # failure. Both pair across functions by design ("static": False), so
    # the runtime ownership ledger audits them at the drain boundary.
    __acquires__ = {
        "_quarantine_slot": {"resource": "slot.quarantine",
                             "releases": ("_release_quarantine",),
                             "drops": ("_discard_pipeline",),
                             "static": False},
        "_ensure_grammar": {"resource": "guided.ref",
                            "releases": ("_deref_guided_key",
                                         "_deref_guided_request",
                                         "_release_guided"),
                            "static": False},
    }

    def __init__(
        self,
        bundle,
        params,
        *,
        max_batch: int = 8,
        max_seq_len: int = 2048,
        prefill_buckets: Optional[List[int]] = None,
        mesh=None,
        eos_token_id: Optional[int] = None,
        rng_seed: int = 0,
        decode_steps: int = 4,
        quantize: Optional[str] = None,
        # canonical name for the weight-quantization knob (docs/w4a16.md);
        # ``quantize`` stays as the historical alias
        weight_quant: Optional[str] = None,
        cache_mode: str = "dense",
        page_size: int = 16,
        num_pages: Optional[int] = None,
        long_prefill_threshold: Optional[int] = None,
        long_bucket_step: Optional[int] = None,
        chunked_prefill_size: Optional[int] = None,
        prefill_segments_per_decode: Optional[int] = 2,
        prefill_stall_timeout: Optional[float] = None,
        speculation: Optional[str] = None,
        spec_k: int = 4,
        spec_ngram: int = 2,
        spec_sampling: bool = True,
        # draft TREES on the verify rows (docs/spec_decode_trees.md):
        # the ragged scheduler's q=k+1 verify row becomes a fixed-budget
        # draft tree from the n-gram FOREST proposer — same verify budget,
        # higher acceptance. Paged cache only (the dense chunk layers have
        # no per-token tree mask); spec_branch caps root branching.
        spec_tree: bool = False,
        spec_branch: int = 2,
        pipeline_chunk: int = 512,
        lora_adapters: Optional[Dict[str, Any]] = None,
        prefix_cache: Optional[int] = None,
        prefix_block: int = 64,
        prefix_cache_bytes: Optional[int] = None,
        prefix_cache_pages: Optional[int] = None,
        # host-RAM KV tier (docs/kv_tiering.md, paged backend only): number
        # of preallocated host pages behind the prefix cache — device-budget
        # eviction demotes cached runs there instead of dropping them, and a
        # hit on a demoted run re-onlines via async DMA overlapped with the
        # tail prefill. None/0 disables (legacy drop-on-evict).
        prefix_cache_host_pages: Optional[int] = None,
        prefix_cache_host_bytes: Optional[int] = None,
        logprobs_k: int = 20,  # OpenAI's top_logprobs ceiling
        tokenizer=None,  # required for guided decoding (token byte tables)
        # -- request-lifecycle hardening (None disables each knob; the
        # serving front installs production defaults — unit tests keep the
        # historical unbounded behavior unless they opt in) ---------------
        max_pending: Optional[int] = None,   # admission bound on _pending
        queue_timeout: Optional[float] = None,  # default queue-wait budget
        ttft_timeout: Optional[float] = None,   # default first-token budget
        total_timeout: Optional[float] = None,  # default whole-request budget
        watchdog_interval: Optional[float] = None,  # stall detector period
        # decode pipeline depth (None -> TPUSERVE_PIPELINE_DEPTH env, default
        # 2); 1 restores the serial dispatch->sync->emit loop
        pipeline_depth: Optional[int] = None,
        # -- ragged scheduling (docs/ragged_attention.md) ------------------
        # "ragged": admissions ride the decode loop as budget-bounded
        # prefill-chunk rows of ONE mixed launch per iteration (token-budget
        # admission replaces the prefill gate); "two_dispatch" (default):
        # the historical separate prefill/decode dispatches. None defers to
        # TPUSERVE_SCHEDULER.
        scheduler: Optional[str] = None,
        # ragged mode: max tokens (decode rows + prefill-chunk rows) per
        # launch; must exceed max_batch so admissions always make progress.
        # None -> TPUSERVE_STEP_TOKEN_BUDGET, default max(128, 4*max_batch)
        step_token_budget: Optional[int] = None,
        # ragged mode: decode rows carry up to this many chained token
        # positions per mixed launch (multi-step decode rows,
        # docs/ragged_attention.md) — the launch advances each decode slot
        # by up to this many tokens, amortizing the per-launch dispatch
        # bubble and weight read the way the pipelined chunk does. The
        # per-launch window buckets to a power of two
        # (llm/shapes.decode_steps_bucket) and shrinks with the token
        # budget. None inherits ``decode_steps``; 1 restores q=1 rows.
        ragged_decode_steps: Optional[int] = None,
        # -- SLO-aware scheduling (docs/slo_scheduling.md) -----------------
        # preemptible batch lane: under slot pressure with interactive work
        # queued, batch-class slots are preempted at a chunk boundary (their
        # generated-so-far KV committed into the radix prefix cache) and
        # requeued; preempt_budget bounds preemptions per request
        preempt_batch: bool = True,
        preempt_budget: int = 2,
        # starvation floor: a lower class that waited through this many
        # higher-class queue pops takes the next pop
        starvation_floor: int = 8,
        # brownout controller: None -> enabled iff admission control is on
        # (max_pending set); explicit True/False overrides
        brownout: Optional[bool] = None,
        brownout_batch_cap: int = 32,   # stage>=2 batch max_new_tokens cap
        brownout_dwell: float = 2.0,    # min seconds between stage drops
        # replica identity (docs/replication.md): set by the replica group
        # (llm/replica.py) so health()/lifecycle_stats() — and through them
        # the Prometheus lifecycle series — carry a ``replica`` label.
        # None keeps the legacy single-engine payload shape.
        replica: Optional[str] = None,
    ):
        self.bundle = bundle
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.eos_token_id = eos_token_id
        self.decode_steps = max(1, int(decode_steps))
        if cache_mode == "paged" and int(
            bundle.config.get("sliding_window", 0) or 0
        ):
            raise ValueError(
                "sliding_window models need engine.cache=dense (the paged "
                "decode path does not window its attention yet)"
            )
        if cache_mode == "paged" and getattr(
            bundle, "paged_unsupported_reason", None
        ):
            raise ValueError(bundle.paged_unsupported_reason)
        if cache_mode not in ("dense", "paged"):
            raise ValueError("cache_mode must be 'dense' or 'paged'")
        self.cache_mode = cache_mode
        # host-tier knob validation (docs/kv_tiering.md): a budget that
        # silently does nothing reads as "tiering on" to the operator —
        # fail at construction (= endpoint load) naming the knob instead.
        # "auto" sizes the tier from /proc/meminfo at construction
        # (clamped; HostTierAutoSizeError names unsupported platforms).
        host_auto = (
            isinstance(prefix_cache_host_bytes, str)
            and prefix_cache_host_bytes.strip().lower() == "auto"
        )
        if isinstance(prefix_cache_host_bytes, str) and not host_auto:
            raise ValueError(
                "prefix_cache_host_bytes (aux engine.prefix_cache_host_mb) "
                "must be a size or 'auto': got {!r}".format(
                    prefix_cache_host_bytes
                )
            )
        if host_auto and prefix_cache_host_pages:
            raise ValueError(
                "prefix_cache_host_mb='auto' derives the host page count "
                "itself; drop engine.prefix_cache_host_pages (or set an "
                "explicit size)"
            )
        if prefix_cache_host_bytes and not host_auto \
                and not prefix_cache_host_pages:
            raise ValueError(
                "prefix_cache_host_bytes (aux engine.prefix_cache_host_mb) "
                "is set but the host tier is disabled: set "
                "prefix_cache_host_pages (aux "
                "engine.prefix_cache_host_pages) to enable it"
            )
        if (prefix_cache_host_pages or host_auto) and (
            cache_mode != "paged"
            or not prefix_cache
            or not hasattr(bundle, "prefill_chunk")
        ):
            raise ValueError(
                "prefix_cache_host_pages (or prefix_cache_host_mb='auto') "
                "needs cache_mode='paged' and a prefix_cache on a bundle "
                "with prefill_chunk (the host tier spills the paged radix "
                "prefix cache; docs/kv_tiering.md)"
            )
        # -- ragged scheduling (docs/ragged_attention.md) ------------------
        # resolved EARLY: the dense cache slack and the prefill gate both
        # depend on the scheduler choice
        sched = (
            scheduler
            if scheduler is not None
            else os.environ.get("TPUSERVE_SCHEDULER", "") or "two_dispatch"
        )
        if sched not in ("two_dispatch", "ragged"):
            raise ValueError(
                "scheduler must be 'two_dispatch' or 'ragged' (got {!r})"
                .format(sched)
            )
        self._ragged = sched == "ragged"
        if self._ragged and (
            getattr(bundle, "forward_ragged", None) is None
            or getattr(bundle, "forward_ragged_dense", None) is None
        ):
            raise ValueError(
                "scheduler='ragged' needs a model bundle with "
                "forward_ragged/forward_ragged_dense surfaces"
            )
        if step_token_budget is None:
            raw = os.environ.get("TPUSERVE_STEP_TOKEN_BUDGET", "")
            step_token_budget = int(raw) if raw else None
        self._step_token_budget = (
            int(step_token_budget)
            if step_token_budget is not None
            else max(128, 4 * self.max_batch)
        )
        if self._ragged and self._step_token_budget <= self.max_batch:
            # every decode row costs one budget token; a budget at or below
            # max_batch could starve admissions forever
            raise ValueError(
                "step_token_budget ({}) must exceed max_batch ({}) so "
                "prefill chunks always fit beside a full decode batch"
                .format(self._step_token_budget, self.max_batch)
            )
        # multi-step ragged decode rows (docs/ragged_attention.md): each
        # launch advances every decode slot by up to this many chained
        # tokens. Capped by decode_steps' slack sizing below: the paged
        # table width and the dense cache slack are dimensioned from
        # decode_steps, so the ragged window may not exceed it.
        self._ragged_decode_steps = (
            max(1, int(ragged_decode_steps))
            if ragged_decode_steps is not None
            else self.decode_steps
        )
        if self._ragged_decode_steps > self.decode_steps:
            raise ValueError(
                "ragged_decode_steps ({}) must not exceed decode_steps "
                "({}): per-slot KV slack and page-table width are sized "
                "from decode_steps".format(
                    self._ragged_decode_steps, self.decode_steps
                )
            )
        # the largest per-launch window actually reachable (pow2-bucketed);
        # warmup enumerates every power of two up to it
        self._ragged_steps_cap = decode_steps_bucket(self._ragged_decode_steps)
        self._buckets = sorted(
            b for b in (prefill_buckets or _DEFAULT_PREFILL_BUCKETS) if b <= max_seq_len
        ) or [max_seq_len]
        self._mesh = mesh
        # long-context sequence parallelism: prompts past the threshold
        # prefill through ring attention over the mesh's sp axis (the prompt
        # spreads across chips; SURVEY.md §5.7) — needs sp > 1 and a bundle
        # with a prefill_ring surface
        self._sp = int(dict(mesh.shape).get("sp", 1)) if mesh is not None else 1
        if self._sp > 1 and getattr(bundle, "prefill_ring", None) is None:
            self._sp = 1
        self._long_threshold = (
            int(long_prefill_threshold)
            if long_prefill_threshold is not None
            else self._buckets[-1]
        )
        # long-prefill shapes pad to multiples of this (must divide sp)
        step = int(long_bucket_step) if long_bucket_step else self._sp * 512
        self._long_step = -(-step // self._sp) * self._sp
        # largest sp-divisible ring bucket that still fits the cache: prompts
        # between this and max_seq_len fall back to plain prefill (rounding
        # the bucket UP past max_seq_len would crash the cache insert)
        self._long_cap = (self.max_seq_len // self._sp) * self._sp if self._sp > 1 else 0

        # multi-LoRA: install each named adapter into the param tree's
        # stacked factors (models/lora.py) BEFORE quantization/sharding —
        # the stacks stay full precision (quantize only touches base
        # projections) and shard/replicate per parallel/sharding.py
        self._adapter_index: Dict[str, int] = {}
        if lora_adapters:
            from ..models import lora as lora_lib

            if not int(getattr(bundle, "lora_rank", 0) or 0):
                raise ValueError(
                    "lora_adapters given but the model was built without "
                    "lora_rank (set engine.lora.rank / config lora_rank)"
                )
            if len(lora_adapters) > int(bundle.max_loras):
                raise ValueError(
                    "{} adapters exceed max_loras {}".format(
                        len(lora_adapters), bundle.max_loras
                    )
                )
            for i, (name, tree) in enumerate(lora_adapters.items(), start=1):
                params = lora_lib.install_adapter(params, i, tree)
                self._adapter_index[name] = i
        self._lora_enabled = bool(self._adapter_index)

        # int8 weight quantization: params live in HBM as int8 + scales; the
        # model's weight accessor (models/llama.py `_w`) dequantizes each
        # weight INSIDE the traced layer body — per layer even under
        # scan_layers — so XLA fuses dequant next to each consumer matmul and
        # weights at rest stay int8 (HBM ~halves) or group-int4 (~quarters;
        # the decode path is weight-read bound, so bytes saved are tok/s).
        if weight_quant and quantize and weight_quant != quantize:
            raise ValueError(
                "weight_quant={!r} conflicts with the legacy quantize={!r} "
                "alias; set only one".format(weight_quant, quantize)
            )
        quantize = weight_quant or quantize
        self._quantized = False
        self.weight_quant = ""
        # offline-quantized bundles (scripts/quantize_ckpt.py) arrive
        # already packed: detect BEFORE quantizing so a redundant (or
        # mismatched) weight_quant knob becomes a no-op (or a clear error)
        # instead of quantize_llama_params choking on the packed dicts —
        # and so TP sharding picks the quantized specs / stats report the
        # real weight format when no knob is set at all.
        from ..ops.quant import detect_weight_quant

        pre = detect_weight_quant(params)
        if quantize and quantize not in ("int8", "int4"):
            raise ValueError(
                "unsupported weight_quant mode {!r} (expected 'int8' or "
                "'int4')".format(quantize)
            )
        if pre and quantize and pre != quantize:
            raise ValueError(
                "weight_quant={!r} requested but the bundle is already "
                "{}-quantized (scripts/quantize_ckpt.py output); drop the "
                "knob or quantize from the original full-precision "
                "checkpoint".format(quantize, pre)
            )
        if pre:
            self._quantized = True
            self.weight_quant = pre
        elif quantize:
            from ..ops.quant import quantize_llama_params

            params = quantize_llama_params(
                params, bits=4 if quantize == "int4" else 8
            )
            self._quantized = True
            self.weight_quant = quantize
        # weight-tree HBM footprint (global bytes; per-chip is 1/tp under a
        # mesh) — the decode roofline's dominant bytes/step term, surfaced
        # through lifecycle_stats()/health() and bench.py --int4-ab
        import jax as _jax

        self._weight_bytes = int(sum(
            leaf.nbytes
            for leaf in _jax.tree.leaves(params)
            if hasattr(leaf, "nbytes")
        ))

        if mesh is not None:
            from ..parallel.sharding import (
                llama_cache_sharding,
                llama_param_sharding,
                llama_quantized_param_sharding,
                shard_params,
            )

            heads = dict(
                n_kv_heads=getattr(bundle, "n_kv_heads", None),
                n_heads=bundle.config.get("n_heads"),
            )
            if not self._quantized:
                self.params = shard_params(
                    mesh, params, llama_param_sharding(mesh, params, **heads)
                )
            else:
                # int8 tree TP-shards like the bf16 weights (scales lose the
                # input-axis entry) — per-chip HBM ≈ 1/tp of the model
                self.params = shard_params(
                    mesh, params,
                    llama_quantized_param_sharding(mesh, params, **heads),
                )
            self._cache_sharding = llama_cache_sharding(
                mesh, quantized=bool(bundle.config.get("kv_quant"))
            )
        else:
            self.params = params
            self._cache_sharding = None

        # speculative chunks verify spec_k+1 positions per round and
        # decode_steps rounds per dispatch; both cache backends carry that
        # much per-slot slack so in-chunk writes never clamp/overflow
        # (sized from the CLAMPED spec_k — max(1, ...), applied again below —
        # a raw spec_k<=0 would under-allocate)
        spec_slack = (
            self.decode_steps * (max(1, int(spec_k)) + 1) if speculation else 0
        )
        # ragged dense steps write each row's whole C-token chunk window at
        # its start position (pad tail included, overwritten before it is
        # ever visible) — the buffer needs chunk-window-wide slack past
        # max_seq_len or dynamic_update_slice would CLAMP the window
        # backward over live KV at the sequence edge (the same hazard the
        # spec slack covers). C buckets to the next power of two of the
        # step's widest chunk, which can EXCEED the budget (budget 24 ->
        # C 32), so the slack covers the bucketed bound, not the budget.
        if self._ragged and cache_mode == "dense":
            spec_slack = max(
                spec_slack, 1 << (self._step_token_budget - 1).bit_length()
            )
        # kept for supervised recovery: a poisoned dense decode step may have
        # consumed (donated) the cache — rebuilding needs the original size
        self._cache_slack = spec_slack
        # int8 paged KV (docs/paged_kv_quant.md): the same kv_quant knob the
        # dense cache honors now reaches the paged backend — int8 pools +
        # per-(token, head) scale pools, dequant inside the paged kernel
        self._paged_quant = (
            self.cache_mode == "paged"
            and bool(bundle.config.get("kv_quant"))
        )
        if self.cache_mode == "paged":
            from .kv_cache import PagedKVCache

            if self._paged_quant and page_size % 32:
                # the int8 Pallas tile is (32, 128): misaligned pages route
                # every TPU decode to the XLA-gather fallback, forfeiting
                # the halved-DMA win (docs/paged_kv_quant.md). Not an error
                # — CPU/interpret runs and capacity-only deployments are
                # legitimate — but it must not be silent.
                import warnings

                warnings.warn(
                    "kv_quant=int8 with page_size={} : the int8 paged "
                    "Pallas kernel needs page_size % 32 == 0 on TPU; this "
                    "config will use the XLA-gather fallback there (set "
                    "engine.page_size=32)".format(page_size),
                    stacklevel=2,
                )
            # default pool: every slot can hold max_seq_len + one decode chunk
            # (no oversubscription by default; page 0 is the reserved null page).
            # Speculation over-allocates decode_steps*(k+1) tokens per chunk
            # and rolls back (PagePool.truncate), so the table width and the
            # default pool must cover that worst case.
            pages_per_slot = -(
                -(self.max_seq_len + max(self.decode_steps, spec_slack))
                // page_size
            )
            total_pages = num_pages or (self.max_batch * pages_per_slot + 1)
            self.paged_cache = PagedKVCache(
                bundle.n_layers, bundle.n_kv_heads, bundle.head_dim,
                num_pages=total_pages, page_size=page_size,
                max_slots=self.max_batch,
                dtype=bundle.config.get("dtype", "bfloat16"),
                kv_quant=str(bundle.config.get("kv_quant") or ""),
            )
            if mesh is not None:
                # shard the pools' kv-head dim over tp (pools [L,Hkv,N,P,D]) —
                # without this every chip replicates the full pool
                from jax.sharding import NamedSharding, PartitionSpec as P

                pool_sharding = NamedSharding(mesh, P(None, "tp", None, None, None))
                self.paged_cache.k = jax.device_put(self.paged_cache.k, pool_sharding)
                self.paged_cache.v = jax.device_put(self.paged_cache.v, pool_sharding)
                if self._paged_quant:
                    # scale pools [L, Hkv, N, P] shard the same kv-head dim
                    scale_sharding = NamedSharding(mesh, P(None, "tp", None, None))
                    self.paged_cache.k_scale = jax.device_put(
                        self.paged_cache.k_scale, scale_sharding
                    )
                    self.paged_cache.v_scale = jax.device_put(
                        self.paged_cache.v_scale, scale_sharding
                    )
            self._pages_per_seq = pages_per_slot
            self.cache = None
        else:
            self.paged_cache = None
            # dense: the slack keeps verify's dynamic_update_slice writes
            # from clamping at the buffer edge (a clamp would overwrite
            # live K/V)
            self.cache = bundle.init_cache(
                self.max_batch, self.max_seq_len + spec_slack
            )
            if self._cache_sharding is not None:
                self.cache = {
                    k: jax.device_put(v, self._cache_sharding[k])
                    for k, v in self.cache.items()
                }

        # slot bookkeeping (host side)
        self._slot_req: List[Optional[GenRequest]] = [None] * self.max_batch
        self._next_token = np.zeros(self.max_batch, np.int32)
        self._temperature = np.zeros(self.max_batch, np.float32)
        self._top_k = np.zeros(self.max_batch, np.int32)
        self._top_p = np.ones(self.max_batch, np.float32)
        self._lora_slots = np.zeros(self.max_batch, np.int32)  # 0 = base
        # sampling extras (penalties / bias / seeds): host mirrors per slot;
        # the [B, V] device state (generated-token counts, prompt mask, dense
        # bias) allocates lazily on the first request that needs any of it
        self._vocab = int(bundle.config.get("vocab_size", 0))
        self._presence = np.zeros(self.max_batch, np.float32)
        self._frequency = np.zeros(self.max_batch, np.float32)
        self._repetition = np.ones(self.max_batch, np.float32)
        self._seeds = np.full(self.max_batch, -1, np.int64)
        self._min_tokens = np.zeros(self.max_batch, np.int32)
        # per-slot stop-token sets for min_tokens suppression (the same set
        # _emit finishes on: stop_token_ids or [eos]); -1-padded, first
        # _STOP_SLOTS honored
        self._stop_rows = np.full((self.max_batch, _STOP_SLOTS), -1, np.int32)
        self._slot_extra = np.zeros(self.max_batch, bool)
        self._counts_dev = None   # [B, V] int32 generated-token histogram
        self._bias_dev = None     # [B, V] float32 dense logit bias
        self._pmask_dev = None    # [B, V] bool prompt-token mask

        # per-class pending queues (strict class order, EDF within a class,
        # starvation floor) — docs/slo_scheduling.md
        self._pending = _ClassedPendingQueue(starvation_floor)
        self._loop_task: Optional[asyncio.Task] = None
        # replica identity in a fleet (docs/replication.md); None = legacy
        # single-engine payloads (no `replica` key in health/stats)
        self.replica_id = str(replica) if replica is not None else None
        # -- request-lifecycle hardening state ----------------------------
        self.max_pending = int(max_pending) if max_pending else None
        self._queue_timeout = float(queue_timeout) if queue_timeout else None
        self._ttft_timeout = float(ttft_timeout) if ttft_timeout else None
        self._total_timeout = float(total_timeout) if total_timeout else None
        self._watchdog_interval = (
            float(watchdog_interval) if watchdog_interval else None
        )
        self._watchdog_task: Optional[asyncio.Task] = None
        self._last_progress = time.monotonic()
        # bumped by the watchdog when it fails a stalled batch; the loop
        # compares it around every dispatch and discards stale results
        self._recover_epoch = 0
        self._recovering = False
        self.counters: Dict[str, int] = {
            "sheds_queue": 0,
            "sheds_pool": 0,
            "deadline_queue": 0,
            "deadline_ttft": 0,
            "deadline_total": 0,
            "watchdog_trips": 0,
            "step_failures": 0,
            "preemptions": 0,
            "ragged_steps": 0,
            # decode tokens advanced by ragged mixed launches (multi-step
            # windows + accepted spec tokens): ragged_steps / this ratio is
            # dispatches-per-decode-token, the bubble-amortization headline
            "ragged_decode_tokens": 0,
            # rows the engine.spec.tree chaos seam demoted from spec-verify
            # back to plain decode (docs/spec_decode_trees.md fallback row)
            "spec_tree_fallbacks": 0,
        }
        # -- SLO-aware scheduling state (docs/slo_scheduling.md) ----------
        # per-(reason, class) shed counters backing engine_sheds_total
        self._class_sheds: Dict[str, Dict[str, int]] = {}
        # recent admission-commit timestamps: the observed drain rate turns
        # a 429's Retry-After from a constant into queue_depth / rate
        self._admit_times: Deque[float] = deque(maxlen=32)
        self._admit_count = 0
        self._preempt = bool(preempt_batch)
        self._preempt_budget = max(0, int(preempt_budget))
        self._brownout = (
            _BrownoutController(dwell=brownout_dwell)
            if (brownout if brownout is not None else max_pending is not None)
            else None
        )
        self._brownout_batch_cap = max(1, int(brownout_batch_cap))
        self._brownout_checked = 0.0
        # (t, deadline_hits, watchdog_trips, admits) snapshot anchoring the
        # pressure window's deadline/watchdog rates
        self._pressure_window: Optional[tuple] = None
        self._rng = jax.random.PRNGKey(rng_seed)
        self._rng_lock = threading.Lock()
        self._step_counter = itertools.count()
        self._stopped = False
        self._prefill_templates: Dict[int, Any] = {}
        self._template_lock = threading.Lock()
        # admission overlap: prefills run in worker threads while decode
        # chunks continue; finished prefills land here and are committed into
        # their reserved slot at the next chunk boundary (loop thread only)
        self._ready: "asyncio.Queue" = asyncio.Queue()
        self._admitting: set = set()
        self._admission_tasks: set = set()  # strong refs; see _run_loop_inner
        # guided decoding (llm/guided.py): grammars compile once per unique
        # spec into a COMBINED state space (per-grammar state offsets) so
        # mixed-grammar batches share one mask/byte-table pair on device.
        # Retraces are bounded by padding the combined state count to
        # power-of-two buckets.
        self._guided_lock = threading.Lock()
        self._tokenizer = tokenizer
        self._grammars: Dict[str, dict] = {}      # key -> entry
        self._gmask_np: Optional[np.ndarray] = None   # [S, Vb] uint8
        self._gbyte_np: Optional[np.ndarray] = None   # [S, 256] int16
        self._gmask_dev = None
        self._gbyte_dev = None
        self._gtok_dev = None                     # (tok_bytes, tok_len)
        self._gtok_np = None
        self._gtok_bytes = None                   # cached token_byte_table
        self._gstate = np.full(self.max_batch, -1, np.int32)
        self._slot_guided_key: List[Optional[str]] = [None] * self.max_batch
        self._guided_dirty = False
        # decode-first prefill pacing (None/0 disables the policy). The
        # ragged scheduler REPLACES the gate outright: admission pacing is
        # the per-step token budget, and there are no standalone prefill
        # dispatches left to pace (docs/ragged_attention.md)
        self._prefill_gate = (
            _PrefillGate(
                int(prefill_segments_per_decode),
                **(
                    {"stall_timeout": float(prefill_stall_timeout)}
                    if prefill_stall_timeout
                    else {}
                ),
            )
            if (prefill_segments_per_decode and not self._ragged)
            else None
        )
        # -- ragged scheduler state (docs/ragged_attention.md) -------------
        # in-progress chunked admissions, consumed by the loop in order
        # (class order held by the admission pop); loop-affine
        self._prefill_jobs: List[_RaggedJob] = []
        # admissions whose worker-thread prep (grammar compile) finished,
        # waiting for the loop to open their job
        self._ragged_ready: "asyncio.Queue" = asyncio.Queue()
        # per-step token-budget utilization (used / budget) and per-phase
        # row counters, exported as engine_step_token_budget_utilization /
        # engine_step_rows{phase} (statistics/metrics.py)
        self._hist_budget = _MsHistogram(
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
        )
        self._step_rows = {"prefill": 0, "decode": 0, "spec_verify": 0}
        # multi-step / spec-as-row observability (loop-affine, like the
        # budget histogram): decode tokens advanced per mixed launch
        # (multi-step windows + accepted spec tokens) and the per-launch
        # mean accepted-draft fraction over spec verify rows — the two
        # numbers that say whether the per-launch dispatch bubble is
        # actually amortized (engine_decode_tokens_per_launch /
        # engine_spec_acceptance_rate in statistics/metrics.py)
        self._hist_launch_tokens = _MsHistogram(
            buckets=(1, 2, 4, 8, 16, 32, 64)
        )
        self._hist_spec_accept = _MsHistogram(
            buckets=(0.0, 0.2, 0.4, 0.6, 0.8, 1.0)
        )
        self._wake: Optional[asyncio.Event] = None

        # -- pipelined decode (docs/pipelined_decode.md) -------------------
        # Bounded in-flight dispatch queue: chunk N+1 is enqueued while
        # chunk N still computes on device; chunk N's readback + emission
        # (the retire stage) overlaps chunk N+1's compute. The only
        # cross-chunk data dependency — the last sampled token — chains on
        # device (chunk[:, -1]), so no host roundtrip sits between chunks.
        self.pipeline_depth = (
            max(1, int(pipeline_depth))
            if pipeline_depth is not None
            else _env_pipeline_depth()
        )
        self._inflight: Deque[_InFlightChunk] = deque()
        self._dispatch_seq = 0
        # (seq, active_mask) of a chunk whose worker-thread dispatch is in
        # progress (not yet an _inflight entry): the slot-reuse barrier
        # must see it — the concurrent retire stage can free slots
        self._dispatching: Optional[tuple] = None
        # slot -> dispatch seq that must retire before the slot's pages may
        # be freed / the slot re-admitted: it was freed at a retire while
        # younger chunks that still decode it were in flight (their extra
        # tokens are dropped by _emit's None check; their KV writes must not
        # land in re-allocated pages)
        self._quarantine: Dict[int, int] = {}
        # device-resident cross-chunk state (None -> upload the host
        # mirror); _slot_overrides marks slots whose host value must win at
        # the next dispatch (fresh commits between dispatches)
        self._next_token_dev = None
        self._gstate_dev = None
        self._slot_overrides = np.zeros(self.max_batch, bool)
        # cached device-side sampling constants: re-uploading temperature /
        # top_k / top_p (and the static extras rows) as fresh device arrays
        # every chunk puts 6+ tiny host->device transfers on every dispatch;
        # they only change at commit (invalidated there)
        self._sampling_dev = None
        self._extras_dev = None
        # dispatch/retire stage timing for the lifecycle collector
        self._hist_dispatch = _MsHistogram()
        self._hist_retire = _MsHistogram()
        # host-tier promotion reaping (docs/kv_tiering.md): loop-affine —
        # completed promotion DMAs are observed at retire boundaries
        self._tier_counters = {"reaps": 0}

        # -- disaggregated prefill/decode (docs/disaggregation.md) ---------
        # KV-transport endpoint + role, attached by the replica group
        # (attach_kv_transport); None = monolithic engine, every ship/
        # receive path short-circuits. Counters are plain GIL-atomic bumps:
        # ships land on the loop thread (commit), receives on the group's
        # receive worker, hit/recompute accounting on admission workers.
        self._kv_transport = None
        self.replica_role = "hybrid"
        self._kv_ship_stats = {
            "ships": 0,            # shipments exported + sent
            "ship_pages": 0,
            "ship_drops": 0,       # transport full / injected ship fault
            "receives": 0,         # shipments imported into the cache
            "receive_pages": 0,
            "receive_empty": 0,    # nothing queued for the key
            "receive_failures": 0, # fault/pool/geometry -> dropped
            "hits": 0,             # shipped request admitted over the
            "recomputes": 0,       # shipped prefix vs. recomputed it
            "draft_ships": 0,      # draft-ahead partial frames sent at
            "draft_pages": 0,      # ragged chunk boundaries
            "draft_aborts": 0,     # kv.ship.partial fault / send failure
        }
        # draft-ahead shipping state (loop thread): slot -> {offset pages
        # already shipped unsealed, aborted}. Sealed/cleared at commit
        # (_maybe_ship), dropped with the slot on every failure path
        # (_free_ragged_slot) — an unsealed receiver assembly is never
        # consumable, so dropping the state IS the remote cleanup.
        self._kv_draft_ahead: Dict[int, dict] = {}
        # ship (export+send, loop thread) / receive (import, group worker)
        # wall-time — engine_kv_ship_ms{direction} in statistics/metrics.py
        self._hist_ship_ms = _MsHistogram()
        self._hist_receive_ms = _MsHistogram()

        # -- compiled functions --------------------------------------------
        # frozen config the traced closures need is captured as LOCALS, not
        # read off self: a jitted function that closes over self bakes the
        # attribute value into the trace, and a later mutation is silently
        # ignored (tpuserve-analyze TPU201 enforces this tree-wide)
        decode_steps = self.decode_steps

        def _prefill(params, tokens, seq_lens, cache_template, lora_idx=None):
            if lora_idx is None:  # static at trace: non-LoRA graphs unchanged
                return bundle.prefill(params, tokens, seq_lens, cache_template)
            return bundle.prefill(
                params, tokens, seq_lens, cache_template, lora_idx
            )

        self._prefill_jit = jax.jit(_prefill)

        if self._sp > 1:

            def _prefill_ring(params, tokens, seq_lens, cache_template,
                              lora_idx=None):
                if lora_idx is None:
                    return bundle.prefill_ring(
                        params, tokens, seq_lens, cache_template, mesh
                    )
                return bundle.prefill_ring(
                    params, tokens, seq_lens, cache_template, mesh, lora_idx
                )

            self._prefill_ring_jit = jax.jit(_prefill_ring)
        else:
            self._prefill_ring_jit = None

        # pipeline-parallel prefill over the mesh's pp axis: long prompts
        # flow through layer-stage slabs as sequence-chunk microbatches
        # (models/llama.py prefill_pipeline) so all pp groups compute
        # concurrently instead of all-gathering weights per layer. Gated to
        # configs the stage body reproduces exactly (no LoRA here: adapter
        # stacks ride the scanned layer axis the pipeline re-slabs).
        self._pp = int(dict(mesh.shape).get("pp", 1)) if mesh is not None else 1
        self._pp_chunk = max(1, int(pipeline_chunk))
        if (
            self._pp > 1
            and getattr(bundle, "prefill_pipeline", None) is not None
            and bundle.n_layers % self._pp == 0
            and not lora_adapters
        ):

            pp_stages, pp_chunk = self._pp, self._pp_chunk

            def _prefill_pp(params, tokens, seq_lens, cache_template,
                            lora_idx=None):
                assert lora_idx is None
                return bundle.prefill_pipeline(
                    params, tokens, seq_lens, cache_template,
                    stages=pp_stages, chunk=pp_chunk,
                )

            self._prefill_pipeline_jit = jax.jit(_prefill_pp)
        else:
            self._prefill_pipeline_jit = None

        # chunked prefill: bound each admission dispatch to C tokens so
        # decode chunks interleave on the device stream between prompt
        # segments instead of queueing behind one full-prompt prefill
        self._chunked = int(chunked_prefill_size or 0)
        if self._chunked > 0 and hasattr(bundle, "prefill_chunk"):
            # the first chunk reads the shared never-mutated template, so it
            # must NOT donate; later chunks own their cache and do. Non-final
            # chunks skip the lm_head projection (static with_logits arg).
            self._prefill_chunk_first_jit = jax.jit(
                bundle.prefill_chunk, static_argnames=("with_logits",)
            )
            self._prefill_chunk_jit = jax.jit(
                bundle.prefill_chunk,
                donate_argnums=(4,),
                static_argnames=("with_logits",),
            )
        else:
            self._chunked = 0

        # automatic prefix caching (llm/prefix_cache.py): radix tree of
        # block-granular prompt-prefix KV shared across admissions. On the
        # dense path a hit assembles the stored KV into the mini cache and
        # prefills only the remainder via prefill_chunk; on the paged path a
        # hit maps refcounted pool pages straight into the slot's page table
        # (zero KV copies for the shared run) and storing a prompt is a
        # refcount bump on the slot's own pages. Ring-prefill prompts skip it.
        self._prefix = None
        if prefix_cache and hasattr(bundle, "prefill_chunk"):
            from .prefix_cache import RadixPrefixCache

            if cache_mode == "paged":
                # shared runs must cover whole pages (a block ending mid-page
                # would put live-slot writes inside shared pages): round the
                # block up to the page size
                block = -(-int(prefix_block) // page_size) * page_size
                pool = self.paged_cache.pool
                # a cached page's true HBM cost — K+V data planes plus, on
                # int8 pools, the f32 scale rows that share its lifecycle —
                # derived from the pools themselves so the budget can't
                # drift from the layout kv_cache.py owns
                page_bytes = (
                    sum(self.paged_cache.pool_bytes().values())
                    // pool.num_pages
                )
                # host-RAM tier (docs/kv_tiering.md): preallocate the host
                # page slabs and hand the cache the demote/promote backend —
                # leaf-LRU eviction then spills to host RAM instead of
                # dropping, and warm TTFT becomes capacity-planned
                tier_backend = None
                if host_auto:
                    # size the tier from MemAvailable at CONSTRUCTION
                    # (docs/kv_tiering.md): half of what the host reports,
                    # clamped, converted through the true per-page bytes the
                    # pools themselves define. Off-Linux the probe raises
                    # the named HostTierAutoSizeError — endpoint load
                    # fails fast instead of serving tierless.
                    from .kv_cache import (
                        available_host_memory_bytes,
                        cohosted_worker_processes,
                    )

                    # the half-of-MemAvailable heuristic is PER HOST, not
                    # per process: co-hosted process-backend workers
                    # (TPUSERVE_COHOSTED_PROCS, serving/process_replica.py)
                    # each run this same sizer against the same meminfo
                    # reading, so the budget divides by the fleet width or
                    # a 2-worker fleet over-commits host RAM 2x
                    budget = (
                        available_host_memory_bytes() // 2
                        // cohosted_worker_processes()
                    )
                    budget = min(
                        max(budget, _AUTO_HOST_TIER_MIN_BYTES),
                        _AUTO_HOST_TIER_MAX_BYTES,
                    )
                    prefix_cache_host_pages = max(1, budget // page_bytes)
                    prefix_cache_host_bytes = None  # budget = capacity
                if prefix_cache_host_pages:
                    self.paged_cache.enable_host_tier(
                        int(prefix_cache_host_pages)
                    )
                    tier_backend = self.paged_cache
                self._prefix = RadixPrefixCache(
                    int(prefix_cache), block, max_bytes=prefix_cache_bytes,
                    max_pages=prefix_cache_pages, pool=pool,
                    page_bytes=page_bytes,
                    backend=tier_backend,
                    host_max_bytes=prefix_cache_host_bytes,
                )
                paged_quant = self._paged_quant

                def _gather_pages(kp, vp, pages, plen, ksp=None, vsp=None):
                    # shared pages -> dense mini-cache layout [L,1,S,Hkv,D]
                    # (compute input for the tail's prefill_chunk; the pool
                    # pages themselves are mapped by reference at commit).
                    # `pages` is padded with the null page to the bucket's
                    # page count so traces stay bucketed; garbage beyond
                    # plen is masked by the cache length. int8 pools also
                    # gather the scale rows ([L,1,S,Hkv]) — the dense
                    # mini-cache layout prefill_chunk already consumes
                    # under kv_quant.
                    sk = kp[:, :, pages]                   # [L,H,NP,P,D]
                    l, h, n, p, d = sk.shape
                    k = jnp.moveaxis(sk.reshape(l, h, n * p, d), 1, 2)[:, None]
                    sv = vp[:, :, pages]
                    v = jnp.moveaxis(sv.reshape(l, h, n * p, d), 1, 2)[:, None]
                    out = {
                        "k": k, "v": v,
                        "length": jnp.reshape(plen, (1,)).astype(jnp.int32),
                    }
                    if paged_quant:
                        sks = ksp[:, :, pages]             # [L,H,NP,P]
                        out["k_scale"] = jnp.moveaxis(
                            sks.reshape(l, h, n * p), 1, 2
                        )[:, None]
                        svs = vsp[:, :, pages]
                        out["v_scale"] = jnp.moveaxis(
                            svs.reshape(l, h, n * p), 1, 2
                        )[:, None]
                    return out

                self._gather_pages_jit = jax.jit(_gather_pages)
            else:
                self._prefix = RadixPrefixCache(
                    int(prefix_cache), int(prefix_block),
                    max_bytes=prefix_cache_bytes,
                )
            self._prefix_chunk = self._chunked or int(prefix_block)

            def _assemble(template, prefix_bufs, plen):
                out = {
                    name: jax.lax.dynamic_update_slice(
                        template[name], pre, (0,) * template[name].ndim
                    )
                    for name, pre in prefix_bufs.items()
                }
                out["length"] = jnp.reshape(plen, (1,)).astype(jnp.int32)
                return out

            self._assemble_prefix_jit = jax.jit(_assemble)
            if self._chunked == 0:
                # the hit path drives (the donating) prefill_chunk even when
                # chunked prefill is not configured — it always owns its
                # assembled cache, so no non-donating first-segment variant
                # is needed here
                self._prefill_chunk_jit = jax.jit(
                    bundle.prefill_chunk,
                    donate_argnums=(4,),
                    static_argnames=("with_logits",),
                )

        def _insert(cache, mini_kv, length, slot):
            """Route a prefilled mini cache's buffers into the slot batch.
            Generic over the cache's buffer keys (k/v plus the int8 KV
            path's k_scale/v_scale)."""
            out = {}
            for key, buf in cache.items():
                if key == "length":
                    continue
                zeros = (0,) * (buf.ndim - 2)
                out[key] = jax.lax.dynamic_update_slice(
                    buf, mini_kv[key], (0, slot) + zeros
                )
            out["length"] = jax.lax.dynamic_update_slice(
                cache["length"], length[None].astype(jnp.int32), (slot,)
            )
            return out

        self._insert_jit = jax.jit(_insert, donate_argnums=(0,))

        def _merge_rows(dev, host, override):
            """Fold host-side per-slot overrides (fresh commits) into a
            device-chained [B] vector without a full re-upload."""
            return jnp.where(override, host, dev)

        self._merge_rows_jit = jax.jit(_merge_rows)

        self._lp_k = lp_k = max(1, int(logprobs_k))

        def _lp_of(logits, sampled, nb):
            """(chosen logprob [B], top ids [B,K], top logprobs [B,K]).
            Callers pass the PENALIZED logits when bias/penalties are active
            — reported logprobs reflect what was actually sampled from
            (OpenAI semantics for logit_bias)."""
            lp_full = jax.nn.log_softmax(logits)
            chosen = lp_full[jnp.arange(nb), sampled]
            top_lp, top_id = jax.lax.top_k(lp_full, lp_k)
            return chosen, top_id.astype(jnp.int32), top_lp

        def _guided_mask(logits, gstate, guided):
            """Constrain logits to the slots' grammar states (llm/guided.py
            compiled tables). gstate < 0 = unguided slot."""
            mask_bits, _bt, _tb, _tl = guided
            nb = logits.shape[0]
            guided_on = gstate >= 0
            rows = mask_bits[jnp.clip(gstate, 0)]               # [B, Vb] u8
            bits = (rows[:, :, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
            allowed = bits.reshape(nb, -1)[:, : logits.shape[-1]] > 0
            allowed = jnp.where(guided_on[:, None], allowed, True)
            # a fully-masked row (cannot happen for pruned grammars; belt
            # and braces) degrades to unconstrained instead of NaN
            any_ok = jnp.any(allowed, axis=-1, keepdims=True)
            allowed = allowed | ~any_ok
            return jnp.where(allowed, logits, jnp.float32(-1e30))

        def _guided_advance(gstate, sampled, ok, guided):
            """Walk the sampled token's bytes through the byte DFA (on
            device; Lmax tiny gathers). Zero-length tokens (EOS/specials)
            leave the state in place — EOS finishes the request anyway."""
            _mb, byte_trans, tok_bytes, tok_len = guided
            tb = tok_bytes[sampled]                              # [B, L]
            tl = tok_len[sampled]                                # [B]
            s0 = jnp.clip(gstate, 0)

            def step(i, s):
                nxt = byte_trans[
                    jnp.clip(s, 0), tb[:, i].astype(jnp.int32)
                ].astype(jnp.int32)
                return jnp.where(i < tl, nxt, s)

            walked = jax.lax.fori_loop(0, tok_bytes.shape[1], step, s0)
            return jnp.where((gstate >= 0) & ok, walked, gstate)

        def _decode_chunk(params, tokens, cache, active, sampling, rng,
                          lora_idx=None, extras=None, counts=None, pmask=None,
                          guided=None, gstate=None, want_lp=False):
            """`decode_steps` decode+sample steps fused in one executable
            (lax.scan) — host dispatch overhead amortizes over the chunk.
            ``extras``/``counts``/``pmask`` (penalties, bias, seeds, token
            histogram) are optional: the no-extras trace is unchanged.
            ``guided``/``gstate`` (grammar tables + per-slot DFA states)
            constrain sampling on device when present.
            ``want_lp`` (static) additionally emits per-token logprobs."""
            nb = tokens.shape[0]

            def body(carry, xs):
                tokens, cache, counts, gstate = carry
                step_rng, step_off = xs
                old_len = cache["length"]
                if lora_idx is None:
                    logits, cache = bundle.decode(params, tokens, cache)
                else:
                    logits, cache = bundle.decode(params, tokens, cache, lora_idx)
                # inactive slots: keep their length frozen (their garbage KV
                # write sits beyond `length` and is masked / later overwritten)
                cache["length"] = jnp.where(active, cache["length"], old_len)
                logits = logits.astype(jnp.float32)
                if guided is not None:
                    logits = _guided_mask(logits, gstate, guided)
                if extras is None:
                    sampled = sample_tokens(logits, sampling, step_rng)
                    lp_src = logits
                else:
                    ex = extras._replace(counters=extras.counters + step_off)
                    sampled = sample_tokens(
                        logits, sampling, step_rng, ex, counts, pmask
                    )
                    # reported logprobs reflect bias/penalties (OpenAI
                    # semantics); XLA CSEs this against the sampler's own
                    # penalize pass
                    lp_src = (
                        penalize_logits(logits, ex, counts, pmask)
                        if want_lp
                        else logits
                    )
                    counts = counts.at[jnp.arange(nb), sampled].add(
                        active.astype(jnp.int32)
                    )
                if guided is not None:
                    gstate = _guided_advance(gstate, sampled, active, guided)
                out = (sampled, _lp_of(lp_src, sampled, nb)) if want_lp else sampled
                return (sampled, cache, counts, gstate), out

            rngs = jax.random.split(rng, decode_steps)
            steps = jnp.arange(decode_steps, dtype=jnp.int32)
            if gstate is None:
                gstate = jnp.full((nb,), -1, jnp.int32)
            (_, cache, counts, gstate), out = jax.lax.scan(
                body, (tokens, cache, counts, gstate), (rngs, steps)
            )
            if want_lp:
                toks, (chosen, top_id, top_lp) = out
                # [steps, ...] -> batch-major
                lp = (chosen.T, jnp.swapaxes(top_id, 0, 1), jnp.swapaxes(top_lp, 0, 1))
                return toks.T, cache, counts, lp, gstate
            return out.T, cache, counts, None, gstate  # [B, decode_steps]

        self._decode_chunk_jit = jax.jit(
            _decode_chunk, donate_argnums=(2,), static_argnames=("want_lp",)
        )
        # first-token (admission) logprobs from the prefill logits
        def _score_prompt(params, tokens, lora_idx=None):
            """Teacher-forced scoring: tokens [1, S] -> (chosen [S-1],
            top_ids [S-1, K], top_lp [S-1, K]) for positions 1..S-1 (the
            first token has no conditional). OpenAI completions
            `echo` + `logprobs` needs per-prompt-token logprobs.

            The softmax/top-k pass runs in SEQUENTIAL position blocks
            (lax.map): a full-bucket float32 log_softmax over a 128k vocab
            would be a multi-GB HBM transient next to resident weights +
            KV — an OOM that kills in-flight decode."""
            logits = bundle.apply(params, tokens, lora_idx=lora_idx)[0]
            src = logits[:-1]                            # [S-1, V] model dtype
            tgt = tokens[0, 1:]
            block = 256
            s1, v = src.shape
            pad = (-s1) % block
            src = jnp.pad(src, ((0, pad), (0, 0)))
            tgt = jnp.pad(tgt, (0, pad))

            def blk(args):
                lg, tg = args
                lp = jax.nn.log_softmax(lg.astype(jnp.float32))
                chosen = jnp.take_along_axis(lp, tg[:, None], axis=1)[:, 0]
                # exact rank among the full vocab (vLLM prompt_logprobs
                # reports true ranks, not top-k positions)
                rank = 1 + jnp.sum(lp > chosen[:, None], axis=-1)
                tl, ti = jax.lax.top_k(lp, lp_k)
                return chosen, rank.astype(jnp.int32), ti.astype(jnp.int32), tl

            ch, rk, ti, tl = jax.lax.map(
                blk,
                (src.reshape(-1, block, v), tgt.reshape(-1, block)),
            )
            return (
                ch.reshape(-1)[:s1],
                rk.reshape(-1)[:s1],
                ti.reshape(-1, lp_k)[:s1],
                tl.reshape(-1, lp_k)[:s1],
            )

        self._score_prompt_jit = jax.jit(_score_prompt)

        self._first_lp_jit = jax.jit(
            lambda logits, chosen: _lp_of(logits, chosen, logits.shape[0])
        )

        # -- n-gram speculative decoding (per-slot; dense or paged cache) --
        # Fully on-device draft-and-verify: each scan round proposes spec_k
        # draft tokens per slot by matching the last spec_ngram tokens
        # against the slot's own history (prompt-lookup speculation), then
        # ONE verify pass scores all spec_k+1 positions with a single weight
        # read. Accepted-prefix + bonus token means every round emits 1 to
        # spec_k+1 tokens — never fewer tokens/dispatch than the plain scan,
        # and far fewer HBM weight reads per token when drafts hit
        # (repetitive spans: summarization, extraction, code).
        #
        # Per-slot gating (VERDICT r3 #5): only greedy unconstrained slots
        # accept drafts (spec_mask). Plain temperature>0 slots speculate
        # too (sspec_mask) via REJECTION SAMPLING over the draft chain
        # (sampling.speculative_sample_chain — vLLM spec-sampling
        # semantics; distribution-exact, gated by engine.spec_sampling).
        # Slots with sampling extras, grammar constraints, or logprob
        # tracking ride the SAME verify dispatch but take exactly one token
        # per round, fully sampled from position 0's logits with the plain
        # chunk's semantics (penalties/bias/seeds, guided masks + DFA
        # advance, logprobs). On a weight-read-bound decode the k extra
        # verify positions are nearly free, so a mixed batch never forces
        # the engine off the speculative path.
        self._speculation = None
        # captured as a local for the jitted closures below (TPU201: a jit
        # closing over self would trace against stale state)
        paged_quant = self._paged_quant
        if speculation:
            if speculation != "ngram":
                raise ValueError("speculation must be 'ngram' (got {!r})".format(speculation))
            need = "verify_paged" if cache_mode == "paged" else "verify"
            if getattr(bundle, need, None) is None:
                raise ValueError(
                    "model bundle has no {}() surface; speculation needs a "
                    "decoder with multi-position verification".format(need)
                )
            self._speculation = speculation
        self._spec_sampling = bool(spec_sampling)
        self._spec_k = max(1, int(spec_k))
        self._spec_ngram = max(1, int(spec_ngram))
        self._spec_slack = self.decode_steps * (self._spec_k + 1)
        # -- draft-tree verify rows (docs/spec_decode_trees.md) ------------
        # spec_tree routes the ragged verify rows through the pluggable
        # proposer's FOREST topology: same k+1 node budget per row, but the
        # nodes form a tree (ancestor-masked attention, longest-path
        # acceptance, in-launch KV path compaction). Chain engines keep the
        # legacy code path byte-for-byte: no tree arrays enter their jit.
        self._spec_tree = bool(spec_tree)
        self._spec_proposer = None
        if self._spec_tree:
            if not self._speculation:
                raise ValueError(
                    "spec_tree needs speculation='ngram' (the tree is a "
                    "topology over the n-gram proposer's drafts)"
                )
            if cache_mode != "paged":
                raise ValueError(
                    "spec_tree needs cache_mode='paged': the dense chunk "
                    "layers apply plain causal masks and cannot express a "
                    "draft tree's ancestor visibility "
                    "(docs/spec_decode_trees.md)"
                )
        if self._speculation:
            from .spec_proposer import make_proposer

            self._spec_proposer = (
                make_proposer(
                    "ngram-forest",
                    ngram=self._spec_ngram,
                    branch=max(1, int(spec_branch)),
                )
                if self._spec_tree
                else make_proposer("ngram-chain", ngram=self._spec_ngram)
            )
        # accepted PATH DEPTH per tree verify row (0..k), the tree
        # headline engine_spec_tree_accept_depth reads — integer-valued,
        # bucketed at every possible depth for the default k=4
        self._hist_spec_tree_depth = _MsHistogram(
            buckets=(0, 1, 2, 3, 4, 8, 16)
        )
        if self._speculation:
            k_, n_ = self._spec_k, self._spec_ngram
            buf_len = self.max_seq_len + self._spec_slack + 1
            self._tokbuf = np.zeros((self.max_batch, buf_len), np.int32)

            def _make_spec_chunk(paged: bool):
                def _spec_chunk(params, tokbuf, pending, cachelike, active,
                                spec_mask, sspec_mask, sampling, rng,
                                lora_idx=None,
                                extras=None, counts=None, pmask=None,
                                guided=None, gstate=None, want_lp=False,
                                with_sspec=False):
                    t_idx = jnp.arange(buf_len, dtype=jnp.int32)
                    nb = pending.shape[0]
                    # position-0 plain-path slots (extras/guided/logprobs)
                    ns_mask = active & ~spec_mask
                    if with_sspec:
                        ns_mask = ns_mask & ~sspec_mask
                    if gstate is None:
                        gstate = jnp.full((nb,), -1, jnp.int32)
                    if paged:
                        if paged_quant:
                            (k_pools, v_pools, k_scales, v_scales,
                             page_table, lengths) = cachelike
                        else:
                            k_pools, v_pools, page_table, lengths = cachelike
                            k_scales = v_scales = None

                    def round_body(carry, xs):
                        step_rng, step_off = xs
                        if paged:
                            (tokbuf, pending, k_pools, v_pools, k_scales,
                             v_scales, length, counts, gstate) = carry
                        else:
                            tokbuf, pending, cache, counts, gstate = carry
                            length = cache["length"]                # [B]
                        hist = length + 1  # known tokens incl. pending
                        # ---- n-gram proposal from each slot's own history ----
                        tail_pos = (hist[:, None] - n_ + jnp.arange(n_)[None]).clip(0)
                        tail = jnp.take_along_axis(tokbuf, tail_pos, axis=1)  # [B,n]
                        n_win = buf_len - n_ + 1
                        match = jnp.ones((tokbuf.shape[0], n_win), bool)
                        for j in range(n_):  # n_ is static and tiny
                            match = match & (
                                tokbuf[:, j : n_win + j] == tail[:, j : j + 1]
                            )
                        win_idx = jnp.arange(n_win, dtype=jnp.int32)[None]
                        # window must end before the tail starts (a previous
                        # occurrence, not the tail matching itself)
                        valid = match & (win_idx < (hist - n_)[:, None] - n_ + 1)
                        has = jnp.any(valid, axis=1)
                        i_best = jnp.argmax(
                            jnp.where(valid, win_idx, -1), axis=1
                        ).astype(jnp.int32)                         # [B]
                        draft_pos = (
                            i_best[:, None] + n_ + jnp.arange(k_, dtype=jnp.int32)[None]
                        ).clip(0, buf_len - 1)
                        drafts = jnp.take_along_axis(tokbuf, draft_pos, axis=1)
                        # no-match slots: draft the tail's last token repeated —
                        # cheap, and a reject still emits the bonus token
                        drafts = jnp.where(has[:, None], drafts, tail[:, -1:])
                        # ---- one verify pass over pending + drafts ----------
                        tokens_in = jnp.concatenate([pending[:, None], drafts], axis=1)
                        if paged:
                            scale_kw = (
                                {"k_scales": k_scales, "v_scales": v_scales}
                                if paged_quant
                                else {}
                            )
                            if lora_idx is None:
                                vout = bundle.verify_paged(
                                    params, tokens_in, k_pools, v_pools,
                                    page_table, length, **scale_kw,
                                )
                            else:
                                vout = bundle.verify_paged(
                                    params, tokens_in, k_pools, v_pools,
                                    page_table, length, lora_idx, **scale_kw,
                                )
                            if paged_quant:
                                (logits, k_pools, v_pools, k_scales,
                                 v_scales) = vout
                            else:
                                logits, k_pools, v_pools = vout
                        else:
                            if lora_idx is None:
                                logits, cache = bundle.verify(params, tokens_in, cache)
                            else:
                                logits, cache = bundle.verify(
                                    params, tokens_in, cache, lora_idx
                                )
                        logits = logits.astype(jnp.float32)
                        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B,k+1]
                        acc = jnp.sum(
                            jnp.cumprod((drafts == g[:, :k_]).astype(jnp.int32), axis=1),
                            axis=1,
                        )                                            # [B] 0..k
                        if with_sspec:
                            # rejection-sampled draft chain for plain
                            # temperature>0 slots (distribution-exact)
                            step_rng, chain_rng = jax.random.split(step_rng)
                        # ---- plain-path slots: one token from position 0,
                        # plain-chunk semantics (mask -> penalize -> sample ->
                        # count -> DFA advance) -------------------------------
                        l0 = logits[:, 0, :]
                        if guided is not None:
                            l0 = _guided_mask(l0, gstate, guided)
                        if extras is None:
                            sampled = sample_tokens(l0, sampling, step_rng)
                            lp_src = l0
                        else:
                            ex = extras._replace(counters=extras.counters + step_off)
                            sampled = sample_tokens(
                                l0, sampling, step_rng, ex, counts, pmask
                            )
                            lp_src = (
                                penalize_logits(l0, ex, counts, pmask)
                                if want_lp
                                else l0
                            )
                            counts = counts.at[jnp.arange(nb), sampled].add(
                                ns_mask.astype(jnp.int32)
                            )
                        if guided is not None:
                            gstate = _guided_advance(gstate, sampled, ns_mask, guided)
                        acc = jnp.where(spec_mask, acc, 0)
                        if with_sspec:
                            g_s, acc_s = speculative_sample_chain(
                                logits, drafts, sampling, chain_rng
                            )
                            acc = jnp.where(sspec_mask, acc_s, acc)
                            g = jnp.where(sspec_mask[:, None], g_s, g)
                            keep = spec_mask | sspec_mask
                        else:
                            keep = spec_mask
                        g = g.at[:, 0].set(jnp.where(keep, g[:, 0], sampled))
                        new_pending = jnp.take_along_axis(g, acc[:, None], axis=1)[:, 0]
                        new_len = jnp.where(active, length + 1 + acc, length)
                        # append the emitted tokens to the history buffer
                        for i in range(k_ + 1):
                            w = (t_idx[None] == (hist + i)[:, None]) & (
                                (i <= acc) & active
                            )[:, None]
                            tokbuf = jnp.where(w, g[:, i : i + 1], tokbuf)
                        pending = jnp.where(active, new_pending, pending)
                        out = (
                            (g, acc, _lp_of(lp_src, sampled, nb))
                            if want_lp
                            else (g, acc)
                        )
                        if paged:
                            carry = (tokbuf, pending, k_pools, v_pools,
                                     k_scales, v_scales,
                                     new_len.astype(jnp.int32), counts, gstate)
                        else:
                            cache = {**cache, "length": new_len.astype(jnp.int32)}
                            carry = (tokbuf, pending, cache, counts, gstate)
                        return carry, out

                    rngs = jax.random.split(rng, decode_steps)
                    steps = jnp.arange(decode_steps, dtype=jnp.int32)
                    if paged:
                        carry0 = (tokbuf, pending, k_pools, v_pools,
                                  k_scales, v_scales, lengths, counts, gstate)
                    else:
                        carry0 = (tokbuf, pending, cachelike, counts, gstate)
                    carry, out = jax.lax.scan(round_body, carry0, (rngs, steps))
                    if want_lp:
                        gs, accs, lp = out  # lp round-major [R, B, ...]
                    else:
                        (gs, accs), lp = out, None
                    if paged:
                        tokbuf, pending, k_pools, v_pools = carry[:4]
                        counts, gstate = carry[7], carry[8]
                        if paged_quant:
                            new_cachelike = (k_pools, v_pools, carry[4],
                                             carry[5])
                        else:
                            new_cachelike = (k_pools, v_pools)
                    else:
                        tokbuf, pending, new_cachelike, counts, gstate = carry
                    # gs [rounds, B, k+1], accs [rounds, B]
                    return (tokbuf, pending, new_cachelike, gs, accs,
                            counts, gstate, lp)

                return _spec_chunk

            if cache_mode == "paged":
                self._spec_chunk_jit = None
                self._spec_paged_jit = jax.jit(
                    _make_spec_chunk(True), donate_argnums=(3,),
                    static_argnames=("want_lp", "with_sspec"),
                )
            else:
                self._spec_chunk_jit = jax.jit(
                    _make_spec_chunk(False), donate_argnums=(3,),
                    static_argnames=("want_lp", "with_sspec"),
                )
                self._spec_paged_jit = None
        else:
            self._tokbuf = None
            self._spec_chunk_jit = None
            self._spec_paged_jit = None

        paged_quant = getattr(self, "_paged_quant", False)

        def _decode_paged_chunk(
            params, tokens, k_pools, v_pools, k_scales, v_scales,
            page_table, lengths0,
            write_pages, write_offsets, sampling, rng, lora_idx=None,
            extras=None, counts=None, pmask=None, guided=None, gstate=None,
            want_lp=False,
        ):
            """Paged-cache variant of the fused decode chunk. Page/offset
            write coordinates for every step come pre-computed from the host
            page allocator (write_pages/offsets: [B, steps]).
            ``k_scales``/``v_scales`` are the int8 pools' dequant scale
            pools (None on bf16 pools), chained through the scan like the
            data pools."""
            nb = tokens.shape[0]
            active = jnp.asarray(
                lengths0 > 0
            )  # paged slots with content; inactive rows count nothing

            def body(carry, xs):
                (tokens, k_pools, v_pools, k_scales, v_scales, counts,
                 step, gstate) = carry
                step_rng, wp, wo = xs
                scale_kw = (
                    {"k_scales": k_scales, "v_scales": v_scales}
                    if paged_quant
                    else {}
                )
                if lora_idx is None:
                    out = bundle.decode_paged(
                        params, tokens, k_pools, v_pools, page_table,
                        lengths0 + step, wp, wo, **scale_kw,
                    )
                else:
                    out = bundle.decode_paged(
                        params, tokens, k_pools, v_pools, page_table,
                        lengths0 + step, wp, wo, lora_idx, **scale_kw,
                    )
                if paged_quant:
                    logits, k_pools, v_pools, k_scales, v_scales = out
                else:
                    logits, k_pools, v_pools = out
                logits = logits.astype(jnp.float32)
                if guided is not None:
                    logits = _guided_mask(logits, gstate, guided)
                if extras is None:
                    sampled = sample_tokens(logits, sampling, step_rng)
                    lp_src = logits
                else:
                    ex = extras._replace(counters=extras.counters + step)
                    sampled = sample_tokens(
                        logits, sampling, step_rng, ex, counts, pmask
                    )
                    lp_src = (
                        penalize_logits(logits, ex, counts, pmask)
                        if want_lp
                        else logits
                    )
                    counts = counts.at[jnp.arange(nb), sampled].add(
                        active.astype(jnp.int32)
                    )
                if guided is not None:
                    gstate = _guided_advance(gstate, sampled, active, guided)
                out = (sampled, _lp_of(lp_src, sampled, nb)) if want_lp else sampled
                return (
                    (sampled, k_pools, v_pools, k_scales, v_scales, counts,
                     step + 1, gstate),
                    out,
                )

            rngs = jax.random.split(rng, decode_steps)
            if gstate is None:
                gstate = jnp.full((nb,), -1, jnp.int32)
            (
                (_, k_pools, v_pools, k_scales, v_scales, counts, _, gstate),
                out,
            ) = jax.lax.scan(
                body,
                (tokens, k_pools, v_pools, k_scales, v_scales, counts,
                 jnp.int32(0), gstate),
                (rngs, write_pages.T, write_offsets.T),
            )
            if want_lp:
                toks, (chosen, top_id, top_lp) = out
                lp = (chosen.T, jnp.swapaxes(top_id, 0, 1), jnp.swapaxes(top_lp, 0, 1))
                return (toks.T, k_pools, v_pools, k_scales, v_scales, counts,
                        lp, gstate)
            return (out.T, k_pools, v_pools, k_scales, v_scales, counts,
                    None, gstate)

        self._decode_paged_chunk_jit = jax.jit(
            _decode_paged_chunk,
            # donate the data pools (2, 3) and, on int8 pools, the scale
            # pools (4, 5) — donating a None arg is rejected by jax, so the
            # tuple is built per backend
            donate_argnums=(2, 3, 4, 5) if self._paged_quant else (2, 3),
            static_argnames=("want_lp",),
        )
        self._sample_jit = sample_tokens

        # -- ragged mixed prefill+decode step (docs/ragged_attention.md) ---
        # ONE launch per loop iteration: every decode row advances one token
        # while prefill rows process budget-bounded prompt chunks, all
        # through bundle.forward_ragged / forward_ragged_dense. Decode-row
        # sampling mirrors the plain chunk body exactly (guided mask ->
        # penalized sample -> count -> DFA advance), which is what keeps
        # ragged streams byte-identical to the two-dispatch path; finishing
        # prefill rows return their raw last-token logits for the loop's
        # host-side first-token sampling (the same code the legacy
        # admission path runs).
        if self._ragged:

            def _sample_rows(logits, mask, sampling, rng, extras, counts,
                             pmask, guided, gstate, want_lp):
                nb = logits.shape[0]
                if gstate is None:
                    gstate = jnp.full((nb,), -1, jnp.int32)
                masked = logits
                if guided is not None:
                    masked = _guided_mask(masked, gstate, guided)
                if extras is None:
                    sampled = sample_tokens(masked, sampling, rng)
                    lp_src = masked
                else:
                    sampled = sample_tokens(
                        masked, sampling, rng, extras, counts, pmask
                    )
                    lp_src = (
                        penalize_logits(masked, extras, counts, pmask)
                        if want_lp
                        else masked
                    )
                    counts = counts.at[jnp.arange(nb), sampled].add(
                        mask.astype(jnp.int32)
                    )
                if guided is not None:
                    gstate = _guided_advance(gstate, sampled, mask, guided)
                lp = _lp_of(lp_src, sampled, nb) if want_lp else None
                return sampled, counts, lp, gstate

            def _spec_accept(spec, spec_logits, sampling, tree=None):
                """In-launch draft acceptance over the spec-verify rows'
                per-position logits [B, K+1, V]: greedy rows take the
                argmax-match chain, sampled (sspec) rows the
                rejection-sampled chain from llm/sampling.py — the same
                acceptance math the legacy serial scan ran, applied once
                per launch instead of decode_steps times. With ``tree``
                (tree_tokens [B, K+1], tree_parents [B, K+1], tree_n [B])
                the rows are draft TREES and acceptance is the longest
                root-to-leaf walk (docs/spec_decode_trees.md) — the chain
                is its degenerate single-branch case, byte-identical
                (tests/test_spec_tree.py). Returns
                (g [B, K+1], acc [B], spec_any [B], nodes) where nodes
                [B, K+1] is the position->node KV compaction map (None on
                the chain path: accepted positions are already
                contiguous)."""
                spec_sel, sspec_sel, drafts, _idx, spec_rng = spec
                spec_any = spec_sel | sspec_sel
                sl = spec_logits.astype(jnp.float32)
                k_ = drafts.shape[1]
                if tree is not None:
                    t_tok, t_par, t_n = tree
                    g_arg = jnp.argmax(sl, axis=-1).astype(jnp.int32)
                    g_g, acc_g, nodes_g = greedy_tree_walk(
                        g_arg, t_tok, t_par, t_n
                    )
                    g_s, acc_s, nodes_s = speculative_sample_tree(
                        sl, t_tok, t_par, t_n, sampling, spec_rng
                    )
                    g = jnp.where(sspec_sel[:, None], g_s, g_g)
                    acc = jnp.where(
                        sspec_sel, acc_s,
                        jnp.where(spec_sel, acc_g, jnp.zeros_like(acc_g)),
                    ).astype(jnp.int32)
                    ident = jnp.broadcast_to(
                        jnp.arange(t_tok.shape[1], dtype=jnp.int32),
                        t_tok.shape,
                    )
                    nodes = jnp.where(sspec_sel[:, None], nodes_s, nodes_g)
                    nodes = jnp.where(spec_any[:, None], nodes, ident)
                    return g, acc, spec_any, nodes
                g = jnp.argmax(sl, axis=-1).astype(jnp.int32)  # [B, K+1]
                acc_g = jnp.sum(
                    jnp.cumprod(
                        (drafts == g[:, :k_]).astype(jnp.int32), axis=1
                    ),
                    axis=1,
                )
                g_s, acc_s = speculative_sample_chain(
                    sl, drafts, sampling, spec_rng
                )
                g = jnp.where(sspec_sel[:, None], g_s, g)
                acc = jnp.where(
                    sspec_sel, acc_s,
                    jnp.where(spec_sel, acc_g, jnp.zeros_like(acc_g)),
                ).astype(jnp.int32)
                return g, acc, spec_any, None

            def _chain_sample(l, m, step, s_rng, sampling, extras, counts,
                              pmask, guided, gstate, want_lp, nb):
                """One chained decode step's sampling tail — the plain
                chunk body's exact semantics (guided mask -> penalized
                sample -> count -> DFA advance) with the per-step seed
                counter offset, masked to the rows whose window is still
                open this step."""
                l = l.astype(jnp.float32)
                if guided is not None:
                    l = _guided_mask(l, gstate, guided)
                if extras is None:
                    s_tok = sample_tokens(l, sampling, s_rng)
                    lp_src = l
                else:
                    ex = extras._replace(
                        counters=extras.counters + step + 1
                    )
                    s_tok = sample_tokens(
                        l, sampling, s_rng, ex, counts, pmask
                    )
                    lp_src = (
                        penalize_logits(l, ex, counts, pmask)
                        if want_lp
                        else l
                    )
                    counts = counts.at[jnp.arange(nb), s_tok].add(
                        m.astype(jnp.int32)
                    )
                if guided is not None:
                    gstate = _guided_advance(gstate, s_tok, m, guided)
                lp = _lp_of(lp_src, s_tok, nb) if want_lp else None
                return s_tok, counts, gstate, lp

            def _stack_chain(sampled, lp, chain_out, want_lp):
                """[B] step-0 outputs + [S-1, B] chained outputs -> step-major
                [S, B] (and the lp triple likewise)."""
                if want_lp:
                    chain_toks, chain_lp = chain_out
                    sampled = jnp.concatenate([sampled[None], chain_toks])
                    lp = tuple(
                        jnp.concatenate([a[None], b])
                        for a, b in zip(lp, chain_lp)
                    )
                else:
                    sampled = jnp.concatenate([sampled[None], chain_out])
                return sampled, lp

            if cache_mode == "paged":

                def _ragged_paged_step(params, tokens, tok_pos, tok_row,
                                       tok_valid, row_last, k_pools, v_pools,
                                       k_scales, v_scales, page_table,
                                       kv_lens, row_starts, row_lens,
                                       write_page, write_offset, block_rows,
                                       block_q0, decode_mask, sampling, rng,
                                       lora_idx=None, extras=None,
                                       counts=None, pmask=None, guided=None,
                                       gstate=None, want_lp=False,
                                       spec=None, chain=None, tree=None):
                    scale_kw = (
                        {"k_scales": k_scales, "v_scales": v_scales}
                        if paged_quant
                        else {}
                    )
                    logit_kw = (
                        {"row_logit_idx": spec[3]} if spec is not None else {}
                    )
                    if tree is not None:
                        # draft-tree verify rows: per-token ancestor lists
                        # route the attention mask down to the kernel
                        # (docs/spec_decode_trees.md)
                        logit_kw["tree_anc"] = tree[3]
                    out = bundle.forward_ragged(
                        params, tokens, tok_pos, tok_row, tok_valid,
                        row_last, k_pools, v_pools, page_table, kv_lens,
                        row_starts, row_lens, write_page, write_offset,
                        block_rows, block_q0, lora_idx, **scale_kw,
                        **logit_kw,
                    )
                    if paged_quant:
                        logits, k_pools, v_pools, k_scales, v_scales = out
                    else:
                        logits, k_pools, v_pools = out
                    spec_g = spec_acc = None
                    plain_mask = decode_mask
                    if spec is not None:
                        logits, spec_logits = logits
                        spec_g, spec_acc, spec_any, spec_nodes = _spec_accept(
                            spec, spec_logits, sampling,
                            tree=None if tree is None else tree[:3],
                        )
                        plain_mask = decode_mask & ~spec_any
                        if tree is not None:
                            # KV PATH COMPACTION: a tree row's accepted
                            # root-to-leaf nodes sit at non-contiguous row
                            # positions in the pools — gather each accepted
                            # node's just-written K/V and rewrite it at its
                            # path depth, so the retire-stage truncate to
                            # pre+1+acc keeps a contiguous prefix exactly
                            # like a chain row's. Non-moves (and non-tree
                            # rows) scatter to the null page (page 0), the
                            # same discard target every pad write uses.
                            nn = spec_nodes.shape[1]
                            pos = jnp.arange(1, nn, dtype=jnp.int32)
                            src = (
                                row_starts[:, None] + spec_nodes[:, 1:]
                            ).reshape(-1)
                            dst = (
                                row_starts[:, None] + pos[None, :]
                            ).reshape(-1)
                            move = (
                                spec_any[:, None]
                                & (spec_nodes[:, 1:] != pos[None, :])
                            ).reshape(-1)
                            sp, so = write_page[src], write_offset[src]
                            dp = jnp.where(move, write_page[dst], 0)
                            do = jnp.where(move, write_offset[dst], 0)
                            k_pools = k_pools.at[:, :, dp, do].set(
                                k_pools[:, :, sp, so]
                            )
                            v_pools = v_pools.at[:, :, dp, do].set(
                                v_pools[:, :, sp, so]
                            )
                            if paged_quant:
                                k_scales = k_scales.at[:, :, dp, do].set(
                                    k_scales[:, :, sp, so]
                                )
                                v_scales = v_scales.at[:, :, dp, do].set(
                                    v_scales[:, :, sp, so]
                                )
                    raw = logits.astype(jnp.float32)
                    sampled, counts, lp, gstate = _sample_rows(
                        raw, plain_mask, sampling, rng, extras, counts,
                        pmask, guided, gstate, want_lp,
                    )
                    if chain is not None:
                        # multi-step decode rows: chain the sampled token
                        # through S-1 further fused decode steps — the
                        # pipelined chunk's scan, riding the SAME launch as
                        # the mixed ragged pass (docs/ragged_attention.md)
                        step_rngs, chain_mask, chain_wp, chain_wo = chain
                        nb = sampled.shape[0]

                        def body(carry, xs):
                            (tok_c, k_p, v_p, k_s, v_s, counts_c,
                             gstate_c, step) = carry
                            s_rng, m, wp, wo = xs
                            skw = (
                                {"k_scales": k_s, "v_scales": v_s}
                                if paged_quant
                                else {}
                            )
                            if lora_idx is None:
                                o = bundle.decode_paged(
                                    params, tok_c, k_p, v_p, page_table,
                                    kv_lens + step, wp, wo, **skw,
                                )
                            else:
                                o = bundle.decode_paged(
                                    params, tok_c, k_p, v_p, page_table,
                                    kv_lens + step, wp, wo, lora_idx, **skw,
                                )
                            if paged_quant:
                                l, k_p, v_p, k_s, v_s = o
                            else:
                                l, k_p, v_p = o
                            s_tok, counts_c, gstate_c, lp_s = _chain_sample(
                                l, m, step, s_rng, sampling, extras,
                                counts_c, pmask, guided, gstate_c, want_lp,
                                nb,
                            )
                            tok_next = jnp.where(m, s_tok, tok_c)
                            out_s = (
                                (tok_next, lp_s) if want_lp else tok_next
                            )
                            return (
                                (tok_next, k_p, v_p, k_s, v_s, counts_c,
                                 gstate_c, step + 1),
                                out_s,
                            )

                        (
                            (_, k_pools, v_pools, k_scales, v_scales,
                             counts, gstate, _),
                            chain_out,
                        ) = jax.lax.scan(
                            body,
                            (sampled, k_pools, v_pools, k_scales, v_scales,
                             counts, gstate, jnp.int32(0)),
                            (step_rngs, chain_mask, chain_wp, chain_wo),
                        )
                        sampled, lp = _stack_chain(
                            sampled, lp, chain_out, want_lp
                        )
                    return (sampled, raw, k_pools, v_pools, k_scales,
                            v_scales, counts, lp, gstate, spec_g, spec_acc)

                self._ragged_paged_jit = jax.jit(
                    _ragged_paged_step,
                    donate_argnums=(
                        (6, 7, 8, 9) if self._paged_quant else (6, 7)
                    ),
                    static_argnames=("want_lp",),
                )
                self._ragged_dense_jit = None
            else:

                def _ragged_dense_step(params, tokens, start, last_rel,
                                       row_active, cache, decode_mask,
                                       sampling, rng, lora_idx=None,
                                       extras=None, counts=None, pmask=None,
                                       guided=None, gstate=None,
                                       want_lp=False, spec=None, chain=None):
                    logit_kw = (
                        {"logit_rel": spec[3]} if spec is not None else {}
                    )
                    logits, cache = bundle.forward_ragged_dense(
                        params, tokens, start, last_rel, row_active, cache,
                        lora_idx, **logit_kw,
                    )
                    spec_g = spec_acc = None
                    plain_mask = decode_mask
                    if spec is not None:
                        logits, spec_logits = logits
                        spec_g, spec_acc, spec_any, _ = _spec_accept(
                            spec, spec_logits, sampling
                        )
                        plain_mask = decode_mask & ~spec_any
                        # verify() contract: only the accepted prefix (plus
                        # the pending token) advances the row's length; K/V
                        # past it sit beyond ``length`` and are overwritten
                        # by later writes at the same positions
                        cache = dict(
                            cache,
                            length=jnp.where(
                                spec_any,
                                (start + 1 + spec_acc).astype(jnp.int32),
                                cache["length"],
                            ),
                        )
                    raw = logits.astype(jnp.float32)
                    sampled, counts, lp, gstate = _sample_rows(
                        raw, plain_mask, sampling, rng, extras, counts,
                        pmask, guided, gstate, want_lp,
                    )
                    if chain is not None:
                        step_rngs, chain_mask = chain
                        nb = sampled.shape[0]

                        def body(carry, xs):
                            tok_c, cache_c, counts_c, gstate_c, step = carry
                            s_rng, m = xs
                            if lora_idx is None:
                                l, cache_n = bundle.decode(
                                    params, tok_c, cache_c
                                )
                            else:
                                l, cache_n = bundle.decode(
                                    params, tok_c, cache_c, lora_idx
                                )
                            # rows whose window is closed this step freeze
                            # their length: the garbage K/V the batched
                            # write left at the frozen position sits beyond
                            # ``length`` and the next REAL token's write
                            # overwrites it in full
                            cache_n = dict(
                                cache_n,
                                length=jnp.where(
                                    m, cache_n["length"], cache_c["length"]
                                ),
                            )
                            s_tok, counts_c, gstate_c, lp_s = _chain_sample(
                                l, m, step, s_rng, sampling, extras,
                                counts_c, pmask, guided, gstate_c, want_lp,
                                nb,
                            )
                            tok_next = jnp.where(m, s_tok, tok_c)
                            out_s = (
                                (tok_next, lp_s) if want_lp else tok_next
                            )
                            return (
                                (tok_next, cache_n, counts_c, gstate_c,
                                 step + 1),
                                out_s,
                            )

                        (
                            (_, cache, counts, gstate, _),
                            chain_out,
                        ) = jax.lax.scan(
                            body,
                            (sampled, cache, counts, gstate, jnp.int32(0)),
                            (step_rngs, chain_mask),
                        )
                        sampled, lp = _stack_chain(
                            sampled, lp, chain_out, want_lp
                        )
                    return (sampled, raw, cache, counts, lp, gstate,
                            spec_g, spec_acc)

                self._ragged_dense_jit = jax.jit(
                    _ragged_dense_step,
                    donate_argnums=(5,),
                    static_argnames=("want_lp",),
                )
                self._ragged_paged_jit = None
            # static flat-token capacity per launch: ONE trace per
            # (platform, extras/guided/lp variant). On TPU each row's
            # segment aligns to the kernel's q block (worst-case alignment
            # waste = one block per row); off-TPU the XLA reference needs
            # no alignment and rows pack densely. The q-block size is the
            # KERNEL'S constant — the layout the engine builds and the
            # grid forward_ragged launches must share one contract, not
            # two constants that happen to agree.
            from ..ops.paged_attention import _RAGGED_QB

            self._ragged_on_tpu = jax.devices()[0].platform == "tpu"
            qb = _RAGGED_QB if self._ragged_on_tpu else 1
            self._ragged_qb = qb
            budget = self._step_token_budget
            waste = self.max_batch * (qb - 1) if qb > 1 else 0
            self._ragged_tpad = -(-(budget + waste) // qb) * qb

            def _gather_finish_logits(logits, rows):
                # only the FINISHING admission rows' logits leave the
                # device: retire used to read back the full [R, vocab]
                # matrix every step that completed a job (8B: R x 128k
                # f32), when it only ever consumes the finishing rows —
                # row lists pad to a power of two so traces stay bounded
                return logits[rows]

            self._gather_finish_jit = jax.jit(_gather_finish_logits)

        # runtime KV/refcount sanitizer (llm/kv_sanitizer.py): armed via
        # TPUSERVE_SANITIZE=1 (tests arm it for the chaos + paged suites).
        # After every decode step and at drain it audits refcount
        # conservation across slot tables, the radix cache, admission pins,
        # and pending CoW — a violated invariant raises instead of limping.
        self._sanitizer = None
        if self.paged_cache is not None and kv_sanitizer.enabled():
            self._sanitizer = kv_sanitizer.KVSanitizer(
                self.paged_cache.pool, self._prefix,
                paged_cache=self.paged_cache,
            )

        # runtime compile sentry (llm/compile_sentry.py): armed via
        # TPUSERVE_COMPILE_SENTRY=1|strict. Hooks JAX's compile path,
        # splits compilations at the warmup fence (llm/warmup.py), and in
        # strict mode a post-fence compile raises CompileSentryError at
        # the next loop boundary — the dynamic half of the TPU6xx
        # compile-surface discipline (docs/static_analysis.md).
        self._compile_sentry = (
            compile_sentry.get() if compile_sentry.enabled() else None
        )

        # runtime ownership ledger (llm/lifecycle_ledger.py): armed via
        # TPUSERVE_LEDGER=1|strict. Records every declared acquire/release
        # with owner + site, audits pairing per request at emit/fail/cancel
        # and globally at drain — the dynamic half of the TPU7xx ownership
        # discipline (docs/static_analysis.md), covering the static pass's
        # declared blind spots (cross-function, cross-thread transfers).
        self._ledger = (
            lifecycle_ledger.arm() if lifecycle_ledger.enabled() else None
        )

        # runtime sharding sentry (llm/sharding_sentry.py): armed via
        # TPUSERVE_SHARD_SENTRY=1|strict. At every loop boundary it audits
        # the live KV pools and chained device state (plus the params tree
        # at init/drain) against the specs the __shardings__ builders gave
        # them at init, counting implicit device<->host transfers and
        # unplanned reshards per launch; strict mode raises
        # ShardSentryError through the structured step-failure path — the
        # dynamic half of the TPU8xx sharding discipline
        # (docs/static_analysis.md).
        self._shard_sentry = (
            sharding_sentry.arm() if sharding_sentry.enabled() else None
        )
        # co-hosted replica engines share the process-wide sentry: a
        # per-engine path prefix keeps their spec tables disjoint
        self._shard_prefix = "engine[{}]".format(next(_ENGINE_IDS))
        if self._shard_sentry is not None:
            self._shard_sentry.audit(
                self._shard_audit_entries(params=True), where="init"
            )

    def _shard_audit_entries(self, params: bool = False) -> list:
        """(path, value, declared) entries for the sharding sentry's
        boundary audit: chained device state and the KV pools every
        boundary; the params tree only at init and drain boundaries (it
        never rebinds mid-serve, and walking it per step is wasted work).
        """
        p = self._shard_prefix
        entries = [
            (p + "._next_token_dev", self._next_token_dev, None),
            (p + "._gstate_dev", self._gstate_dev, None),
        ]
        if self.paged_cache is not None:
            entries += [
                (p + ".paged_cache.k", self.paged_cache.k, None),
                (p + ".paged_cache.v", self.paged_cache.v, None),
                (p + ".paged_cache.k_scale", self.paged_cache.k_scale, None),
                (p + ".paged_cache.v_scale", self.paged_cache.v_scale, None),
            ]
        elif self.cache is not None:
            entries += [
                (p + ".cache.{}".format(k), v, None)
                for k, v in self.cache.items()
            ]
        if params:
            import jax as _jax

            for path, leaf in _jax.tree_util.tree_leaves_with_path(
                self.params
            ):
                entries.append(
                    (p + ".params" + _jax.tree_util.keystr(path), leaf, None)
                )
        if faults.active():
            # seeded-defect seam (llm/faults.py engine.shard.drift): swap a
            # host-materialized copy in for the chained decode row, exactly
            # the silent device->host round-trip the sentry exists to catch
            # — the self-test proves strict mode raises on it
            try:
                faults.fire("engine.shard.drift")
            except faults.InjectedFault:
                drifted = (
                    np.asarray(self._next_token_dev)
                    if self._next_token_dev is not None
                    else np.zeros(self.max_batch, np.int32)
                )
                entries.append((p + "._next_token_dev", drifted, None))
        return entries

    def _ledger_domains(self) -> list:
        """The primitives whose drain-zero entries THIS engine audits
        (co-hosted replica engines share one process-wide ledger)."""
        domains = [self]
        if self.paged_cache is not None:
            domains += [self.paged_cache, self.paged_cache.pool]
            if self.paged_cache.host_tier is not None:
                domains.append(self.paged_cache.host_tier)
        if self._prefix is not None:
            domains.append(self._prefix)
        return domains

    def _ledger_audit_request(self, request: "GenRequest",
                              where: str) -> None:
        """Per-request pairing audit at a request exit boundary (emit
        finish / fail / cancel): every request-scoped acquire attributed
        to this request must have been released. Strict mode raises on
        the loop thread — the structured step-failure path handles it,
        exactly like a sanitizer violation."""
        if self._ledger is not None and request is not None:
            self._ledger.audit_request(
                lifecycle_ledger.request_tag(request), where=where
            )

    def _sanitize(self, where: str, drained: bool = False) -> None:
        if self._sanitizer is not None:
            self._sanitizer.check(
                where, drained=drained, inflight=len(self._inflight)
            )
        if self._compile_sentry is not None:
            # strict-mode violations surface here, on the loop thread,
            # through the structured step-failure path (like the sanitizer)
            self._compile_sentry.check(where=where)
        if self._ledger is not None:
            self._ledger.check(
                where=where,
                drained=drained and not self._inflight,
                domains=self._ledger_domains(),
            )
        if self._shard_sentry is not None:
            self._shard_sentry.audit(
                self._shard_audit_entries(params=drained), where=where
            )
            # strict-mode sharding violations surface here too, on the
            # loop thread, naming array path + declared vs actual spec
            self._shard_sentry.check(where=where)

    @contextlib.contextmanager
    def _sentry_scope(self, phase: str, **ctx):
        """Thread-local launch attribution for a dispatch/prefill worker
        (no-op unless a sentry is armed): the compile sentry tags the
        compiles and the sharding sentry tags the transfer/reshard
        violations this thread's launches surface."""
        with contextlib.ExitStack() as stack:
            if self._compile_sentry is not None:
                stack.enter_context(self._compile_sentry.context(
                    phase=phase, depth=self.pipeline_depth, **ctx
                ))
            if self._shard_sentry is not None:
                stack.enter_context(self._shard_sentry.context(
                    phase=phase, depth=self.pipeline_depth, **ctx
                ))
            yield

    async def warmup(self, full: bool = True) -> dict:
        """Compile the serve loop's XLA key space ahead of traffic: drive
        the shared warmup shape registry (llm/warmup.py) against this
        engine and set the compile sentry's warmup fence when armed.
        Endpoint startup, ``bench.py --loadtest`` and the coverage tests
        all run THIS sweep — one coverage-checked list."""
        from . import warmup as _warmup

        return await _warmup.run_warmup(self, full=full)

    # -- public API ----------------------------------------------------------

    def validate(self, request: GenRequest) -> None:
        """Raises ValueError for inadmissible requests. Callers that stream
        MUST call this before sending response headers."""
        if len(request.prompt_ids) >= self.max_seq_len:
            raise ValueError(
                "prompt length {} exceeds engine max_seq_len {}".format(
                    len(request.prompt_ids), self.max_seq_len
                )
            )
        if request.priority not in PRIORITY_CLASSES:
            raise ValueError(
                "priority must be one of {} (got {!r})".format(
                    "/".join(PRIORITY_CLASSES), request.priority
                )
            )
        if request.adapter and request.adapter not in self._adapter_index:
            raise ValueError(
                "unknown lora adapter {!r} (loaded: {})".format(
                    request.adapter, sorted(self._adapter_index) or "none"
                )
            )
        if request.logit_bias:
            for tok in request.logit_bias:
                try:
                    tok_i = int(tok)
                except (TypeError, ValueError):
                    raise ValueError(
                        "logit_bias keys must be token ids (got {!r})".format(tok)
                    )
                if not (0 <= tok_i < self._vocab):
                    raise ValueError(
                        "logit_bias token id {} out of range for vocab {}".format(
                            tok_i, self._vocab
                        )
                    )
        if request.repetition_penalty is not None and request.repetition_penalty <= 0:
            raise ValueError("repetition_penalty must be > 0")
        if request.min_tokens:
            if request.min_tokens < 0:
                raise ValueError("min_tokens must be >= 0")
            if request.min_tokens > request.max_new_tokens:
                raise ValueError(
                    "min_tokens {} exceeds max_tokens {}".format(
                        request.min_tokens, request.max_new_tokens
                    )
                )
            if len(request.stop_token_ids or []) > _STOP_SLOTS:
                # suppression rows are fixed-width; an unsuppressed stop id
                # could end the sequence before the floor (ADVICE r3) —
                # reject up front instead of silently under-enforcing
                raise ValueError(
                    "min_tokens supports at most {} stop_token_ids "
                    "(got {})".format(
                        _STOP_SLOTS, len(request.stop_token_ids)
                    )
                )
        if request.logprobs is not None:
            if request.logprobs < 0:
                raise ValueError("logprobs must be >= 0")
            if request.logprobs > self._lp_k:
                raise ValueError(
                    "logprobs={} exceeds the engine's logprobs_k={}".format(
                        request.logprobs, self._lp_k
                    )
                )
        if request.guided is not None:
            from . import guided as _g

            if self._tokenizer is None:
                raise ValueError(
                    "guided decoding needs the engine's tokenizer "
                    "(constructed without one)"
                )
            if self.eos_token_id is None:
                raise ValueError("guided decoding requires an eos token")
            spec = request.guided
            if spec.kind not in ("regex", "json_schema", "json_object"):
                raise ValueError("unknown guided kind {!r}".format(spec.kind))
            # cheap syntactic pre-flight so 4xx errors precede streaming
            # headers; the full (token-lifting) compile runs at admission
            try:
                if spec.kind == "regex":
                    _g._Parser(spec.payload).parse()
                elif spec.kind == "json_schema":
                    import json as _json

                    _g.json_schema_to_regex(_json.loads(spec.payload))
            except _g.RegexError as ex:
                raise ValueError("invalid guided grammar: {}".format(ex))
            except Exception as ex:
                raise ValueError("invalid guided schema: {}".format(ex))

    # -- guided-decoding registry (llm/guided.py) ------------------------

    def _ensure_grammar(self, request: GenRequest) -> dict:
        """Compile (or reuse) the request's grammar and splice it into the
        COMBINED device tables. Runs in the admission worker thread — the
        compile (DFA + token lifting) can take seconds for large vocabs.
        Returns the registry entry {offset, n_states, terminal, refs}."""
        from . import guided as _g

        key = request.guided.cache_key()
        with self._guided_lock:
            entry = self._grammars.get(key)
            if entry is not None:
                entry["refs"] += 1
                request._guided_key = key
                self._ledger_guided_acquire(key, request)
                return entry
        # the O(V) token byte table is per-tokenizer: build once, reuse for
        # every grammar (compile AND device walk share it)
        with self._guided_lock:
            token_bytes = self._gtok_bytes
        if token_bytes is None:
            token_bytes = _g.token_byte_table(self._tokenizer, self._vocab)
        # compile outside the lock (pure); splice under it
        grammar = _g.compile_guided(
            request.guided, self._tokenizer, self._vocab, self.eos_token_id,
            token_bytes=token_bytes,
        )
        with self._guided_lock:
            entry = self._grammars.get(key)
            if entry is not None:  # raced another admission; reuse theirs
                entry["refs"] += 1
                request._guided_key = key
                self._ledger_guided_acquire(key, request)
                return entry
            if self._gtok_bytes is None:
                self._gtok_bytes = token_bytes
            if self._gtok_dev is None:
                tb, tl = _g.build_token_byte_arrays(token_bytes)
                self._gtok_np = (tb, tl)
                self._gtok_dev = (jnp.asarray(tb), jnp.asarray(tl))
            # int16 device states: bound the combined table so offsets can
            # never wrap; fails only THIS request, and only when many
            # distinct grammars are concurrently alive
            total = self._gmask_np.shape[0] if self._gmask_np is not None else 0
            if total + grammar.n_states > 32000:
                raise ValueError(
                    "guided-grammar state budget exhausted ({} + {} states); "
                    "retry when active grammars drain".format(
                        total, grammar.n_states
                    )
                )
            # opportunistic compaction, ONLY when every grammar is dead
            # (refs==0 means no slot state and no in-flight admission holds
            # a key — a partial rebuild would shift offsets under states
            # computed by concurrent admissions, so all-or-nothing)
            if self._grammars and all(
                e["refs"] <= 0 for e in self._grammars.values()
            ):
                self._grammars.clear()
                self._gmask_np = None
                self._gbyte_np = None
                self._guided_dirty = True
            offset = self._gmask_np.shape[0] if self._gmask_np is not None else 0
            entry = {
                "offset": offset,
                "n_states": grammar.n_states,
                "terminal": offset + grammar.terminal,
                "start": offset + grammar.start,
                "refs": 1,
                "grammar": grammar,
            }
            self._grammars[key] = entry
            self._append_guided_tables_locked(grammar)
            request._guided_key = key
            self._ledger_guided_acquire(key, request)
            return entry

    def _append_guided_tables_locked(self, grammar) -> None:
        from . import guided as _g

        offset = self._gmask_np.shape[0] if self._gmask_np is not None else 0
        byte = grammar.byte_trans.astype(np.int32)
        byte = np.where(byte == _g.DEAD, _g.DEAD, byte + offset).astype(np.int16)
        if self._gmask_np is None:
            self._gmask_np = grammar.mask_bits.copy()
            self._gbyte_np = byte
        else:
            self._gmask_np = np.vstack([self._gmask_np, grammar.mask_bits])
            self._gbyte_np = np.vstack([self._gbyte_np, byte])
        self._guided_dirty = True

    def _guided_device_tables(self):
        """(mask_bits, byte_trans, tok_bytes, tok_len) on device, padded to
        power-of-two state counts so trace shapes are bucketed."""
        with self._guided_lock:
            if self._gmask_np is None:
                return None
            if self._guided_dirty or self._gmask_dev is None:
                s = self._gmask_np.shape[0]
                bucket = 1
                while bucket < s:
                    bucket *= 2
                pad = bucket - s
                mask = np.vstack(
                    [self._gmask_np,
                     np.zeros((pad, self._gmask_np.shape[1]), np.uint8)]
                )
                byte = np.vstack(
                    [self._gbyte_np, np.full((pad, 256), -1, np.int16)]
                )
                self._gmask_dev = jnp.asarray(mask)
                self._gbyte_dev = jnp.asarray(byte)
                self._guided_dirty = False
            return (self._gmask_dev, self._gbyte_dev) + self._gtok_dev

    def _release_guided(self, slot: int, request: GenRequest = None) -> None:
        """Slot freed: clear its DFA state and deref its grammar. The key is
        captured at commit time in _slot_guided_key because _slot_req[slot]
        is already None on some finish paths (callers that still hold the
        request pass it so the ledger discharges ITS slab on a grammar key
        shared by concurrent requests)."""
        self._gstate[slot] = -1
        key = self._slot_guided_key[slot]
        if key is None:
            return
        self._slot_guided_key[slot] = None
        self._deref_guided_key(key, request=request)

    def _deref_guided_request(self, request: GenRequest) -> None:
        """Admission failed/dropped before its slot commit: return the
        grammar ref taken by _ensure_grammar."""
        if request._guided_key is not None:
            key, request._guided_key = request._guided_key, None
            self._deref_guided_key(key, request=request)

    def _deref_guided_key(self, key: str,
                          request: GenRequest = None) -> None:
        with self._guided_lock:
            entry = self._grammars.get(key)
            if entry is not None:
                entry["refs"] -= 1
                if self._ledger is not None:
                    lifecycle_ledger.release(
                        "guided.ref", key=key, domain=self,
                        owner=(
                            lifecycle_ledger.request_tag(request)
                            if request is not None else None
                        ),
                    )

    def _ledger_guided_acquire(self, key: str, request: "GenRequest") -> None:
        """One grammar-registry ref taken on the request's behalf
        (_ensure_grammar's three take paths share this record)."""
        if self._ledger is not None:
            lifecycle_ledger.acquire(
                "guided.ref", key=key, domain=self,
                owner=lifecycle_ledger.request_tag(request),
            )

    @property
    def adapter_names(self) -> List[str]:
        return list(self._adapter_index)

    def _slot_lora(self, request: GenRequest) -> int:
        return self._adapter_index.get(request.adapter or "", 0)

    # -- sampling extras (penalties / bias / seeds) -------------------------

    def _request_stop_row(self, request: GenRequest) -> "np.ndarray":
        """The stop set min_tokens suppresses — identical to what _emit
        finishes on: stop_token_ids if given, else the engine eos."""
        ids = request.stop_token_ids or (
            [self.eos_token_id] if self.eos_token_id is not None else []
        )
        row = np.full(_STOP_SLOTS, -1, np.int32)
        for i, t in enumerate(ids[:_STOP_SLOTS]):
            row[i] = int(t)
        return row

    @staticmethod
    def _request_has_extras(request: GenRequest) -> bool:
        return bool(
            request.presence_penalty
            or request.frequency_penalty
            or (request.repetition_penalty and request.repetition_penalty != 1.0)
            or request.seed is not None
            or request.logit_bias
            or request.min_tokens > 0
        )

    def _ensure_extras_state(self) -> None:
        if self._counts_dev is None:
            self._counts_dev = jnp.zeros((self.max_batch, self._vocab), jnp.int32)
            self._bias_dev = jnp.zeros((self.max_batch, self._vocab), jnp.float32)
            self._pmask_dev = jnp.zeros((self.max_batch, self._vocab), bool)

            def _set_row(counts, bias, pmask, slot, first_tok, bias_row, pmask_row):
                # reset the slot's histogram to just the prefill-sampled
                # token (it IS generated output for penalty purposes)
                counts = counts.at[slot].set(0).at[slot, first_tok].set(1)
                bias = bias.at[slot].set(bias_row)
                pmask = pmask.at[slot].set(pmask_row)
                return counts, bias, pmask

            self._set_sampling_row_jit = jax.jit(
                _set_row, donate_argnums=(0, 1, 2)
            )

    def _extras_active(self, active_mask: np.ndarray) -> bool:
        return self._counts_dev is not None and bool(
            np.any(self._slot_extra[active_mask])
        )

    def _batch_sampling(self) -> "SamplingParams":
        """Device-side SamplingParams for the slot batch, cached across
        chunks — the rows only change at commit (which invalidates). The
        host rows are COPIED before upload: zero-copy aliasing of a live,
        commit-mutated buffer would let a future commit rewrite what an
        in-flight chunk samples with (see _chain_input)."""
        if self._sampling_dev is None:
            self._sampling_dev = SamplingParams(
                temperature=jnp.asarray(self._temperature.copy()),
                top_k=jnp.asarray(self._top_k.copy()),
                top_p=jnp.asarray(self._top_p.copy()),
            )
        return self._sampling_dev

    def _batch_extras(self) -> "SamplingExtras":
        """Device-side sampling extras. The per-slot config rows (penalties
        / seeds / min_tokens / stop sets) are cached device constants,
        invalidated only at commit; the produced-token counters are
        per-dispatch data and account for chunks still in flight (a live
        slot advances decode_steps per in-flight chunk — dead slots'
        counters are garbage by then, but their samples are dropped at
        retire anyway)."""
        if self._extras_dev is None:
            seeds = np.where(
                self._seeds < 0, -1, self._seeds & 0x7FFFFFFF
            ).astype(np.int32)
            # host rows COPIED before upload (live buffers; see _chain_input)
            self._extras_dev = SamplingExtras(
                presence=jnp.asarray(self._presence.copy()),
                frequency=jnp.asarray(self._frequency.copy()),
                repetition=jnp.asarray(self._repetition.copy()),
                bias=None,       # device-chained state, patched per call
                seeds=jnp.asarray(seeds),
                counters=None,   # per-dispatch, patched below
                min_new=jnp.asarray(self._min_tokens.copy()),
                stop=jnp.asarray(self._stop_rows.copy()),
            )
        produced = np.asarray(
            [r.produced if r is not None else 0 for r in self._slot_req],
            np.int32,
        )
        for entry in self._inflight:
            produced = produced + (
                entry.active_mask.astype(np.int32) * self.decode_steps
            )
        return self._extras_dev._replace(
            bias=self._bias_dev, counters=jnp.asarray(produced)
        )

    def _bias_pmask_rows(self, request: GenRequest):
        bias = np.zeros(self._vocab, np.float32)
        if request.logit_bias:
            for tok, bv in request.logit_bias.items():
                tok = int(tok)
                if 0 <= tok < self._vocab:
                    bias[tok] = float(bv)
        pmask = np.zeros(self._vocab, bool)
        ids = [t for t in request.prompt_ids if 0 <= t < self._vocab]
        pmask[ids] = True
        return bias, pmask

    def _request_extras_row(self, request: GenRequest):
        """Single-row extras for admission (first-token) sampling."""
        bias, pmask = self._bias_pmask_rows(request)
        seed = -1 if request.seed is None else int(request.seed) & 0x7FFFFFFF
        extras = SamplingExtras(
            presence=jnp.asarray([request.presence_penalty], jnp.float32),
            frequency=jnp.asarray([request.frequency_penalty], jnp.float32),
            repetition=jnp.asarray(
                [request.repetition_penalty or 1.0], jnp.float32
            ),
            bias=jnp.asarray(bias[None]),
            seeds=jnp.asarray([seed], jnp.int32),
            counters=jnp.zeros((1,), jnp.int32),
            min_new=jnp.asarray(
                [min(max(0, int(request.min_tokens or 0)), 2**31 - 1)],
                jnp.int32,
            ),
            stop=jnp.asarray(self._request_stop_row(request)[None]),
        )
        return (
            extras,
            jnp.zeros((1, self._vocab), jnp.int32),
            jnp.asarray(pmask[None]),
        )

    def check_admission(self, request: GenRequest, reserve: int = 0) -> None:
        """Load shedding: raise a structured 429/503 error instead of
        queueing a request the engine cannot serve in time. Streaming
        callers MUST run this before sending response headers (generate()
        re-checks at submission). ``reserve``: sibling requests the caller
        will submit ahead of this one (an n-choice batch pre-checks all n
        against one queue snapshot — without the reservation, the batch's
        own earlier submissions could shed the later ones mid-SSE)."""
        if self._stopped:
            raise EngineUnavailableError("engine is stopped")
        tot = (
            request.total_timeout
            if request.total_timeout is not None
            else self._total_timeout
        )
        if tot is not None and tot <= 0:
            # an already-expired budget fails fast, before any queueing —
            # this is also the pre-headers 408 path for streaming clients
            self.counters["deadline_total"] += 1
            raise DeadlineExceededError(
                "request budget {}s already elapsed at submission".format(tot),
                stage="total",
            )
        cls = (
            request.priority
            if request.priority in PRIORITY_CLASSES
            else "interactive"
        )
        self._update_brownout()
        try:
            faults.fire("engine.admit", request=request)
        except faults.InjectedFault as ex:
            self._count_shed("queue", cls)
            raise EngineOverloadedError(
                "admission shed (injected): {}".format(ex),
                retry_after=self._retry_after_hint(),
                shed_class=cls,
            ) from ex
        try:
            # class-aware admission seam: chaos forces a class-policy shed
            # regardless of queue state
            faults.fire("engine.admit.class", request=request)
        except faults.InjectedFault as ex:
            self._count_shed("class", cls)
            raise EngineOverloadedError(
                "admission shed by class policy (injected): {}".format(ex),
                retry_after=self._retry_after_hint(),
                shed_class=cls,
            ) from ex
        if (
            self._brownout is not None
            and self._brownout.stage >= 3
            and cls == "best_effort"
        ):
            # deepest brownout stage: best-effort traffic sheds at the door
            # so interactive + batch keep the engine's remaining headroom
            self._count_shed("brownout", cls)
            raise EngineOverloadedError(
                "brownout stage {}: best-effort traffic shed".format(
                    self._brownout.stage
                ),
                retry_after=self._retry_after_hint(),
                shed_class=cls,
            )
        if (
            self.max_pending is not None
            and self._pending.qsize() + reserve >= self.max_pending
        ):
            # class-aware shedding: evict a strictly-lower-class queued
            # request (best-effort first, then batch) to make room for a
            # higher-class arrival; only a queue with nothing lower sheds
            # the arrival itself
            victim = self._pending.shed_lowest(cls)
            if victim is not None:
                self._release_resume_pin(victim)
                self._count_shed("queue", victim.priority)
                victim.error = EngineOverloadedError(
                    "shed from the queue by a higher-priority admission",
                    retry_after=self._retry_after_hint(),
                    shed_class=victim.priority,
                )
                victim.cancelled = True  # admission pop skips it
                victim.out_queue.put_nowait(_FINISHED)
            else:
                self._count_shed("queue", cls)
                raise EngineOverloadedError(
                    "pending queue full ({} waiting, bound {})".format(
                        self._pending.qsize() + reserve, self.max_pending
                    ),
                    retry_after=self._retry_after_hint(),
                    shed_class=cls,
                )
        # KV-pool headroom: only enforced when admission control is
        # configured (max_pending set) — with unbounded admission the
        # historical queue-until-pages-free behavior stands
        if self.max_pending is not None and self.paged_cache is not None:
            pool = self.paged_cache.pool
            need_tokens = len(request.prompt_ids) + 1
            if self._prefix is not None:
                # a cached prefix maps in by reference — only the tail needs
                # fresh pages; without this, the shedder would reject exactly
                # the cheap shared-prefix requests the cache accelerates
                need_tokens -= self._prefix.match_len(
                    request.prompt_ids, self._slot_lora(request)
                )
            saturated = not pool.can_allocate(need_tokens)
            try:
                faults.fire("engine.pool", request=request)
            except faults.InjectedFault:
                saturated = True
            if saturated:
                self._count_shed("pool", cls)
                raise EngineOverloadedError(
                    "kv page pool saturated ({} free pages)".format(
                        pool.free_pages
                    ),
                    retry_after=self._retry_after_hint(),
                    shed_class=cls,
                )

    def _count_shed(self, reason: str, cls: str) -> None:
        """Book one shed under both the legacy totals (sheds_queue /
        sheds_pool) and the per-(reason, class) table backing
        ``engine_sheds_total{reason,class}``."""
        if reason == "pool":
            self.counters["sheds_pool"] += 1
        else:
            self.counters["sheds_queue"] += 1
        per = self._class_sheds.setdefault(reason, {})
        per[cls] = per.get(cls, 0) + 1

    def _retry_after_hint(self, ahead: Optional[int] = None) -> float:
        """Seconds until the queue has likely drained enough for a retry to
        land, derived from the OBSERVED admission drain rate (commits/s over
        the recent window) instead of a constant: hint = (depth ahead + 1) /
        rate, clamped to [0.5, 60]. With no drain observed yet the fallback
        still grows with depth, so deep queues never advertise a 1 s retry."""
        if ahead is None:
            ahead = self._pending.qsize()
        times = self._admit_times
        rate = None
        if len(times) >= 2:
            # anchor the span at NOW, not at the last commit: a wedged loop
            # would otherwise advertise the rate of a historical burst
            # forever, inviting clients to hammer a non-draining engine
            span = time.monotonic() - times[0]
            if span > 0:
                rate = (len(times) - 1) / span
        if rate:
            hint = (ahead + 1) / rate
        else:
            hint = 1.0 + 0.25 * ahead
        return min(60.0, max(0.5, hint))

    # -- brownout controller (docs/slo_scheduling.md) ---------------------

    def _pressure_score(self) -> tuple:
        """(score, signals): overload pressure in [0, ~2] as the max over
        queue depth vs the admission bound, paged-pool occupancy, and the
        deadline-hit / watchdog rates over a sliding ~5 s window."""
        signals: Dict[str, float] = {}
        if self.max_pending:
            signals["queue"] = min(
                2.0, self._pending.qsize() / float(self.max_pending)
            )
        if self.paged_cache is not None:
            pool = self.paged_cache.pool
            usable = max(1, pool.num_pages - 1)  # page 0 is the null page
            headroom = pool.free_pages
            if self._prefix is not None:
                # budget-retained prefix-cache pages are reclaimable on
                # demand (leaf-LRU eviction frees them when allocation
                # needs room): counting them as occupancy would read a
                # warm-but-idle cache as permanent overload and pin the
                # brownout stage high with zero traffic. (Transiently
                # optimistic about pinned preempted-history runs, which
                # unpin at their resume's admission.)
                headroom += self._prefix.cached_pages
            signals["pool"] = max(0.0, (usable - headroom) / usable)
        c = self.counters
        deadlines = (
            c["deadline_queue"] + c["deadline_ttft"] + c["deadline_total"]
        )
        now = time.monotonic()
        win = self._pressure_window
        if win is not None:
            d_dead = deadlines - win[1]
            d_trips = c["watchdog_trips"] - win[2]
            d_admit = self._admit_count - win[3]
            if d_dead + d_admit >= 4:
                # minimum-volume floor: one expired request against zero
                # admissions is a ratio of 1.0 — a single misbehaving
                # client (e.g. submitting already-elapsed budgets) must
                # not slam an idle engine into stage-3 brownout
                signals["deadline"] = d_dead / float(d_dead + d_admit)
            if d_trips > 0:
                signals["watchdog"] = 1.0
        if win is None or now - win[0] >= 5.0:
            self._pressure_window = (
                now, deadlines, c["watchdog_trips"], self._admit_count
            )
        return max(signals.values(), default=0.0), signals

    def _update_brownout(self) -> None:
        """Feed the pressure score into the brownout controller (throttled;
        called from the loop top and from check_admission so the stage stays
        live even while the loop sits in a long chunk) and apply the
        stage's side effects that live outside the hot path."""
        controller = self._brownout
        if controller is None:
            return
        now = time.monotonic()
        if now - self._brownout_checked < 0.1:
            return
        self._brownout_checked = now
        score, signals = self._pressure_score()
        prev = controller.stage
        stage = controller.update(score, signals, now)
        if stage != prev and self._prefill_gate is not None:
            # stage 3 shrinks the prefill admission budget to one segment
            # per decode chunk; dropping below restores the configured value
            self._prefill_gate.set_budget(1 if stage >= 3 else None)

    def _brownout_snapshot(self) -> Optional[dict]:
        if self._brownout is None:
            return None
        return {
            "stage": self._brownout.stage,
            "score": round(self._brownout.score, 4),
            "signals": {
                k: round(v, 4) for k, v in self._brownout.signals.items()
            },
        }

    def _effective_max_new(self, request: GenRequest) -> int:
        """Brownout stage >= 2 caps batch-lane generation length so long
        batch decodes release their slots early; the cap lifts with the
        stage (a capped request already past the cap finishes at its next
        emission)."""
        if (
            self._brownout is not None
            and self._brownout.stage >= 2
            and request.priority != "interactive"
        ):
            return min(request.max_new_tokens, self._brownout_batch_cap)
        return request.max_new_tokens

    # -- preemptible batch lane (docs/slo_scheduling.md) ------------------

    def _maybe_preempt(self) -> None:
        """Loop-thread, chunk boundary: under slot pressure with interactive
        work queued, preempt batch-lane slots — one per queued interactive
        request that has no free slot waiting for it. Each victim's
        generated-so-far KV is committed into the radix prefix cache by page
        reference first, so its re-admission replays the whole history with
        near-zero prefill; the freed slots go through the normal
        quarantine/pipeline-barrier machinery before reuse."""
        if not self._preempt:
            return
        want = self._pending.waiting("interactive")
        if want <= 0:
            return
        # quarantined-but-unowned slots count as free HERE: they become
        # admissible the moment their pipeline barrier retires (within one
        # chunk), and preempting another batch slot because the one just
        # freed hasn't cleared quarantine yet would double-preempt per
        # interactive arrival at pipeline depth >= 2
        free = sum(
            1
            for i, r in enumerate(self._slot_req)
            if r is None and i not in self._admitting
        )
        need = want - free
        while need > 0:
            victim_slot = None
            victim_key = None
            for slot, request in enumerate(self._slot_req):
                if request is None or request.priority == "interactive":
                    continue
                if request.cancelled or request.produced < 1:
                    continue
                if request._preempt_count >= self._preempt_budget:
                    continue  # budget exhausted: immune (starvation floor)
                # resume replays through a fresh prefill of prompt+generated:
                # exact only for plain sampling — grammar states, penalties,
                # seeds-with-counters and logprob streams do not survive the
                # round trip, so those slots are never victims
                if request.guided is not None or self._gstate[slot] >= 0:
                    continue
                if (
                    self._request_has_extras(request)
                    or request.logprobs is not None
                ):
                    continue
                key = (
                    _CLASS_RANK[request.priority],      # lowest class first
                    request._deadline
                    if request._deadline is not None
                    else float("inf"),                   # latest deadline
                    -request.produced,                   # least progress
                )
                if victim_key is None or key > victim_key:
                    victim_slot, victim_key = slot, key
            if victim_slot is None or not self._preempt_slot(victim_slot):
                return
            need -= 1

    def _preempt_slot(self, slot: int) -> bool:
        """Preempt the batch-lane request in ``slot`` at a chunk boundary:
        commit its generated-so-far KV into the radix prefix cache, free the
        slot (quarantined while in-flight chunks still reference it), and
        requeue the request with its full token history as the resume
        prompt. The consumer's stream is untouched — resume continues
        emitting into the same out_queue. Returns False when an injected
        ``engine.preempt`` fault aborted the preemption (nothing leaks: the
        radix store alone is the same store every admission commit runs)."""
        request = self._slot_req[slot]
        if request is None:
            return False
        history = list(request.prompt_ids) + [int(t) for t in request._gen_ids]
        if self.paged_cache is not None and self._prefix is not None:
            # store the final-KV prefix by reference to this slot's pages.
            # Only block-aligned WHOLE pages are stored and the stored run
            # ends at/below len(history)-1 — the last emitted token's KV is
            # not written yet, and in-flight chunks only write at/after it,
            # so every stored page is immutable from here on.
            self._prefix.store_pages(
                history, self._slot_lora(request),
                self.paged_cache.pool.slot_pages(slot),
            )
        try:
            faults.fire("engine.preempt", request=request)
        except faults.InjectedFault:
            # chaos seam, mid-commit: a failure here ABORTS the preemption.
            # The request keeps decoding in its slot; the radix store above
            # is identical to a normal admission-commit store (refcounted,
            # CoW-protected), so no page leaks and no state is torn.
            return False
        self.counters["preemptions"] += 1
        request._preempt_count += 1
        request.prompt_ids = history
        request._gen_ids = []
        if self._prefix is not None and self.paged_cache is not None:
            # hold the stored run against eviction until the resume's
            # lookup: the whole point of the commit above is a near-zero
            # prefill on re-admission, and queue-time pool pressure must
            # not LRU it away (the resume would then recompile a fresh
            # full-length prefill on the serving loop). A prior leg's pin
            # is impossible here: it was released at this leg's admission
            with lifecycle_ledger.owner(
                lifecycle_ledger.request_tag(request)
            ):
                request._resume_pin = self._prefix.pin_run(
                    history, self._slot_lora(request)
                )
        # the queue-wait budget restarts for the resume leg: the request
        # already proved admissible once, and expiring it for time spent
        # GENERATING would punish the preempted class twice
        qt = (
            request.queue_timeout
            if request.queue_timeout is not None
            else self._queue_timeout
        )
        request._queue_deadline = (
            time.monotonic() + qt if qt is not None else None
        )
        self._slot_req[slot] = None
        self._release_guided(slot, request)  # no-op for victims; kept for symmetry
        self._free_slot_pages(slot)
        self._pending.put_nowait(request)
        self._wake_loop()
        return True

    def _resolve_deadlines(self, request: GenRequest) -> None:
        """Pin the request's monotonic deadlines at submission (per-request
        budgets override the engine defaults)."""
        now = time.monotonic()
        qt = (
            request.queue_timeout
            if request.queue_timeout is not None
            else self._queue_timeout
        )
        tt = (
            request.ttft_timeout
            if request.ttft_timeout is not None
            else self._ttft_timeout
        )
        tot = (
            request.total_timeout
            if request.total_timeout is not None
            else self._total_timeout
        )
        request._queue_deadline = now + qt if qt is not None else None
        request._ttft_deadline = now + tt if tt is not None else None
        request._deadline = now + tot if tot is not None else None

    async def generate(self, request: GenRequest) -> AsyncIterator[int]:
        """Submit a request; yields sampled token ids as they decode."""
        if self._stopped:
            raise EngineUnavailableError("engine is stopped")
        self.validate(request)
        self.check_admission(request)
        self._resolve_deadlines(request)
        request.prompt_len = len(request.prompt_ids)
        request.out_queue = asyncio.Queue()
        self._pending.put_nowait(request)
        self._ensure_loop()
        self._wake_loop()
        try:
            while True:
                token = await request.out_queue.get()
                if token is _FINISHED:
                    if request.error is not None:
                        raise request.error
                    return
                yield token
        finally:
            # consumer stopped early (client disconnect / generator close):
            # flag the request so the engine frees its slot and pages instead
            # of decoding to max_new_tokens for nobody. No-op after a normal
            # finish (the slot is already free).
            request.cancelled = True

    def stop(self) -> None:
        """Stop the loop and fail out every active/pending request (their
        consumers must never hang on a dead engine). A request mid-admission is
        caught by the loop's post-exit drain (_run_loop's stopped check)."""
        self._stopped = True
        err = EngineUnavailableError("engine stopped")
        self._fail_all(err)
        for request in self._pending.pop_all():
            self._release_resume_pin(request)
            request.error = err
            request.out_queue.put_nowait(_FINISHED)
        self._wake_loop()  # unblock an idle loop so its cleanup runs

    @property
    def active_slots(self) -> int:
        return sum(1 for r in self._slot_req if r is not None)

    async def wait_drained(self, timeout: float = 30.0) -> None:
        """Await the decode loop going fully idle (loop task returned: no
        active slots, no in-flight pipeline chunks, no admissions). Under
        the pipelined loop a consumer can see its last token while younger
        chunks are still in flight — page accounting is only FINAL at
        drain, so tests/ops code that audits the pool should await this."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            task = self._loop_task
            if task is None or task.done():
                return
            await asyncio.sleep(0.005)
        raise TimeoutError("engine loop did not drain within {}s".format(timeout))

    @property
    def is_ready(self) -> bool:
        """Liveness signal for the HTTP /ready endpoint: False while the
        engine is stopped or the watchdog is mid-recovery."""
        return not self._stopped and not self._recovering

    def _kv_pool_snapshot(self):
        """Paged-pool capacity block shared by health() and
        lifecycle_stats() (docs/paged_kv_quant.md): bytes split by kind so
        the int8 win shows up on a dashboard. None on the dense backend."""
        if self.paged_cache is None:
            return None
        return dict(
            self.paged_cache.pool_bytes(),
            dtype=self.paged_cache.pool_dtype,
            num_pages=self.paged_cache.pool.num_pages,
            page_size=self.paged_cache.pool.page_size,
        )

    def _reap_promotions(self, force: bool = False) -> None:
        """Loop-thread: retire-stage observation of completed host-tier
        promotion DMAs (docs/kv_tiering.md). A no-op without a host tier;
        ``force`` blocks on stragglers (drain/stop)."""
        pc = self.paged_cache
        if pc is None or (pc.host_tier is None and self._kv_transport is None):
            # transport imports ride the same promotion-fence records as
            # host-tier re-onlines, so a transport-attached engine reaps
            # even without a host tier (docs/disaggregation.md)
            return
        reaped = pc.reap_promotions(force=force)
        if reaped:
            self._tier_counters["reaps"] += reaped

    def _kv_tier_snapshot(self):
        """Host-tier capacity/movement block shared by health() and
        lifecycle_stats() (docs/kv_tiering.md). None when no tier."""
        pc = self.paged_cache
        if pc is None or pc.host_tier is None:
            return None
        backend = pc.tier_stats()
        prefix = self._prefix.stats() if self._prefix is not None else {}
        page_bytes = sum(pc.pool_bytes().values()) // pc.pool.num_pages
        return {
            "pages": {
                "hbm": prefix.get("cached_pages", 0),
                "host": prefix.get("host_pages", 0),
            },
            "bytes": {
                "hbm": prefix.get("cached_bytes", 0),
                "host": prefix.get("host_bytes", 0),
            },
            "nodes": {
                "hbm": (
                    prefix.get("nodes", 0) - prefix.get("host_nodes", 0)
                ),
                "host": prefix.get("host_nodes", 0),
            },
            "demotions": prefix.get("demotions", 0),
            "promotions": prefix.get("promotions", 0),
            "hits_by_tier": prefix.get("hits_by_tier", {}),
            "host_pages_capacity": backend["host_pages_capacity"],
            "host_pages_used": backend["host_pages_used"],
            "demoted_pages_total": backend["demoted_pages_total"],
            "promoted_pages_total": backend["promoted_pages_total"],
            "promo_overlap_ratio": backend["overlap_ratio"],
            "promo_wait_ms": backend["promo_wait_ms"],
            "promo_total_ms": backend["promo_total_ms"],
            "reaps": self._tier_counters["reaps"],
            "page_bytes": page_bytes,
        }

    # -- disaggregated prefill/decode (docs/disaggregation.md) -------------

    def attach_kv_transport(self, endpoint, role: str = "hybrid") -> None:
        """Wire a KV-transport endpoint (llm/kv_transport.py) and this
        replica's role into the engine. Called by the replica group at
        construction; requires the paged backend with a prefix cache —
        the shipment payload IS the radix-storable prefix."""
        if role not in ("prefill", "decode", "hybrid"):
            raise ValueError(
                "replica role must be prefill/decode/hybrid: got {!r}"
                .format(role)
            )
        if endpoint is not None and (
            self.cache_mode != "paged" or self._prefix is None
        ):
            raise ValueError(
                "KV transport needs cache_mode='paged' and a prefix_cache "
                "(the shipment payload is the radix-storable prefix; "
                "docs/disaggregation.md)"
            )
        self._kv_transport = endpoint
        self.replica_role = role

    def _maybe_ship_draft(self, job) -> None:
        """Draft-ahead KV shipping (loop thread; docs/spec_decode_trees.md):
        at a ragged prefill chunk boundary, the job's newly-FINAL storable
        pages export into an unsealed partial shipment — the transport
        overlaps the remaining prefill compute instead of serializing
        behind the commit. Always holds back the last storable page so the
        commit-time seal (:meth:`_maybe_ship`) carries real tail pages.
        Best-effort by contract: an injected ``kv.ship.partial`` fault, a
        real export/send failure, or a transport drop ABORTS the job's
        whole draft-ahead stream and skips the seal — the receiver's
        unsealed assembly is never consumable, so the decode replica falls
        back to recompute with zero page leaks on either side."""
        request = job.request
        dst = request._ship_to
        endpoint = self._kv_transport
        if not dst or endpoint is None or self.paged_cache is None \
                or self._prefix is None:
            return
        ids = request.prompt_ids
        storable = self._prefix.longest_prefix_len(len(ids))
        if storable < self._prefix.block:
            return
        page_size = self.paged_cache.pool.page_size
        state = self._kv_draft_ahead.get(job.slot)
        if state is not None and state["aborted"]:
            return
        # whole pages the prefilled prefix now covers, minus the held-back
        # tail page (the seal's payload)
        n_pages = min(
            min(job.pos, storable) // page_size,
            storable // page_size - 1,
        )
        offset = state["offset"] if state is not None else 0
        if n_pages <= offset:
            return
        from .kv_transport import KVShipment, shipment_key

        lora = self._slot_lora(request)
        pages = self.paged_cache.pool.slot_pages(job.slot)[offset:n_pages]
        if state is None:
            state = self._kv_draft_ahead[job.slot] = {
                "offset": 0, "aborted": False,
            }
        try:
            faults.fire("kv.ship.partial", request=request)
            slabs = self.paged_cache.export_pages(pages)
            sent = endpoint.send(dst, KVShipment(
                key=shipment_key(ids, self._prefix.block, lora),
                src=self.replica_id or "r?",
                prefix_len=n_pages * page_size,
                page_size=page_size,
                lora=lora,
                hk=slabs["hk"], hv=slabs["hv"],
                hk_scale=slabs.get("hk_scale"),
                hv_scale=slabs.get("hv_scale"),
                page_offset=offset, final=False,
            ))
        except faults.InjectedFault:
            state["aborted"] = True
            self._kv_ship_stats["draft_aborts"] += 1
            return
        except Exception as ex:  # noqa: BLE001 - best-effort by contract
            state["aborted"] = True
            self._kv_ship_stats["draft_aborts"] += 1
            logger.warning(
                "draft-ahead kv ship to %s aborted (%s: %s); decode-side "
                "recompute", dst, type(ex).__name__, ex,
            )
            return
        if not sent:
            state["aborted"] = True
            self._kv_ship_stats["draft_aborts"] += 1
            return
        state["offset"] = n_pages
        self._kv_ship_stats["draft_ships"] += 1
        self._kv_ship_stats["draft_pages"] += n_pages - offset

    def _maybe_ship(self, request: GenRequest, slot: int) -> None:
        """Ship-at-commit (loop thread): export the just-committed
        admission's block-aligned prefix pages into a KV-transport
        shipment addressed to ``request._ship_to`` (docs/disaggregation.md).
        When draft-ahead shipping already streamed the prefix head
        (:meth:`_maybe_ship_draft`), only the TAIL pages ship here as the
        sealing final frame; an aborted draft-ahead stream skips the seal
        outright (the unsealed assembly must stay unconsumable).
        Best-effort by contract — an injected ``engine.kv.ship`` fault or
        a full receive slab drops the shipment and the decode replica
        recomputes; nothing here can fail the request."""
        state = self._kv_draft_ahead.pop(slot, None)
        dst = request._ship_to
        endpoint = self._kv_transport
        if not dst or endpoint is None or self.paged_cache is None \
                or self._prefix is None:
            return
        if state is not None and state["aborted"]:
            # the partial stream died mid-flight: sealing now could attach
            # a prefix we cannot prove contiguous — drop to recompute
            self._kv_ship_stats["ship_drops"] += 1
            return
        offset = state["offset"] if state is not None else 0
        from .kv_transport import KVShipment, shipment_key

        ids = request.prompt_ids
        prefix_len = self._prefix.longest_prefix_len(len(ids))
        if prefix_len < self._prefix.block:
            return
        t0 = time.perf_counter()
        lora = self._slot_lora(request)
        n_pages = prefix_len // self.paged_cache.pool.page_size
        pages = self.paged_cache.pool.slot_pages(slot)[offset:n_pages]
        try:
            faults.fire("engine.kv.ship", request=request)
            slabs = self.paged_cache.export_pages(pages)
            sent = endpoint.send(dst, KVShipment(
                key=shipment_key(ids, self._prefix.block, lora),
                src=self.replica_id or "r?",
                prefix_len=prefix_len,
                page_size=self.paged_cache.pool.page_size,
                lora=lora,
                hk=slabs["hk"], hv=slabs["hv"],
                hk_scale=slabs.get("hk_scale"),
                hv_scale=slabs.get("hv_scale"),
                page_offset=offset, final=True,
            ))
        except faults.InjectedFault:
            self._kv_ship_stats["ship_drops"] += 1
            return
        except Exception as ex:  # noqa: BLE001 - ship is best-effort by contract
            # a REAL export/send failure (e.g. MemoryError staging the
            # host slabs) must degrade exactly like an injected one:
            # dropped + counted, never a failed commit on the loop thread
            self._kv_ship_stats["ship_drops"] += 1
            logger.warning(
                "kv ship to %s dropped (%s: %s); decode-side recompute",
                dst, type(ex).__name__, ex,
            )
            return
        if not sent:
            self._kv_ship_stats["ship_drops"] += 1
            return
        self._kv_ship_stats["ships"] += 1
        # ship_pages counts the WHOLE prefix (head pages rode the draft
        # frames): the overlap gauge divides draft_pages by it, and page
        # accounting stays comparable with the single-frame path
        self._kv_ship_stats["ship_pages"] += n_pages
        self._hist_ship_ms.observe((time.perf_counter() - t0) * 1e3)

    def receive_shipment(self, prompt_ids: List[int], lora: int = 0) -> dict:
        """Receive-and-promote (docs/disaggregation.md): pop the shipment
        for this prompt's prefix from the transport receive slab and
        re-online it through the promote-under-dispatch-lock fence —
        fresh device pages, the async host→device scatter ENQUEUED before
        the page ids publish, the radix-cache attach last
        (prefix_cache.store_shipped). The next admission's prefix lookup
        then hits the shipped run.

        Called by the replica group off the event loop (any thread is
        safe: the tree lock and dispatch lock serialize against the
        serving loop). Returns ``{"status": "imported"|"empty"|"failed"|
        "off", "pages": n}`` — ``failed`` (injected ``engine.kv.receive``
        fault, pool pressure, geometry mismatch) drops the shipment with
        zero page leaks; the group then re-routes the stream to a
        hybrid-capable sibling."""
        endpoint = self._kv_transport
        if endpoint is None or self.paged_cache is None \
                or self._prefix is None:
            return {"status": "off", "pages": 0}
        from .kv_transport import shipment_key

        key = shipment_key(prompt_ids, self._prefix.block, lora)
        shipment = endpoint.recv(key)
        if shipment is None:
            self._kv_ship_stats["receive_empty"] += 1
            return {"status": "empty", "pages": 0}
        t0 = time.perf_counter()
        try:
            faults.fire(
                "engine.kv.receive",
                request=_ShipShim(prompt_ids),
            )
            pages = self._prefix.store_shipped(
                prompt_ids, lora, shipment, self.paged_cache
            )
        except (faults.InjectedFault, MemoryError, ValueError) as ex:
            # the shipment's slabs are plain host memory: dropping the
            # reference IS the cleanup (no pool pages were published)
            self._kv_ship_stats["receive_failures"] += 1
            return {"status": "failed", "pages": 0, "error": repr(ex)[:200]}
        self._kv_ship_stats["receives"] += 1
        self._kv_ship_stats["receive_pages"] += pages
        self._hist_receive_ms.observe((time.perf_counter() - t0) * 1e3)
        return {"status": "imported", "pages": pages}

    def _count_ship_outcome(self, request: GenRequest) -> None:
        """Admission-time ship accounting on the decode replica: a request
        the group marked ``_shipped`` either finds its whole storable
        prefix resident (ship HIT — it recomputes none of the shipped KV)
        or recomputes (transport drop, eviction, receive failure). The
        hit-rate gauge is the disaggregation headline
        (engine_kv_ship_hit_rate; benchmarks/DISAGG_AB_cpu.json asserts
        >= 0.9 on the clean path). One-shot per request."""
        if not request._shipped or self._prefix is None:
            return
        request._shipped = False
        ids = request.prompt_ids
        storable = self._prefix.longest_prefix_len(len(ids))
        lora = self._slot_lora(request)
        if storable >= self._prefix.block and (
            self._prefix.match_len(ids, lora) >= storable
        ):
            self._kv_ship_stats["hits"] += 1
        else:
            self._kv_ship_stats["recomputes"] += 1

    def _kv_ship_snapshot(self):
        """KV-transport movement block shared by health() and
        lifecycle_stats() (docs/disaggregation.md). None when no
        transport is attached."""
        if self._kv_transport is None:
            return None
        s = self._kv_ship_stats
        judged = s["hits"] + s["recomputes"]
        return {
            "role": self.replica_role,
            "ships": s["ships"],
            "ship_pages": s["ship_pages"],
            "ship_drops": s["ship_drops"],
            "receives": s["receives"],
            "receive_pages": s["receive_pages"],
            "receive_empty": s["receive_empty"],
            "receive_failures": s["receive_failures"],
            "hits": s["hits"],
            "recomputes": s["recomputes"],
            "hit_rate": (
                round(s["hits"] / judged, 4) if judged else None
            ),
            "draft_ships": s["draft_ships"],
            "draft_pages": s["draft_pages"],
            "draft_aborts": s["draft_aborts"],
            # share of shipped prefix pages that overlapped the prefill
            # tail instead of serializing behind the commit
            # (engine_kv_ship_overlap_ratio; docs/spec_decode_trees.md)
            "overlap_ratio": (
                round(s["draft_pages"] / s["ship_pages"], 4)
                if s["ship_pages"] else 0.0
            ),
            "ship_ms": self._hist_ship_ms.snapshot(),
            "receive_ms": self._hist_receive_ms.snapshot(),
            "transport": self._kv_transport.stats(),
        }

    def health(self) -> dict:
        out = {
            "ready": self.is_ready,
            "stopped": self._stopped,
            "recovering": self._recovering,
            "active_slots": self.active_slots,
            "queue_depth": self._pending.qsize(),
            "queue_depths": self._pending.depths(),
            "preemptions": self.counters["preemptions"],
            "brownout": self._brownout_snapshot(),
            "watchdog_trips": self.counters["watchdog_trips"],
            "step_failures": self.counters["step_failures"],
            "pipeline": {
                "depth": self.pipeline_depth,
                "inflight": len(self._inflight),
            },
            "scheduler": "ragged" if self._ragged else "two_dispatch",
            "ragged": (
                {
                    "step_token_budget": self._step_token_budget,
                    "effective_budget": self._effective_token_budget(),
                    "prefill_jobs": len(self._prefill_jobs),
                    "steps": self.counters["ragged_steps"],
                    "decode_steps": self._ragged_decode_steps,
                    "decode_tokens": self.counters["ragged_decode_tokens"],
                }
                if self._ragged
                else None
            ),
            "kv_pool": self._kv_pool_snapshot(),
            "kv_tier": self._kv_tier_snapshot(),
            "kv_ship": self._kv_ship_snapshot(),
            "weights": {
                "quant": self.weight_quant or "none",
                "bytes": self._weight_bytes,
            },
            "compile": self._compile_snapshot(),
            "ledger": self._ledger_snapshot(),
            "sharding": self._shard_snapshot(),
            # certificate block like compile/ledger/sharding: None when
            # unarmed. Needed over the process-backend health RPC — the
            # parent cannot reach a worker engine's _sanitizer directly
            "sanitizer": (
                self._sanitizer.stats()
                if self._sanitizer is not None else None
            ),
        }
        if self.replica_id is not None:
            out["replica"] = self.replica_id
        return out

    def _ledger_snapshot(self):
        """Ownership-ledger block shared by health() and lifecycle_stats()
        (docs/static_analysis.md TPU7xx). None when the ledger is unarmed.
        The ledger is process-wide (co-hosted replica engines record into
        one), so counters are fleet totals — per-entry attribution lives
        in the owner/site records, not the counters."""
        if self._ledger is None:
            return None
        return self._ledger.stats()

    def _compile_snapshot(self):
        """Compile-sentry block shared by health() and lifecycle_stats()
        (docs/static_analysis.md TPU6xx). None when the sentry is unarmed.
        The sentry is process-wide (the compile hook surface is global), so
        co-hosted engines report the same counters — attribution lives in
        the per-event context, not the counters."""
        if self._compile_sentry is None:
            return None
        return self._compile_sentry.stats_brief()

    def _shard_snapshot(self):
        """Sharding-sentry block shared by health() and lifecycle_stats()
        (docs/static_analysis.md TPU8xx). None when the sentry is unarmed.
        The sentry is process-wide (co-hosted engines audit into one spec
        table under per-engine path prefixes), so counters are fleet
        totals — per-violation attribution lives in the event records."""
        if self._shard_sentry is None:
            return None
        return self._shard_sentry.stats_brief()

    def lifecycle_stats(self) -> dict:
        """Scrape-time snapshot for statistics.metrics' lifecycle collector
        (counters monotonic; gauges instantaneous)."""
        c = self.counters
        out = {
            "queue_depth": self._pending.qsize(),
            "queue_depths": self._pending.depths(),
            "active_slots": self.active_slots,
            "ready": int(self.is_ready),
            "sheds": {"queue": c["sheds_queue"], "pool": c["sheds_pool"]},
            "sheds_by_class": {
                reason: dict(per)
                for reason, per in self._class_sheds.items()
            },
            "preemptions": c["preemptions"],
            "brownout": self._brownout_snapshot(),
            "deadlines": {
                "queue": c["deadline_queue"],
                "ttft": c["deadline_ttft"],
                "total": c["deadline_total"],
            },
            "watchdog_trips": c["watchdog_trips"],
            "step_failures": c["step_failures"],
            "pipeline": {
                "depth": self.pipeline_depth,
                "inflight": len(self._inflight),
                "dispatch_ms": self._hist_dispatch.snapshot(),
                "retire_ms": self._hist_retire.snapshot(),
            },
            # ragged token-budget scheduler (docs/ragged_attention.md):
            # per-step budget utilization + per-phase row counters backing
            # engine_step_token_budget_utilization / engine_step_rows
            "scheduler": "ragged" if self._ragged else "two_dispatch",
            "ragged": (
                {
                    "step_token_budget": self._step_token_budget,
                    "effective_budget": self._effective_token_budget(),
                    "prefill_jobs": len(self._prefill_jobs),
                    "steps": self.counters["ragged_steps"],
                    "budget_utilization": self._hist_budget.snapshot(),
                    "step_rows": dict(self._step_rows),
                    # multi-step decode rows + spec-as-row
                    # (docs/ragged_attention.md): decode tokens advanced
                    # per launch and the per-launch draft acceptance —
                    # launches/decode_tokens is dispatches-per-decode-token
                    "decode_steps": self._ragged_decode_steps,
                    "decode_tokens": self.counters["ragged_decode_tokens"],
                    "tokens_per_launch": self._hist_launch_tokens.snapshot(),
                    "spec_acceptance": self._hist_spec_accept.snapshot(),
                    # draft-tree verify rows (docs/spec_decode_trees.md):
                    # accepted path depth + pluggable-proposer hit counts
                    # (engine_spec_tree_accept_depth /
                    # engine_spec_proposer_hits_total in
                    # statistics/metrics.py)
                    "spec_tree_depth": (
                        self._hist_spec_tree_depth.snapshot()
                        if self._spec_tree
                        else None
                    ),
                    "spec_tree_fallbacks": (
                        self.counters["spec_tree_fallbacks"]
                    ),
                    "spec_proposer": (
                        dict(
                            self._spec_proposer.stats(),
                            name=self._spec_proposer.name,
                        )
                        if self._spec_proposer is not None
                        else None
                    ),
                }
                if self._ragged
                else None
            ),
            "kv_pool": self._kv_pool_snapshot(),
            "kv_tier": self._kv_tier_snapshot(),
            "kv_ship": self._kv_ship_snapshot(),
            "weights": {
                "quant": self.weight_quant or "none",
                "bytes": self._weight_bytes,
            },
            "compile": self._compile_snapshot(),
            "ledger": self._ledger_snapshot(),
            "sharding": self._shard_snapshot(),
        }
        if self.replica_id is not None:
            out["replica"] = self.replica_id
        return out

    @property
    def logprobs_k(self) -> int:
        """Public top-k ceiling for logprob reporting (OpenAI top_logprobs
        and vLLM prompt_logprobs validate against this)."""
        return self._lp_k

    # -- internals -------------------------------------------------------------

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(self._run_loop())
        if self._watchdog_interval and (
            self._watchdog_task is None or self._watchdog_task.done()
        ):
            self._watchdog_task = asyncio.get_running_loop().create_task(
                self._watchdog_loop()
            )

    # -- watchdog + supervised recovery ---------------------------------------

    async def _watchdog_loop(self) -> None:
        """Detects a stuck decode loop (no chunk progress within
        ``watchdog_interval`` while slots are active), fails ONLY the
        in-flight requests with a structured error, and arms the loop's
        epoch-based recovery so it reclaims state and keeps serving. Also
        sweeps queue-wait deadlines so queued requests expire even when the
        loop is wedged."""
        interval = float(self._watchdog_interval)
        tick = max(0.01, interval / 4.0)
        try:
            while not self._stopped:
                await asyncio.sleep(tick)
                self._expire_pending()
                if (
                    self._loop_task is None
                    or self._loop_task.done()
                    or self.active_slots == 0
                ):
                    # idle (or the loop drained between requests): nothing to
                    # supervise. Stay alive — exiting here would race
                    # _ensure_loop's done() check on the next request and
                    # leave that request unsupervised.
                    self._last_progress = time.monotonic()
                    continue
                disp = self._dispatching
                if disp is not None and (
                    time.monotonic() - disp[2] < 10.0 * interval
                ):
                    # a dispatch call is mid-flight in its worker thread:
                    # first-use XLA compiles run inside that call and can
                    # legitimately take many seconds (the serial loop hid
                    # this by blocking the event loop through the compile).
                    # The grace is BOUNDED at 10x the interval — a dispatch
                    # wedged past that (lock deadlock, hung inline backend)
                    # is a stall, not a compile; device hangs also surface
                    # at the retire sync, where no grace applies.
                    continue
                if time.monotonic() - self._last_progress > interval:
                    self._watchdog_trip(interval)
        except asyncio.CancelledError:
            return

    def _watchdog_trip(self, interval: float) -> None:
        if faults.active():
            # yield-point seam: a trip is about to bump the epoch and fail
            # the in-flight batch (chaos + interleaving-explorer boundary)
            faults.fire(
                "engine.watchdog",
                requests=[r for r in self._slot_req if r is not None],
            )
        self.counters["watchdog_trips"] += 1
        self._recovering = True
        self._recover_epoch += 1
        err = EngineStuckError(
            "decode loop made no progress for {:.1f}s; failing in-flight "
            "requests and recovering".format(interval)
        )
        for slot, request in enumerate(self._slot_req):
            if request is not None:
                request.error = err
                request.out_queue.put_nowait(_FINISHED)
                self._slot_req[slot] = None
                self._release_guided(slot, request)
                # pool pages deliberately NOT freed here: a worker thread may
                # be mutating the pool mid-dispatch; the loop reclaims them at
                # the next safe boundary (_finish_recovery)
        self._last_progress = time.monotonic()

    async def _finish_recovery(self) -> None:
        """After a stale-epoch dispatch returned (or raised): discard the
        whole in-flight pipeline, reclaim freed slots' pages and report
        ready again. DEFERRED while a dispatch worker is still mid-call —
        its device program may still be writing the very pages this would
        free; the dispatch leg (or the step-failure handler) completes
        recovery once it lands, and a dispatch wedged forever correctly
        keeps the engine not-ready instead of freeing pages under it."""
        if self._dispatching is not None:
            return
        await self._discard_pipeline()
        if self.paged_cache is not None:
            for slot in range(self.max_batch):
                if self._slot_req[slot] is None and slot not in self._admitting:
                    self.paged_cache.pool.free(slot)
        self._recovering = False
        self._last_progress = time.monotonic()

    def _fail_slot(self, slot: int, err: BaseException) -> None:
        """Fail one active request with a structured error and reclaim its
        slot/pages/grammar state. Loop-thread-only."""
        request = self._slot_req[slot]
        if request is None:
            return
        request.error = err
        request.out_queue.put_nowait(_FINISHED)
        self._slot_req[slot] = None
        self._release_guided(slot, request)
        self._free_slot_pages(slot)
        self._ledger_audit_request(request, "fail")

    # -- pipelined decode: slot-reuse barrier ---------------------------------

    def _pipeline_barrier(self, slot: int) -> Optional[int]:
        """Newest in-flight (or currently-dispatching) chunk that still
        decodes ``slot`` (None when the pipeline holds no reference)."""
        barrier = None
        for entry in self._inflight:
            if entry.active_mask[slot]:
                barrier = entry.seq
        disp = self._dispatching
        if disp is not None and disp[1][slot]:
            barrier = disp[0]
        return barrier

    def _free_slot_pages(self, slot: int) -> None:
        """Release a freed slot's KV pages — immediately when no in-flight
        chunk still references the slot, otherwise deferred to the retire of
        the newest chunk that does. Until then the slot is also quarantined
        against re-admission: a chunk dispatched before the slot was freed
        still writes its KV region / pages, and a new occupant would receive
        the dead request's leftover tokens at that chunk's retire."""
        barrier = self._pipeline_barrier(slot)
        if barrier is not None:
            self._quarantine_slot(slot, barrier)
            return
        if self.paged_cache is not None:
            self.paged_cache.pool.free(slot)

    def _quarantine_slot(self, slot: int, barrier: int) -> None:
        """Defer a freed slot's page release to the retire of in-flight
        chunk ``barrier`` (the declared acquire of the ``slot.quarantine``
        protocol: _release_quarantine / the pipeline-discard paths are its
        releases, and the ownership ledger audits the pairing — a slot
        stuck in quarantine at drain is a lost free). Loop-thread only."""
        self._quarantine[slot] = barrier
        if self._ledger is not None:
            lifecycle_ledger.acquire("slot.quarantine", key=slot,
                                     domain=self)

    def _release_quarantine(self, retired_seq: int) -> None:
        """Retire point: slots whose barrier has passed become reusable and
        their deferred page frees execute (loop-thread only)."""
        for slot, barrier in list(self._quarantine.items()):
            if barrier <= retired_seq:
                del self._quarantine[slot]
                if self._ledger is not None:
                    lifecycle_ledger.release("slot.quarantine", key=slot,
                                             domain=self, all_of_key=True)
                if (
                    self.paged_cache is not None
                    and self._slot_req[slot] is None
                    and slot not in self._admitting
                ):
                    self.paged_cache.pool.free(slot)

    async def _discard_pipeline(self) -> None:
        """Drop every in-flight chunk and the device-resident chains
        (watchdog recovery / batch-wide step failure: the queued results are
        stale or poisoned). Deferred frees execute now — after waiting out
        the discarded chunks' DEVICE work: an async-dispatched chunk may
        still be writing its slots' pages, and freeing them under that
        write would hand corrupted pages to the next admission (the same
        hazard the quarantine barrier covers on the normal path). The wait
        runs in a worker thread — blocking the event loop on a wedged
        device would freeze /ready, admissions and the watchdog itself.
        The host mirrors become the source of truth for the next dispatch."""
        dropped = list(self._inflight)
        self._inflight.clear()
        pending = list(self._quarantine)
        self._quarantine.clear()
        if self._ledger is not None:
            for slot in pending:
                lifecycle_ledger.release("slot.quarantine", key=slot,
                                         domain=self, all_of_key=True)
        self._reset_device_chains()
        if self.paged_cache is not None and dropped:
            await asyncio.to_thread(self._wait_chunks, dropped)
        for slot in pending:
            if (
                self.paged_cache is not None
                and self._slot_req[slot] is None
                and slot not in self._admitting
            ):
                self.paged_cache.pool.free(slot)

    @staticmethod
    def _wait_chunks(entries) -> None:
        """Worker-thread wait for discarded chunks' device programs (their
        pool writes complete with the same program that produces tokens)."""
        for entry in entries:
            try:
                if entry.chunk is not None:
                    jax.block_until_ready(entry.chunk)
            except Exception:
                pass  # failed execution: nothing more will be written

    def _reset_device_chains(self) -> None:  # tpuserve: ignore[TPU501] pipeline drained/discarded: no dispatch worker is live when the loop resets the chains
        """Forget the device-resident token/DFA chains; the next dispatch
        re-uploads from the host mirrors."""
        self._next_token_dev = None
        self._gstate_dev = None
        self._slot_overrides[:] = False

    async def _handle_step_failure(self, ex: BaseException, epoch: int) -> None:
        """A decode dispatch raised. Fail the affected request(s) and keep
        the loop alive — one poisoned step must not kill the engine."""
        if epoch != self._recover_epoch:
            # the watchdog already failed this batch while the dispatch was
            # stuck; nothing left to fail — just reclaim
            await self._finish_recovery()
            return
        if is_hbm_oom(ex):
            # device allocator poisoned: wrapping in a RequestError would
            # route this away from the router's crash-and-restart policy —
            # let the loop die with the ORIGINAL error (consumers see it
            # verbatim; the generic handler then os._exit(1)s the process)
            raise ex
        self.counters["step_failures"] += 1
        target = getattr(ex, "request", None)
        if target is not None:
            # per-request poison (fault injection / host-side attribution):
            # isolate the blast radius to that single request
            for slot, request in enumerate(self._slot_req):
                if request is target:
                    self._fail_slot(
                        slot,
                        EngineStepError(
                            "decode step failed for this request: {}".format(ex)
                        ),
                    )
                    break
            return
        # batch-wide failure: every in-flight request's device state is
        # suspect — discard the whole pipeline (queued chunks chain off the
        # poisoned buffers), fail all requests with a structured error, then
        # reset what the failed dispatch may have consumed (donated buffers)
        await self._discard_pipeline()
        err = EngineStepError("decode step failed: {}".format(ex))
        for slot, request in enumerate(self._slot_req):
            if request is not None:
                self._fail_slot(slot, err)
        if self._prefill_jobs:
            # a batch-wide ragged failure poisons the very launch the jobs'
            # chunks rode — their KV progress is suspect; fail them too
            self._abort_ragged_jobs(err)
        self._reset_device_state()
        self._last_progress = time.monotonic()

    def _reset_device_state(self) -> None:
        """Best-effort rebuild of donated-through device buffers after a
        failed dispatch (a jit error after donation leaves them deleted)."""
        try:
            if self.cache is not None and any(
                getattr(v, "is_deleted", lambda: False)()
                for v in self.cache.values()
            ):
                self.cache = self.bundle.init_cache(
                    self.max_batch, self.max_seq_len + self._cache_slack
                )
                if self._cache_sharding is not None:
                    self.cache = {
                        k: jax.device_put(v, self._cache_sharding[k])
                        for k, v in self.cache.items()
                    }
        except Exception:
            pass  # recovery is best-effort; the next dispatch surfaces it

    def _expire_pending(self) -> None:
        """Fail queued requests whose queue-wait or total deadline elapsed.
        Runs on the loop thread (each iteration) and from the watchdog (so
        queued requests expire even while the loop is wedged)."""
        queue = self._pending.requests()
        if not queue:
            return
        now = time.monotonic()
        for request in queue:
            if request.cancelled or request.error is not None:
                continue
            err = None
            if (
                request._queue_deadline is not None
                and now > request._queue_deadline
            ):
                self.counters["deadline_queue"] += 1
                err = DeadlineExceededError(
                    "request spent its queue-wait budget before admission",
                    stage="queue",
                )
            elif request._deadline is not None and now > request._deadline:
                self.counters["deadline_total"] += 1
                err = DeadlineExceededError(
                    "request budget elapsed while queued", stage="total"
                )
            if err is not None:
                request.error = err
                request.cancelled = True  # admission pop skips it
                request.out_queue.put_nowait(_FINISHED)

    def _deadline_error_at_commit(
        self, request: GenRequest
    ) -> Optional[BaseException]:
        """TTFT/total deadline check right before the slot commit (the
        prefill may have been slow or the ready queue backed up)."""
        now = time.monotonic()
        if (
            request._ttft_deadline is not None
            and request.first_token_at is None
            and now > request._ttft_deadline
        ):
            self.counters["deadline_ttft"] += 1
            return DeadlineExceededError(
                "no first token within the ttft budget", stage="ttft"
            )
        if request._deadline is not None and now > request._deadline:
            self.counters["deadline_total"] += 1
            return DeadlineExceededError(
                "request budget elapsed during admission", stage="total"
            )
        return None

    def _bucket_for(self, n: int) -> int:
        if faults.active():
            try:
                # chaos seam: SKIP the bucketizer — raw per-request lengths
                # become prefill compile keys, the exact shape-drift defect
                # the compile sentry exists to catch (its self-test arms
                # this point and proves the post-fence compile is caught)
                faults.fire("engine.compile.bucket")
            except faults.InjectedFault:
                return max(1, n)
        for b in self._buckets:
            if n <= b:
                return b
        return self.max_seq_len

    def _free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _next_rng(self):
        with self._rng_lock:  # called from the loop thread AND prefill workers
            self._rng, sub = jax.random.split(self._rng)
        return sub

    def score_prompt(
        self, prompt_ids: List[int], adapter: Optional[str] = None
    ) -> List[dict]:
        """Per-token prompt logprob entries (same shape as
        GenRequest.logprob_entries) for positions 1..n-1 — the first token
        has no conditional. Serves OpenAI completions ``echo`` +
        ``logprobs``; ``adapter`` selects the same LoRA the generation uses
        so prompt and generated logprobs come from ONE model. Pads to the
        prefill bucket (causal attention keeps right padding from touching
        real positions) so traces stay bounded; read-only on params, safe
        alongside decode dispatches."""
        n = len(prompt_ids)
        if n < 2:
            return []
        bucket = self._bucket_for(n)
        row = np.zeros((1, bucket), np.int32)
        row[0, :n] = prompt_ids
        lora_idx = (
            jnp.full((1,), self._adapter_index.get(adapter or "", 0), jnp.int32)
            if self._lora_enabled
            else None
        )
        # _score_prompt_jit is declared "lazy" in __compile_keys__: one
        # bounded compile per bucket on first echo+logprobs use, exempt
        # from the strict post-fence rule (the sentry still counts it)
        with self._sentry_scope("score", lazy=True):
            chosen, rank, top_id, top_lp = self._score_prompt_jit(
                self.params, jnp.asarray(row), lora_idx
            )
        chosen = np.asarray(chosen)
        rank = np.asarray(rank)
        top_id = np.asarray(top_id)
        top_lp = np.asarray(top_lp)
        return [
            {
                "id": int(prompt_ids[i + 1]),
                "logprob": float(chosen[i]),
                "rank": int(rank[i]),
                "top_ids": top_id[i].tolist(),
                "top_logprobs": top_lp[i].tolist(),
            }
            for i in range(n - 1)
        ]

    def _wake_loop(self) -> None:
        if self._wake is not None:
            self._wake.set()

    def _prefill_device(self, request: GenRequest):
        """Device side of admission: prefill the prompt, sample the first
        token. Runs in a worker thread CONCURRENTLY with decode chunks — it
        touches no slot state, so decode throughput does not stall while a
        long prompt prefills. The cheap commit happens on the loop thread at
        the next chunk boundary (_commit_admission)."""
        with self._sentry_scope("prefill", prompt_len=len(request.prompt_ids)):
            return self._prefill_device_inner(request)

    def _prefill_device_inner(self, request: GenRequest):
        if faults.active():
            # chaos seam: delayed prefill (deadline tests) or a raised
            # admission failure (isolated by _admission_task's except path)
            faults.fire("engine.prefill", request=request)
        # disaggregated ship-hit accounting (docs/disaggregation.md): book
        # the shipped prefix's fate before the lookup consumes it
        self._count_ship_outcome(request)
        ids = request.prompt_ids
        use_ring = (
            self._prefill_ring_jit is not None
            and self._long_threshold < len(ids) <= self._long_cap
        )
        use_pp = False
        if (
            not use_ring
            and self._prefill_pipeline_jit is not None
            and len(ids) > self._long_threshold
        ):
            pp_bucket = -(-len(ids) // self._pp_chunk) * self._pp_chunk
            # only pipeline when there are at least as many microbatches as
            # stages — below that the fill/drain bubble dominates and the
            # plain bucketed prefill is faster (m=1 would be fully serial)
            use_pp = (
                pp_bucket <= self.max_seq_len
                and pp_bucket // self._pp_chunk >= self._pp
            )
        if use_ring:
            # sp-sharded long prefill: pad to a multiple of the sp axis,
            # never past the sp-divisible cap
            bucket = min(
                -(-len(ids) // self._long_step) * self._long_step,
                self._long_cap,
            )
        elif use_pp:
            bucket = pp_bucket  # pipeline pads to whole sequence chunks
        else:
            bucket = self._bucket_for(len(ids))
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, : len(ids)] = ids
        seq_lens = jnp.asarray([len(ids)], jnp.int32)
        # prefill KV sized to the bucket: one cached, never-mutated template per
        # bucket (prefill reads only its shape; re-allocating [L,1,bucket,H,D]
        # per admission would put hundreds of MB of HBM traffic on the
        # admission path for 8B-class models)
        template_len = max(bucket, 1)
        with self._template_lock:
            template = self._prefill_templates.get(template_len)
            if template is None:
                template = self.bundle.init_cache(1, template_len)
                self._prefill_templates[template_len] = template
        lora_i = self._slot_lora(request)
        lora_arr = jnp.asarray([lora_i], jnp.int32) if self._lora_enabled else None
        # automatic prefix caching: a stored block-aligned prefix of this
        # prompt (same adapter) skips straight to its remainder
        # single-dispatch interactive admissions skip the prefill gate's
        # pacing (a first-token-critical lone enqueue must not park behind
        # a batch resume's permit — docs/slo_scheduling.md); multi-segment
        # interactive trains stay paced like any other
        gate_bypass = request.priority == "interactive"
        prefix_result = None
        if self._prefix is not None and not use_ring:
            if self.cache_mode == "paged":
                prefix_result = self._prefix_admission_paged(
                    ids, lora_arr, lora_i, request
                )
            else:
                prefix_result = self._prefix_admission(
                    ids, lora_arr, lora_i, gate_bypass
                )
        c = self._chunked
        # the chunked mini cache must be a multiple of C: a final chunk
        # overflowing the bucket would be CLAMPED backward by
        # dynamic_update_slice, silently overwriting earlier prompt K/V
        chunk_bucket = -(-bucket // c) * c if c else 0
        use_chunked = (
            prefix_result is None
            and not use_ring
            and not use_pp
            and c > 0
            and len(ids) > c
            and chunk_bucket <= self.max_seq_len
        )
        if use_chunked and chunk_bucket != bucket:
            bucket = chunk_bucket
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, : len(ids)] = ids
        if prefix_result is not None:
            last_logits, mini_cache = prefix_result
        elif use_chunked:
            # incremental prefill: C-token segments attend over the cache so
            # far; the template is read (not donated) on the first segment
            with self._template_lock:
                template = self._prefill_templates.get(bucket)
                if template is None:
                    template = self.bundle.init_cache(1, bucket)
                    self._prefill_templates[bucket] = template
            cache = template
            last_logits = None
            n_segs = -(-len(ids) // c)
            for seg_i, s in enumerate(range(0, len(ids), c)):
                seg = ids[s : s + c]
                seg_tokens = np.zeros((1, c), np.int32)
                seg_tokens[0, : len(seg)] = seg
                fn = (
                    self._prefill_chunk_first_jit
                    if seg_i == 0
                    else self._prefill_chunk_jit
                )
                if self._prefill_gate is not None:
                    # pace the segment train against decode chunks so the
                    # device queue interleaves instead of bursting (chunked
                    # admissions are multi-segment by construction: no
                    # single-dispatch bypass here)
                    self._prefill_gate.acquire()
                last_logits, cache = fn(
                    self.params,
                    jnp.asarray(seg_tokens),
                    jnp.asarray([s], jnp.int32),
                    jnp.asarray([len(seg) - 1], jnp.int32),
                    cache,
                    with_logits=(seg_i == n_segs - 1),
                    lora_idx=lora_arr,
                )
            mini_cache = cache
        else:
            if use_ring:
                prefill_fn = self._prefill_ring_jit
            elif use_pp:
                prefill_fn = self._prefill_pipeline_jit
            else:
                prefill_fn = self._prefill_jit
            if self._prefill_gate is not None:
                self._prefill_gate.acquire(bypass=gate_bypass)
            last_logits, mini_cache = prefill_fn(
                self.params, jnp.asarray(tokens), seq_lens, template, lora_arr
            )
        if self._prefix is not None and not use_ring and self.cache_mode != "paged":
            # make this prompt's prefix available to future admissions (the
            # paged path stores by page reference at commit time instead —
            # its pages exist only once the loop thread has written them)
            self._prefix.store(
                ids, lora_i,
                {k: v for k, v in mini_cache.items() if k != "length"},
            )
        first_id, first_lp = self._first_token_from_logits(request, last_logits)
        return first_id, mini_cache, first_lp

    def _first_token_from_logits(self, request: GenRequest, last_logits):
        """Sample a request's FIRST token from its prefill logits [1, V]:
        grammar-constrain, apply the request's sampling extras, walk the
        guided DFA host-side, and build the first logprob entry. Shared by
        the legacy admission worker (_prefill_device) and the ragged
        scheduler's finishing-chunk commit — the two paths sampling through
        ONE function is what makes their first tokens byte-identical."""
        sp = SamplingParams(
            temperature=jnp.asarray([request.temperature], jnp.float32),
            top_k=jnp.asarray([request.top_k], jnp.int32),
            top_p=jnp.asarray([request.top_p], jnp.float32),
        )
        logits32 = last_logits.astype(jnp.float32)
        gentry = None
        if request.guided is not None:
            # compile/register the grammar (slow part; on the legacy path
            # we're in the admission worker thread — the ragged path
            # compiled it there already and only refetches its entry) and
            # constrain the FIRST token here — subsequent tokens are
            # constrained inside the decode scan
            if request._guided_key is not None:
                with self._guided_lock:
                    gentry = self._grammars.get(request._guided_key)
            if gentry is None:
                gentry = self._ensure_grammar(request)
            row = self._gmask_np[gentry["start"]]
            allowed = np.unpackbits(row, bitorder="little")[: self._vocab] > 0
            logits32 = jnp.where(
                jnp.asarray(allowed)[None, :], logits32, jnp.float32(-1e30)
            )
        lp_src = logits32
        if self._request_has_extras(request):
            extras, counts0, pmask0 = self._request_extras_row(request)
            first = self._sample_jit(
                logits32, sp, self._next_rng(), extras, counts0, pmask0
            )
            if request.logprobs is not None:
                # reported logprobs reflect bias/penalties (OpenAI semantics)
                lp_src = penalize_logits(logits32, extras, counts0, pmask0)
        else:
            first = self._sample_jit(logits32, sp, self._next_rng())
        first_id = int(np.asarray(first)[0])
        if gentry is not None:
            # host-side byte walk for the first token's state advance
            if first_id == self.eos_token_id:
                request._gstate0 = gentry["terminal"]
            else:
                s = gentry["start"]
                with self._guided_lock:
                    byte_np = self._gbyte_np
                    tb, tl = self._gtok_np
                for b in tb[first_id][: int(tl[first_id])]:
                    s = int(byte_np[s, int(b)])
                    if s < 0:
                        break
                request._gstate0 = s
        first_lp = None
        if request.logprobs is not None:
            chosen, tid, tlp = self._first_lp_jit(lp_src, first)
            first_lp = {
                "id": first_id,
                "logprob": float(np.asarray(chosen)[0]),
                "top_ids": np.asarray(tid)[0].tolist(),
                "top_logprobs": np.asarray(tlp)[0].tolist(),
            }
        return first_id, first_lp

    def _prefix_bucket(self, prefix_len: int, n_tokens: int) -> Optional[int]:
        """Mini-cache bucket covering the prefix plus the tail's segment
        windows, from the bounded engine bucket set — minting a size per
        (prefix_len, remainder) combination would permanently cache a fresh
        multi-hundred-MB template (8B-class) and recompile prefill_chunk for
        every new shape, turning "hits" into compile storms and an HBM
        leak. None when no bucket fits."""
        c2 = self._prefix_chunk
        remainder = n_tokens - prefix_len
        required = prefix_len + -(-remainder // c2) * c2
        bucket = self._bucket_for(required)
        if bucket < required or bucket > self.max_seq_len:
            return None
        return bucket

    def _prefill_tail(self, cache, ids, prefix_len: int, lora_arr,
                      gate_bypass: bool = False):
        """Prefill only the non-shared tail of ``ids`` through the donating
        prefill_chunk, attending over the prefix KV already in ``cache``.
        The cache is owned by this admission, so every segment may donate it
        (unlike the cold chunked path, whose first segment reads the shared
        template). Returns (last_logits, cache)."""
        c2 = self._prefix_chunk
        last_logits = None
        starts = list(range(prefix_len, len(ids), c2))
        # the single-dispatch bypass only applies to a one-segment tail: a
        # longer train is paced exactly like a chunked cold prefill
        gate_bypass = gate_bypass and len(starts) == 1
        for si, s in enumerate(starts):
            seg = ids[s : s + c2]
            seg_tokens = np.zeros((1, c2), np.int32)
            seg_tokens[0, : len(seg)] = seg
            if self._prefill_gate is not None:
                self._prefill_gate.acquire(bypass=gate_bypass)
            last_logits, cache = self._prefill_chunk_jit(
                self.params,
                jnp.asarray(seg_tokens),
                jnp.asarray([s], jnp.int32),
                jnp.asarray([len(seg) - 1], jnp.int32),
                cache,
                with_logits=(si == len(starts) - 1),
                lora_idx=lora_arr,
            )
        return last_logits, cache

    def _prefix_admission(self, ids, lora_arr, lora_i,
                          gate_bypass: bool = False):
        """Dense prefix-cache hit path: assemble the tree's block run into a
        mini cache and prefill only the remainder through prefill_chunk.
        Returns (last_logits, mini_cache) or None (miss / doesn't fit)."""
        hit = self._prefix.lookup(ids, lora_i)
        if hit is None:
            return None
        prefix_len = hit["len"]
        bucket = self._prefix_bucket(prefix_len, len(ids))
        if bucket is None:
            self._prefix.uncount_hit(hit)  # recomputed cold: not a real hit
            return None
        with self._template_lock:
            template = self._prefill_templates.get(bucket)
            if template is None:
                template = self.bundle.init_cache(1, bucket)
                self._prefill_templates[bucket] = template
        cache = self._assemble_prefix_jit(
            template, hit["bufs"], jnp.asarray(prefix_len, jnp.int32)
        )
        return self._prefill_tail(cache, ids, prefix_len, lora_arr,
                                  gate_bypass)

    def _prefix_admission_paged(self, ids, lora_arr, lora_i, request):
        """Paged prefix-cache hit path. The shared pages are PINNED by the
        lookup and carried on the request until the loop-thread commit maps
        them into the slot's page table by reference (zero KV copies for the
        shared run — kv_cache.write_prompt_shared). Here they are only
        GATHERED into the dense mini-cache layout as the compute input for
        the tail's prefill_chunk; that transient is dropped after admission.
        Returns (last_logits, mini_cache) or None (miss / doesn't fit)."""
        with lifecycle_ledger.owner(lifecycle_ledger.request_tag(request)):
            # hit + pin acquires attributed to this request: the ledger's
            # per-request audit at emit/fail/cancel proves they released
            hit = self._prefix.lookup_pages(ids, lora_i)
        if hit is None:
            return None
        try:
            prefix_len = hit["len"]
            bucket = self._prefix_bucket(prefix_len, len(ids))
            page_size = self.paged_cache.pool.page_size
            if bucket is None or bucket % page_size:
                with lifecycle_ledger.owner(
                    lifecycle_ledger.request_tag(request)
                ):
                    self._prefix.release(hit)
                self._prefix.uncount_hit(hit)  # recomputed cold
                return None
            # pad the page list with the null page to the bucket's page count
            # so the gather compiles once per bucket, not per prefix length
            pages = list(hit["pages"])
            padded = pages + [0] * (bucket // page_size - len(pages))
            with self.paged_cache.dispatch_lock:
                scale_args = (
                    (self.paged_cache.k_scale, self.paged_cache.v_scale)
                    if self._paged_quant
                    else ()
                )
                cache = self._gather_pages_jit(
                    self.paged_cache.k, self.paged_cache.v,
                    jnp.asarray(padded, jnp.int32),
                    jnp.asarray(prefix_len, jnp.int32),
                    *scale_args,
                )
            last_logits, cache = self._prefill_tail(
                cache, ids, prefix_len, lora_arr,
                gate_bypass=request.priority == "interactive",
            )
        except BaseException:
            # release() is pop-idempotent by construction: re-entering here
            # after a raise out of the release/uncount pair above re-pops
            # nothing
            with lifecycle_ledger.owner(
                lifecycle_ledger.request_tag(request)
            ):
                self._prefix.release(hit)  # tpuserve: ignore[TPU702] release() pops; re-release is a no-op
            raise
        request._prefix_hit = hit
        return last_logits, cache

    def _release_prefix_hit(self, request: GenRequest) -> None:
        """Admission failed/dropped before its slot commit: drop the pin the
        paged lookup took on the shared pages. No-op otherwise."""
        hit, request._prefix_hit = request._prefix_hit, None
        if hit is not None and self._prefix is not None:
            with lifecycle_ledger.owner(
                lifecycle_ledger.request_tag(request)
            ):
                self._prefix.release(hit)

    def _release_resume_pin(self, request: GenRequest) -> None:
        """Drop the eviction pin a preemption took on the request's stored
        history (prefix_cache.pin_run). Called once the resume's admission
        lookup ran (the hit holds its own page pins from there) or when the
        request leaves the queue without admission (shed, expired,
        cancelled, engine stop). No-op otherwise."""
        pin, request._resume_pin = request._resume_pin, None
        if pin is not None and self._prefix is not None:
            if faults.active():
                try:
                    # chaos seam: an injected raise models a teardown bug
                    # that drops the handle WITHOUT running the unpin — a
                    # lost free no page audit can see (node pins are not
                    # page refcounts). The armed ownership ledger must
                    # name it at the drain audit (tests/test_chaos.py).
                    faults.fire("engine.ledger.leak", request=request)
                except faults.InjectedFault:
                    return
            self._prefix.unpin_run(pin)

    def _commit_admission(self, request: GenRequest, slot: int, first_id: int, mini_cache, first_lp=None) -> None:
        """Loop-thread-only: route the prefilled KV into the shared cache and
        activate the slot. Never runs concurrently with a decode chunk."""
        self._insert_prefill(slot, mini_cache, len(request.prompt_ids), request)
        self._activate_slot(request, slot, first_id, first_lp)

    def _activate_slot(self, request: GenRequest, slot: int, first_id: int,
                       first_lp=None) -> None:
        """Slot activation shared by the legacy commit and the ragged
        scheduler's finishing-chunk commit (whose KV is already in place —
        it was written slot-resident, chunk by chunk): per-slot sampling /
        extras / guided mirrors, admission bookkeeping, and the first
        token's emission."""
        self._slot_req[slot] = request
        # admission-drain bookkeeping: the Retry-After hint derives from the
        # rate these commits land at
        self._admit_times.append(time.monotonic())
        self._admit_count += 1
        request._gen_ids = []  # resume leg: history now lives in prompt_ids
        self._next_token[slot] = first_id
        if self._tokbuf is not None:
            # speculation history invariant: row holds the prompt plus every
            # emitted token; length+1 tokens are known (pending included)
            row = np.zeros(self._tokbuf.shape[1], np.int32)
            ids = request.prompt_ids[: self._tokbuf.shape[1] - 1]
            row[: len(ids)] = ids
            row[len(ids)] = first_id
            self._tokbuf[slot] = row
        self._temperature[slot] = request.temperature
        self._top_k[slot] = request.top_k
        self._top_p[slot] = request.top_p
        self._lora_slots[slot] = self._slot_lora(request)
        self._presence[slot] = request.presence_penalty
        self._frequency[slot] = request.frequency_penalty
        self._repetition[slot] = request.repetition_penalty or 1.0
        # mask BEFORE the int64 store: JSON ints are unbounded and a seed
        # >= 2**63 would overflow the numpy slot array on the loop thread
        self._seeds[slot] = (
            -1 if request.seed is None else int(request.seed) & 0x7FFFFFFF
        )
        self._min_tokens[slot] = min(
            max(0, int(request.min_tokens or 0)), 2**31 - 1
        )
        self._stop_rows[slot] = self._request_stop_row(request)
        if request._guided_key is not None:
            # transfer the grammar ref from the request to the slot; the
            # first token may already have completed the match (terminal)
            self._slot_guided_key[slot] = request._guided_key
            request._guided_key = None
            self._gstate[slot] = request._gstate0
        # fresh per-slot config: invalidate the cached device constants and
        # mark the slot so the next dispatch merges the host value into the
        # device-chained token/DFA vectors
        self._sampling_dev = None
        self._extras_dev = None
        self._slot_overrides[slot] = True
        has_extras = self._request_has_extras(request)
        self._slot_extra[slot] = has_extras
        if has_extras or self._counts_dev is not None:
            # the [B, V] state exists as soon as anyone needs it; rows must
            # then be reset on EVERY admission (stale bias/mask from a
            # previous occupant would leak into this request)
            self._ensure_extras_state()
            bias_row, pmask_row = self._bias_pmask_rows(request)
            (
                self._counts_dev,
                self._bias_dev,
                self._pmask_dev,
            ) = self._set_sampling_row_jit(
                self._counts_dev,
                self._bias_dev,
                self._pmask_dev,
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(first_id, jnp.int32),
                jnp.asarray(bias_row),
                jnp.asarray(pmask_row),
            )
        self._emit(slot, first_id, first_lp)

    async def _admission_task(self, request: GenRequest, slot: int) -> None:
        """Background prefill for one request; reserves `slot` via
        self._admitting until committed or failed."""
        try:
            first_id, mini_cache, first_lp = await asyncio.to_thread(
                self._prefill_device, request
            )
        except Exception as ex:
            # a failed admission fails only its own request
            self._release_resume_pin(request)
            self._deref_guided_request(request)
            self._release_prefix_hit(request)
            request.error = ex
            request.out_queue.put_nowait(_FINISHED)
            self._admitting.discard(slot)
            self._wake_loop()
            return
        # the prefill's prefix lookup ran (hit or miss): the preemption-era
        # eviction pin on the stored history has done its job
        self._release_resume_pin(request)
        if self._stopped:
            self._deref_guided_request(request)
            self._release_prefix_hit(request)
            request.error = EngineUnavailableError("engine stopped")
            request.out_queue.put_nowait(_FINISHED)
            self._admitting.discard(slot)
            return
        await self._ready.put((request, slot, first_id, mini_cache, first_lp))
        self._wake_loop()
        if self._loop_task is None or self._loop_task.done():
            # loop died between prefill and hand-off: nobody will commit —
            # fail anything stranded in the ready queue (incl. our item)
            self._drain_ready(EngineUnavailableError("engine loop exited"))

    def _insert_prefill(self, slot, mini_cache, n_tokens: int,
                        request: Optional[GenRequest] = None) -> None:
        """Route the prefilled prompt KV into the active cache backend."""
        if self.cache_mode == "paged":
            hit = request._prefix_hit if request is not None else None
            page_size = self.paged_cache.pool.page_size

            # loop-thread compile discipline: slice the mini cache with a
            # DYNAMIC start and a PAGE-MULTIPLE static size, so the eager
            # slice (and everything _scatter_pages derives from it) compiles
            # once per (bucket, page-count), not once per token length —
            # an exact [lo:hi] slice recompiled for every novel prompt/tail
            # length ON THE COMMIT PATH (measured 80-200 ms stalls of every
            # active stream under the preemptible lane's arbitrary-length
            # resume prompts). Rows past `count` land in scatter positions
            # the slot's length bookkeeping already treats as dead.
            def _tail(buf, start, count):
                import jax.lax as lax

                padded = -(-count // page_size) * page_size
                if start + padded > buf.shape[2]:
                    # bucket not a page multiple (exotic config): exact
                    # slice, at per-length compile cost
                    padded = count
                return lax.dynamic_slice_in_dim(
                    buf, jnp.asarray(start, jnp.int32), padded, axis=2
                )[:, 0]

            # int8 pools: the prefill mini cache already holds quantized K/V
            # plus per-token scales (the dense kv_quant layout); the scatter
            # carries the scale stacks [L, S, Hkv] beside the int8 pages
            def _scales(lo, hi):
                if not self._paged_quant:
                    return ()
                return (
                    _tail(mini_cache["k_scale"], lo, hi - lo),
                    _tail(mini_cache["v_scale"], lo, hi - lo),
                )

            if hit is not None:
                # prefix-cache hit: shared pages map into the slot's page
                # table BY REFERENCE (scale rows ride the same page ids);
                # only the tail's KV (+ scales) is scattered
                prefix_len = hit["len"]
                request._prefix_hit = None
                try:
                    self.paged_cache.write_prompt_shared(
                        slot, hit["pages"], prefix_len,
                        _tail(mini_cache["k"], prefix_len,
                              n_tokens - prefix_len),
                        _tail(mini_cache["v"], prefix_len,
                              n_tokens - prefix_len),
                        n_tokens,
                        *_scales(prefix_len, n_tokens),
                    )
                finally:
                    # the slot holds its own refs now; drop the lookup pin
                    self._prefix.release(hit)
            else:
                # mini_cache k/v: [L,1,bucket,Hkv,D] -> stacked [L,S,Hkv,D]
                self.paged_cache.write_prompt(
                    slot,
                    _tail(mini_cache["k"], 0, n_tokens),
                    _tail(mini_cache["v"], 0, n_tokens),
                    n_tokens,
                    *_scales(0, n_tokens),
                )
            if self._prefix is not None and request is not None:
                # zero-copy store: the tree takes references on this slot's
                # own pages (shared prefix blocks walk existing nodes; only
                # the newly computed tail blocks add nodes)
                self._prefix.store_pages(
                    request.prompt_ids,
                    self._slot_lora(request),
                    self.paged_cache.pool.slot_pages(slot),
                )
                # disaggregated ship-at-commit (docs/disaggregation.md):
                # the slot's pages now hold the whole prompt — export the
                # storable prefix to the destination decode replica
                self._maybe_ship(request, slot)
        else:
            self.cache = self._insert_jit(
                self.cache,
                {k: v for k, v in mini_cache.items() if k != "length"},
                jnp.asarray(n_tokens, jnp.int32),
                slot,
            )

    def _emit(self, slot: int, token_id: int, lp: dict | None = None) -> None:
        request = self._slot_req[slot]
        if request is None:
            return
        if request.cancelled:
            # consumer is gone — free the slot (and its KV pages) early
            request.out_queue.put_nowait(_FINISHED)
            self._slot_req[slot] = None
            self._release_guided(slot, request)
            self._free_slot_pages(slot)
            self._ledger_audit_request(request, "cancel")
            return
        if (
            request._deadline is not None
            and time.monotonic() > request._deadline
        ):
            # total budget elapsed mid-decode: structured 408, slot reclaimed
            self.counters["deadline_total"] += 1
            self._fail_slot(
                slot,
                DeadlineExceededError(
                    "request budget elapsed after {} tokens".format(
                        request.produced
                    ),
                    stage="total",
                ),
            )
            return
        if lp is not None and request.logprobs is not None:
            # appended BEFORE the token is queued (see GenRequest contract)
            request.logprob_entries.append(lp)
        request.produced += 1
        if request.priority != "interactive":
            # preemptible lane: track emitted tokens so a preemption can
            # fold them into the resume prompt (docs/slo_scheduling.md)
            request._gen_ids.append(int(token_id))
        if request.first_token_at is None:
            request.first_token_at = time.time()  # client-observable TTFT
        request.out_queue.put_nowait(token_id)
        stop_ids = request.stop_token_ids or (
            [self.eos_token_id] if self.eos_token_id is not None else []
        )
        total_len = request.prompt_len + request.produced
        if (
            token_id in stop_ids
            or request.produced >= self._effective_max_new(request)
            or total_len >= self.max_seq_len
        ):
            request.out_queue.put_nowait(_FINISHED)
            self._slot_req[slot] = None
            self._release_guided(slot, request)
            try:
                # chaos seam: an injected raise here models a teardown
                # bug that loses the slot's page references — the armed
                # KV sanitizer must then fail the drain check, naming
                # the leaked pages (tests/test_chaos.py)
                if self.paged_cache is not None:
                    faults.fire("engine.release", request=request)
                self._free_slot_pages(slot)  # recycle (or quarantine) pages
            except faults.InjectedFault:
                pass
            self._ledger_audit_request(request, "emit-finish")

    def _drain_ready(self, err: BaseException) -> None:
        """Fail every completed-but-uncommitted admission (loop is exiting)."""
        while not self._ready.empty():
            request, slot, _first, _cache, _lp = self._ready.get_nowait()
            self._admitting.discard(slot)
            self._deref_guided_request(request)
            self._release_prefix_hit(request)
            request.error = err
            request.out_queue.put_nowait(_FINISHED)

    def _fail_all(self, err: BaseException) -> None:
        """Terminate every active request with `err` (nothing may hang).

        Does NOT touch the page pool: _fail_all can run (via stop()) while a
        worker thread is inside _run_paged_chunk mutating the pool — the loop
        frees all slots itself when it exits (sole-owner point)."""
        for slot, request in enumerate(self._slot_req):
            if request is not None:
                request.error = err
                request.out_queue.put_nowait(_FINISHED)
                self._slot_req[slot] = None
                self._release_guided(slot, request)

    def _spec_eligible_mask(self, active_mask: np.ndarray):
        """(greedy_mask, sampled_mask): greedy_mask — slots the greedy
        verify chain reproduces exactly (temperature 0, no sampling extras,
        no grammar constraint, no logprob tracking); sampled_mask — plain
        temperature>0 slots eligible for rejection-sampled speculation
        (same exclusions; gated by engine.spec_sampling). Everything else
        takes the sampled position-0 path inside the same dispatch."""
        lp_free = np.array(
            [r is None or r.logprobs is None for r in self._slot_req]
        )
        clean = (
            active_mask
            & ~self._slot_extra
            & (self._gstate < 0)
            & lp_free
        )
        greedy = clean & (self._temperature == 0.0)
        sampled = (
            clean & (self._temperature > 0.0)
            if self._spec_sampling
            else np.zeros_like(greedy)
        )
        return greedy, sampled

    def _spec_common_args(self, active_mask, spec_mask, sspec_mask, sampling):
        """Argument tail shared by the dense and paged spec dispatches."""
        use_extras = self._extras_active(active_mask)
        use_guided = bool(np.any(self._gstate[active_mask] >= 0))
        gtables = self._guided_device_tables() if use_guided else None
        args = (
            jnp.asarray(active_mask),
            jnp.asarray(spec_mask),
            jnp.asarray(sspec_mask),
            sampling,
            self._next_rng(),
            # host mirrors snapshot-COPIED at the thread handoff: the spec
            # dispatch runs on a worker thread and jnp.asarray is zero-copy
            # aliasing on CPU (tpuserve-analyze TPU502; same rationale as
            # _chain_input)
            jnp.asarray(self._lora_slots.copy()) if self._lora_enabled else None,
            self._batch_extras() if use_extras else None,
            self._counts_dev if use_extras else None,
            self._pmask_dev if use_extras else None,
            gtables,
            jnp.asarray(self._gstate.copy()) if gtables is not None else None,
        )
        return args, use_extras, gtables

    def _spec_commit_state(self, tokbuf, new_counts, gstate_out, lp,  # tpuserve: ignore[TPU501] serial spec path: the loop is suspended awaiting this worker call and commits land at loop tops, so no loop-thread mutator runs concurrently
                           use_extras, gtables):
        if use_extras:
            self._counts_dev = new_counts
        if gtables is not None:
            # np.array (copy): asarray would alias the immutable device
            # buffer and commit/release paths write rows in place
            self._gstate = np.array(gstate_out)
        # same copy rationale: _commit_admission writes tokbuf rows in place
        self._tokbuf = np.array(tokbuf)
        return tuple(np.asarray(a) for a in lp) if lp is not None else None

    def _dispatch_spec_chunk(self, active_mask: np.ndarray, spec_mask,
                             sspec_mask, sampling, want_lp: bool = False):
        """Worker-thread side of a dense speculative dispatch: run the fused
        draft-verify rounds and read back (gs [R,B,k+1], accs [R,B],
        pending [B], lp). The host token buffer round-trips through the
        executable so the on-device n-gram proposer sees each slot's full
        history."""
        if faults.active():
            faults.fire(
                "engine.decode.stall",
                requests=[r for r in self._slot_req if r is not None],
            )
        tail, use_extras, gtables = self._spec_common_args(
            active_mask, spec_mask, sspec_mask, sampling
        )
        (tokbuf, pending, self.cache, gs, accs, new_counts, gstate_out,
         lp) = self._spec_chunk_jit(
            self.params,
            # copies: worker-thread upload of loop-owned host mirrors
            # (tpuserve-analyze TPU502)
            jnp.asarray(self._tokbuf.copy()),
            jnp.asarray(self._next_token.copy()),
            self.cache,
            *tail,
            want_lp=want_lp,
            with_sspec=bool(sspec_mask.any()),
        )
        lp_np = self._spec_commit_state(
            tokbuf, new_counts, gstate_out, lp, use_extras, gtables
        )
        return np.asarray(gs), np.asarray(accs), np.asarray(pending), lp_np

    def _dispatch_spec_paged_chunk(self, active_mask: np.ndarray, spec_mask,
                                   sspec_mask, sampling,
                                   want_lp: bool = False):
        """Paged-cache speculative dispatch. Pages for the worst-case chunk
        growth (decode_steps*(k+1) tokens per slot) are allocated up front —
        accepted counts are a device-side value, so write coordinates must
        stay dynamic (verify_paged derives them from the page table) — and
        rolled back to what was actually emitted afterwards
        (PagePool.truncate). Returns None when the pool cannot hold the
        over-allocation; the caller falls back to the plain paged chunk for
        this iteration (sequences truly out of memory then fail there,
        per-request, not engine-wide)."""
        if faults.active():
            faults.fire(
                "engine.decode.stall",
                requests=[r for r in self._slot_req if r is not None],
            )
        pool = self.paged_cache.pool
        lengths0 = pool.lengths().copy()
        extended: List[int] = []
        for slot in np.nonzero(active_mask)[0]:
            slot = int(slot)
            # position-0 plain-path slots keep 1 token/round and only the
            # last round's draft writes can land past the kept run — they
            # need rounds+k tokens of headroom, not rounds*(k+1); the
            # smaller ask avoids whole-batch fallback near pool capacity.
            # Both speculating classes (greedy chain AND rejection-sampled
            # chain) can accept drafts, so they take the full slack.
            slack = (
                self._spec_slack
                if (spec_mask[slot] or sspec_mask[slot])
                else self.decode_steps + self._spec_k
            )
            try:
                pool.extend(slot, slack)
            except MemoryError:
                for s in extended:
                    pool.truncate(s, int(lengths0[s]))
                return None
            extended.append(slot)
        try:
            self.paged_cache.apply_pending_cow()
            page_table = pool.page_table(self._pages_per_seq)
            tail, use_extras, gtables = self._spec_common_args(
                active_mask, spec_mask, sspec_mask, sampling
            )
            with self.paged_cache.dispatch_lock:
                # pool handles read under the lock: a racing donating dispatch
                # would invalidate a handle grabbed outside it
                if self._paged_quant:
                    cachelike = (
                        self.paged_cache.k,
                        self.paged_cache.v,
                        self.paged_cache.k_scale,
                        self.paged_cache.v_scale,
                        jnp.asarray(page_table),
                        jnp.asarray(lengths0),
                    )
                else:
                    cachelike = (
                        self.paged_cache.k,
                        self.paged_cache.v,
                        jnp.asarray(page_table),
                        jnp.asarray(lengths0),
                    )
                (tokbuf, pending, new_pools, gs, accs, new_counts,
                 gstate_out, lp) = self._spec_paged_jit(
                    self.params,
                    # copies: worker-thread upload of loop-owned host mirrors
                    # (tpuserve-analyze TPU502)
                    jnp.asarray(self._tokbuf.copy()),
                    jnp.asarray(self._next_token.copy()),
                    cachelike,
                    *tail,
                    want_lp=want_lp,
                    with_sspec=bool(sspec_mask.any()),
                )
                self.paged_cache.k = new_pools[0]
                self.paged_cache.v = new_pools[1]
                if self._paged_quant:
                    self.paged_cache.k_scale = new_pools[2]
                    self.paged_cache.v_scale = new_pools[3]
            lp_np = self._spec_commit_state(
                tokbuf, new_counts, gstate_out, lp, use_extras, gtables
            )
            gs_np, accs_np = np.asarray(gs), np.asarray(accs)
            appended = gs_np.shape[0] + accs_np.sum(axis=0)          # [B]
        except BaseException:
            # tpuserve-analyze TPU701: the speculative over-allocation must
            # roll back on EVERY exit — a dispatch failure here would
            # otherwise strand the slack pages on the surviving slots until
            # the next retire (slot_len inflated past what was ever
            # written). The armed ownership ledger audits exactly this.
            for slot in extended:
                pool.truncate(slot, int(lengths0[slot]))
            raise
        # roll back each slot's over-allocation to the tokens actually
        # written: rounds*(1 token) + accepted drafts. Must happen BEFORE
        # emission — _emit frees a finishing slot's pages entirely.
        for slot in extended:
            pool.truncate(slot, int(lengths0[slot]) + int(appended[slot]))
        return gs_np, accs_np, np.asarray(pending), lp_np

    # -- ragged scheduler: token-budget admission (docs/ragged_attention.md) --

    def _ragged_spec_wanted(self, active_mask: np.ndarray) -> bool:
        """Spec-as-row routing (docs/ragged_attention.md): with speculation
        on, eligible decode slots ride the ragged scheduler's mixed
        launches as q=k+1 verify rows — the legacy serial scan
        (_dispatch_spec_chunk) and its pipeline drain never run under the
        ragged scheduler. Brownout stage 1+ parks speculation exactly like
        the pipelined path: the verify slack and the k wasted positions
        per reject are headroom an overloaded engine no longer has."""
        if not (self._ragged and self._speculation) or not active_mask.any():
            return False
        if self._brownout is not None and self._brownout.stage >= 1:
            return False
        greedy, sampled = self._spec_eligible_mask(active_mask)
        return bool(greedy.any() or sampled.any())

    def _ngram_draft_rows(self, slots, hists) -> "np.ndarray":
        """Host-side n-gram proposal for spec-verify rows ([len(slots), k]
        draft tokens), mirroring the device proposer the legacy serial scan
        ran in-jit: match the history's n-token tail against every earlier
        window of the slot's token buffer, continue from the LAST match;
        no-match rows draft the tail's last token repeated (a reject still
        emits the bonus token). Host-side because the drafts become ragged
        ROW CONTENT — they must be known before the launch is laid out."""
        n_, k_ = self._spec_ngram, self._spec_k
        buf_len = self._tokbuf.shape[1]
        out = np.zeros((len(slots), k_), np.int32)
        for i, (slot, hist) in enumerate(zip(slots, hists)):
            buf = self._tokbuf[slot]
            tail_pos = np.clip(hist - n_ + np.arange(n_), 0, buf_len - 1)
            tail = buf[tail_pos]
            # window must end before the tail starts (a previous
            # occurrence, not the tail matching itself); only the hist
            # tokens actually written participate — the scan is bounded by
            # the generated length, not the buffer capacity (this runs on
            # the loop thread every launch)
            limit = hist - 2 * n_ + 1
            best = -1
            if limit > 0:
                match = np.ones(limit, bool)
                for j in range(n_):
                    match &= buf[j : limit + j] == tail[j]
                idx = np.nonzero(match)[0]
                if idx.size:
                    best = int(idx[-1])
            if best >= 0:
                pos = np.clip(best + n_ + np.arange(k_), 0, buf_len - 1)
                out[i] = buf[pos]
            else:
                out[i] = tail[-1]
        return out

    async def _ragged_admission_task(self, request: GenRequest, slot: int) -> None:
        """Ragged-mode admission: no standalone prefill dispatch — the
        prompt rides the loop's ragged launches as budget-bounded chunk
        rows. Only worker-thread-worthy host prep runs here (a grammar
        compile can take seconds); the slot stays reserved via _admitting
        until the final chunk's commit or a failure path releases it."""

        def prep():
            if faults.active():
                # the same chaos seam the legacy admission worker fires
                # (delay = slow admission, raise = failed admission)
                faults.fire("engine.prefill", request=request)
            if request.guided is not None:
                self._ensure_grammar(request)

        try:
            await asyncio.to_thread(prep)
        except Exception as ex:
            self._release_resume_pin(request)
            self._deref_guided_request(request)
            request.error = ex
            request.out_queue.put_nowait(_FINISHED)
            self._admitting.discard(slot)
            self._wake_loop()
            return
        if self._stopped:
            self._release_resume_pin(request)
            self._deref_guided_request(request)
            request.error = EngineUnavailableError("engine stopped")
            request.out_queue.put_nowait(_FINISHED)
            self._admitting.discard(slot)
            return
        await self._ragged_ready.put((request, slot))
        self._wake_loop()
        if self._loop_task is None or self._loop_task.done():
            # loop died between prep and hand-off: nobody will open the job
            self._drain_ragged_ready(
                EngineUnavailableError("engine loop exited")
            )

    def _drain_ragged_ready(self, err: BaseException) -> None:
        """Fail every prepped-but-unopened ragged admission (loop exiting)."""
        while not self._ragged_ready.empty():
            request, slot = self._ragged_ready.get_nowait()
            self._admitting.discard(slot)
            self._release_resume_pin(request)
            self._deref_guided_request(request)
            request.error = err
            request.out_queue.put_nowait(_FINISHED)

    def _start_ragged_job(self, request: GenRequest, slot: int):
        """Loop-thread: open a ragged admission job for a prepped request.
        Paged radix prefix hits map their shared pages into the slot's
        table by reference HERE (zero KV copies; the tail then prefills
        through chunk rows — the prefix-cache tail-chunk path). Dense
        ragged mode skips prefix reuse: there is no mini cache to assemble
        stored buffers into (documented limitation)."""
        pos = 0
        hit = None
        try:
            # disaggregated ship-hit accounting (docs/disaggregation.md)
            self._count_ship_outcome(request)
            if self.cache_mode == "paged" and self._prefix is not None:
                lora_i = self._slot_lora(request)
                with lifecycle_ledger.owner(
                    lifecycle_ledger.request_tag(request)
                ):
                    hit = self._prefix.lookup_pages(
                        request.prompt_ids, lora_i
                    )
                if hit is not None:
                    plen = hit["len"]
                    page_size = self.paged_cache.pool.page_size
                    if (
                        0 < plen < len(request.prompt_ids)
                        and plen % page_size == 0
                    ):
                        # the mapped prefix pages ride the slot's table
                        # from here: _emit/_fail_ragged_job (and the
                        # except arm below) free the slot — cross-function
                        # pairing the ownership ledger audits at drain
                        self.paged_cache.pool.map_shared(  # tpuserve: ignore[TPU701] pages ride the slot table
                            slot, list(hit["pages"]), plen
                        )
                        pos = plen
                        with lifecycle_ledger.owner(
                            lifecycle_ledger.request_tag(request)
                        ):
                            self._prefix.release(hit)
                    else:
                        # whole-prompt or misaligned hit: recompute cold
                        # (at least one tail token must produce logits)
                        with lifecycle_ledger.owner(
                            lifecycle_ledger.request_tag(request)
                        ):
                            self._prefix.release(hit)
                        self._prefix.uncount_hit(hit)
        except Exception as ex:
            self._release_resume_pin(request)
            self._deref_guided_request(request)
            # a raise between the lookup/map_shared above and the job's
            # activation would otherwise strand resources on a slot no job
            # owns (the less-traveled teardown path the ownership ledger
            # flagged): drop the hit's pin — release() is pop-idempotent,
            # so a hit the happy path already released is a no-op — and
            # free the slot (its table is authoritative: a plain free
            # reclaims whatever was mapped, nothing when nothing was)
            if hit is not None:
                with lifecycle_ledger.owner(
                    lifecycle_ledger.request_tag(request)
                ):
                    self._prefix.release(hit)  # tpuserve: ignore[TPU702] release() pops; re-release is a no-op
            self._free_ragged_slot(slot)
            request.error = ex
            request.out_queue.put_nowait(_FINISHED)
            self._admitting.discard(slot)
            return None
        # the prefix lookup ran (hit or miss): the preemption-era eviction
        # pin on the stored history has done its job (legacy parity)
        self._release_resume_pin(request)
        return _RaggedJob(request=request, slot=slot, pos=pos)

    def _free_ragged_slot(self, slot: int) -> None:
        """Reclaim a ragged job's slot pages (no pipeline barrier applies:
        ragged steps run with the pipeline drained and are synchronous)."""
        # a failed/cancelled job never seals its draft-ahead stream: the
        # receiver's unsealed assembly stays unconsumable and ages out
        self._kv_draft_ahead.pop(slot, None)
        if self.paged_cache is not None:
            self.paged_cache.pool.free(slot)

    def _fail_ragged_job(self, job: "_RaggedJob",
                         err: Optional[BaseException]) -> None:
        """Fail one in-progress ragged admission (err None = cancelled):
        release its grammar ref and slot pages and unblock its consumer."""
        if job in self._prefill_jobs:  # identity (dataclass eq=False)
            self._prefill_jobs.remove(job)
        self._admitting.discard(job.slot)
        request = job.request
        self._deref_guided_request(request)
        self._release_prefix_hit(request)  # defensive; released at job start
        if err is not None:
            request.error = err
        request.out_queue.put_nowait(_FINISHED)
        self._free_ragged_slot(job.slot)
        self._ledger_audit_request(request, "fail-ragged")

    def _abort_ragged_jobs(self, err: BaseException) -> None:
        for job in list(self._prefill_jobs):
            self._fail_ragged_job(job, err)

    def _sweep_ragged_jobs(self) -> None:
        """Drop cancelled / deadline-expired jobs before planning a step —
        budget spent on a dead admission is budget stolen from live ones."""
        for job in list(self._prefill_jobs):
            request = job.request
            if request.cancelled:
                self._fail_ragged_job(job, None)
                continue
            err = self._deadline_error_at_commit(request)
            if err is not None:
                self._fail_ragged_job(job, err)

    def _effective_token_budget(self) -> int:
        """Ragged admission budget for the NEXT step. Brownout stage >= 3
        re-expresses the legacy prefill gate's ``set_budget(1)`` on the
        token budget: the admission share shrinks to about one minimal
        chunk beside the decode batch, so decode slots drain ahead of new
        admissions (docs/slo_scheduling.md; regression in
        tests/test_scheduler.py)."""
        if self._brownout is not None and self._brownout.stage >= 3:
            return min(
                self._step_token_budget,
                self.max_batch + _RAGGED_BROWNOUT_CHUNK,
            )
        return self._step_token_budget

    def _prepare_ragged(self, active_mask: np.ndarray,
                        epoch: int) -> Optional[dict]:
        """Loop-thread half of a ragged step: sweep dead jobs, classify the
        live rows (docs/ragged_attention.md row taxonomy — plain decode
        rows carrying a q=row_steps multi-token window, spec-verify rows
        carrying a q=k+1 draft chain, prefill-chunk rows), hand each live
        job its token share under the step budget (class/arrival order —
        the jobs list is in admission-pop order), and snapshot every piece
        of shared host state the worker needs. A q=N row is N tokens of
        budget; admissions keep their PR-9 share (decode baseline is one
        token per row) and only the LEFTOVER budget widens decode windows,
        so saturating admission traffic sees the historical schedule while
        steady-state decode amortizes the launch across up to
        ``ragged_decode_steps`` tokens. Returns None when nothing is
        dispatchable."""
        self._last_progress = time.monotonic()
        self._sweep_ragged_jobs()
        decode_mask = active_mask.copy()
        budget = self._effective_token_budget()
        n_decode = int(decode_mask.sum())
        k_ = self._spec_k
        # spec-as-row: eligible decode slots become q=k+1 verify rows in
        # THIS mixed launch (host-drafted chain, device-verified, accepted
        # at retire) — the serial spec scan never runs under this scheduler
        spec_mask = np.zeros(self.max_batch, bool)
        sspec_mask = np.zeros(self.max_batch, bool)
        if self._ragged_spec_wanted(decode_mask):
            greedy, sampled_m = self._spec_eligible_mask(decode_mask)
            spec_mask, sspec_mask = greedy.copy(), sampled_m.copy()
            if faults.active() and (spec_mask.any() or sspec_mask.any()):
                # chaos seam: a mid-verify proposer/tree-layout failure
                # falls back to PLAIN DECODE for the poisoned row — it
                # rides this same launch as an ordinary q=1/multi-step
                # decode row; nothing was allocated yet, so the fallback
                # is leak-free by construction (docs/spec_decode_trees.md)
                try:
                    faults.fire(
                        "engine.spec.tree",
                        requests=[
                            self._slot_req[int(s)]
                            for s in np.nonzero(spec_mask | sspec_mask)[0]
                        ],
                    )
                except faults.InjectedFault as ex:
                    self.counters["spec_tree_fallbacks"] += 1
                    if ex.request is None:
                        spec_mask[:] = False
                        sspec_mask[:] = False
                    else:
                        for s in np.nonzero(spec_mask | sspec_mask)[0]:
                            if self._slot_req[int(s)] is ex.request:
                                spec_mask[int(s)] = False
                                sspec_mask[int(s)] = False
            # a verify row costs k extra budget tokens: demote rows
            # (highest slot first) until the baseline fits the budget
            spec_slots = [int(s) for s in np.nonzero(spec_mask | sspec_mask)[0]]
            while spec_slots and n_decode + k_ * len(spec_slots) > budget:
                drop = spec_slots.pop()
                spec_mask[drop] = False
                sspec_mask[drop] = False
        spec_any = spec_mask | sspec_mask
        n_spec = int(spec_any.sum())
        shares: List[tuple] = []
        left = max(0, budget - n_decode - k_ * n_spec)
        for job in list(self._prefill_jobs):
            if left <= 0:
                break
            remaining = len(job.request.prompt_ids) - job.pos
            take = min(left, remaining)
            if take <= 0:
                continue
            if faults.active():
                try:
                    # chaos seam: budget admission of one prefill job into
                    # this step (docs/ragged_attention.md)
                    faults.fire("engine.admit.budget", request=job.request)
                except faults.InjectedFault as ex:
                    self._count_shed("budget", job.request.priority)
                    self._fail_ragged_job(job, EngineOverloadedError(
                        "ragged budget admission shed (injected): {}".format(
                            ex
                        ),
                        retry_after=self._retry_after_hint(),
                        shed_class=job.request.priority,
                    ))
                    continue
            shares.append((job, take))
            left -= take
        if n_decode == 0 and not shares:
            return None
        # multi-step decode windows from the LEFTOVER budget: the launch
        # window buckets to a power of two (bounded compile keys, each
        # warmed by llm/warmup.py) and every row clamps host-side to its
        # own max-token / sequence bounds — a brownout stage-2 cap landing
        # mid-stream clamps the window exactly like max_new_tokens does
        plain_slots = [
            int(s) for s in np.nonzero(decode_mask & ~spec_any)[0]
        ]
        launch_steps = 1
        if plain_slots and self._ragged_steps_cap > 1 and left > 0:
            launch_steps = decode_steps_bucket(
                1 + left // len(plain_slots), cap=self._ragged_steps_cap
            )
        row_steps = np.zeros(self.max_batch, np.int32)
        for slot in plain_slots:
            request = self._slot_req[slot]
            remaining_new = (
                self._effective_max_new(request) - request.produced
            )
            remaining_len = self.max_seq_len - (
                request.prompt_len + request.produced
            )
            row_steps[slot] = max(
                1, min(launch_steps, remaining_new, remaining_len)
            )
        # drafts for the verify rows, proposed from the host token buffer
        # (kept warm at every ragged retire) through the pluggable
        # proposer: chain engines get the ngram-chain backend (drafts
        # byte-identical to the legacy _ngram_draft_rows,
        # tests/test_spec_tree.py pins it); spec_tree engines get the
        # ngram-forest topology plus the per-row tree arrays the device
        # acceptance walk and ancestor mask consume
        drafts = None
        tree_tokens = tree_parents = tree_depths = tree_n = None
        if n_spec:
            spec_slots = [int(s) for s in np.nonzero(spec_any)[0]]
            hists = [
                self._slot_req[s].prompt_len + self._slot_req[s].produced
                for s in spec_slots
            ]
            forest = self._spec_proposer.propose(
                spec_slots, hists, self._tokbuf, k_
            )
            drafts = np.zeros((self.max_batch, k_), np.int32)
            drafts[spec_slots] = forest.tokens[:, 1:]
            if self._spec_tree:
                from .spec_proposer import chain_parents

                tree_tokens = np.zeros((self.max_batch, k_ + 1), np.int32)
                tree_parents = np.broadcast_to(
                    chain_parents(k_), (self.max_batch, k_ + 1)
                ).copy()
                tree_depths = np.broadcast_to(
                    np.arange(k_ + 1, dtype=np.int32),
                    (self.max_batch, k_ + 1),
                ).copy()
                tree_n = np.full(self.max_batch, k_ + 1, np.int32)
                tree_tokens[spec_slots] = forest.tokens
                tree_parents[spec_slots] = forest.parents
                tree_depths[spec_slots] = forest.depths
                tree_n[spec_slots] = forest.n_nodes
        want_lp = any(
            self._slot_req[s] is not None
            and self._slot_req[s].logprobs is not None
            for s in np.nonzero(decode_mask)[0]
        )
        use_extras = self._extras_active(decode_mask)
        use_guided = bool(np.any(self._gstate[decode_mask] >= 0))
        gtables = self._guided_device_tables() if use_guided else None
        self._dispatch_seq += 1
        plan = {
            "seq": self._dispatch_seq,
            "epoch": epoch,
            "decode_mask": decode_mask,
            "shares": shares,
            "budget": budget,
            "want_lp": want_lp,
            "use_extras": use_extras,
            "sampling": self._batch_sampling(),
            "extras": self._batch_extras() if use_extras else None,
            "gtables": gtables,
            "gstate": (
                jnp.asarray(self._gstate.copy())
                if gtables is not None
                else None
            ),
            "rng": self._next_rng(),
            "lora": (
                jnp.asarray(self._lora_slots.copy())
                if self._lora_enabled
                else None
            ),
            "requests": [r for r in self._slot_req if r is not None]
            + [j.request for j, _ in shares],
            "exhausted": [],
            "failed_jobs": [],
            # rows whose admission completes THIS step (host-known at
            # planning time): the dispatch worker gathers only these rows'
            # logits device-side before readback
            "finish_slots": [
                job.slot for job, take in shares
                if job.pos + take >= len(job.request.prompt_ids)
            ],
            # multi-step / spec-as-row row taxonomy
            # (docs/ragged_attention.md)
            "spec_mask": spec_mask,
            "sspec_mask": sspec_mask,
            "spec_k": k_,
            "drafts": drafts,
            # draft-tree verify rows (docs/spec_decode_trees.md): per-row
            # topology arrays + the flat per-token ancestor lists (filled
            # by the paged layout below; None on chain engines so their
            # jit trace is byte-identical to the pre-tree one)
            "tree_tokens": tree_tokens,
            "tree_parents": tree_parents,
            "tree_depths": tree_depths,
            "tree_n": tree_n,
            "tree_anc": None,
            "row_steps": row_steps,
            "launch_steps": launch_steps,
            "step_rngs": (
                jnp.stack([self._next_rng() for _ in range(launch_steps - 1)])
                if launch_steps > 1
                else None
            ),
            "spec_rng": self._next_rng() if n_spec else None,
            # per-step window mask: step i runs for rows whose window is
            # still open ([S-1, B]; host-known — EOS mid-window is masked
            # at retire, max-token/seq bounds here)
            "chain_mask": (
                (
                    np.arange(1, launch_steps)[:, None]
                    < row_steps[None, :]
                )
                if launch_steps > 1
                else None
            ),
            "used_tokens": (
                int(row_steps.sum()) + (k_ + 1) * n_spec
                + sum(t for _, t in shares)
            ),
        }
        job_of = {job.slot: job for job, _ in shares}
        take_of = {job.slot: take for job, take in shares}
        if self.cache_mode == "paged":
            from ..ops.paged_attention import ragged_layout

            pool = self.paged_cache.pool
            # layout lens reserve each row's WHOLE window in the flat token
            # axis (a q=N decode row owns N positions: position 0 rides the
            # mixed pass, positions 1.. are written by the in-launch chain);
            # kernel row_lens count only the positions the ragged pass
            # itself computes
            span_lens = np.zeros(self.max_batch, np.int32)
            row_lens = np.zeros(self.max_batch, np.int32)
            for slot in np.nonzero(decode_mask)[0]:
                slot = int(slot)
                if spec_any[slot]:
                    span_lens[slot] = row_lens[slot] = k_ + 1
                else:
                    span_lens[slot] = row_steps[slot]
                    row_lens[slot] = 1
            for slot, take in take_of.items():
                span_lens[slot] = row_lens[slot] = take
            starts, block_rows, block_q0, tpad = ragged_layout(
                span_lens, self._ragged_qb, total=self._ragged_tpad
            )
            tokens = np.zeros(tpad, np.int32)
            tok_pos = np.zeros(tpad, np.int32)
            tok_row = np.zeros(tpad, np.int32)
            tok_valid = np.zeros(tpad, bool)
            row_last = np.zeros(self.max_batch, np.int32)
            kv_lens = np.zeros(self.max_batch, np.int32)
            pre_lens = np.zeros(self.max_batch, np.int32)
            spans: Dict[int, tuple] = {}
            for slot in range(self.max_batch):
                n = int(span_lens[slot])
                if n == 0:
                    continue
                s = int(starts[slot])
                v = int(row_lens[slot])
                pre = pool.slot_length(slot)
                pre_lens[slot] = pre
                if slot in job_of:
                    job = job_of[slot]
                    tokens[s : s + n] = job.request.prompt_ids[
                        job.pos : job.pos + n
                    ]
                elif spec_any[slot]:
                    tokens[s] = self._next_token[slot]
                    tokens[s + 1 : s + n] = drafts[slot]
                else:
                    tokens[s] = self._next_token[slot]
                spans[slot] = (s, n)
                if tree_depths is not None and spec_any[slot]:
                    # a tree node's ABSOLUTE position is its path depth,
                    # not its node index: sibling drafts at the same depth
                    # share a RoPE position, and the accepted path's K/V
                    # (compacted in-launch to positions pre+1..pre+acc)
                    # was embedded at exactly those positions
                    tok_pos[s : s + n] = pre + tree_depths[slot, :n]
                else:
                    tok_pos[s : s + n] = pre + np.arange(n, dtype=np.int32)
                tok_row[s : s + n] = slot
                # reserved multi-step positions stay invalid in the mixed
                # pass: their tokens are sampled in-launch and their K/V
                # written by the chained decode steps
                tok_valid[s : s + v] = True
                row_last[slot] = s + v - 1
                kv_lens[slot] = pre + v
            if tree_parents is not None and n_spec:
                # flat per-token ancestor lists for the kernel's tree mask
                # (ops.paged_attention.tree_ancestors layout): every
                # non-tree token keeps the -2 plain-causal sentinel
                from ..ops.paged_attention import tree_ancestors

                tree_anc = np.full((tpad, k_ + 1), -1, np.int32)
                tree_anc[:, 0] = -2
                for slot in np.nonzero(spec_any)[0]:
                    slot = int(slot)
                    s = int(starts[slot])
                    tree_anc[s : s + k_ + 1] = tree_ancestors(
                        tree_parents[slot], int(tree_n[slot]),
                        width=k_ + 1,
                    )
                plan["tree_anc"] = tree_anc
            if n_spec:
                row_logit_idx = np.zeros(
                    (self.max_batch, k_ + 1), np.int32
                )
                for slot in range(self.max_batch):
                    if row_lens[slot] > 0:
                        row_logit_idx[slot] = starts[slot] + np.minimum(
                            np.arange(k_ + 1), row_lens[slot] - 1
                        )
            else:
                row_logit_idx = None
            plan.update(
                tokens=tokens, tok_pos=tok_pos, tok_row=tok_row,
                tok_valid=tok_valid, row_last=row_last, kv_lens=kv_lens,
                pre_lens=pre_lens, row_starts=starts, row_lens=row_lens,
                span_lens=span_lens, spans=spans,
                row_logit_idx=row_logit_idx,
                write_page=np.zeros(tpad, np.int32),
                write_offset=np.zeros(tpad, np.int32),
                block_rows=(
                    jnp.asarray(block_rows) if self._ragged_on_tpu else None
                ),
                block_q0=(
                    jnp.asarray(block_q0) if self._ragged_on_tpu else None
                ),
            )
        else:
            # dense ragged: the rectangular chunk layout [B, C] — C buckets
            # to the next power of two of the widest chunk so traces stay
            # bounded (log2(budget) shapes per variant). Decode rows keep a
            # 1-token chunk (their multi-step window chains through
            # bundle.decode in the same launch); spec rows carry the whole
            # k+1 candidate chain.
            c_need = max([take for _, take in shares], default=1)
            if n_spec:
                c_need = max(c_need, k_ + 1)
            c = 1
            while c < c_need:
                c *= 2
            tokens = np.zeros((self.max_batch, c), np.int32)
            start = np.zeros(self.max_batch, np.int32)
            last_rel = np.zeros(self.max_batch, np.int32)
            row_active = np.zeros(self.max_batch, bool)
            for slot in np.nonzero(decode_mask)[0]:
                slot = int(slot)
                request = self._slot_req[slot]
                tokens[slot, 0] = self._next_token[slot]
                if spec_any[slot]:
                    tokens[slot, 1 : k_ + 1] = drafts[slot]
                    last_rel[slot] = k_
                # dense cache length = prompt_len + produced - 1 (the
                # pending token's KV is written by the step consuming it)
                start[slot] = request.prompt_len + request.produced - 1
                row_active[slot] = True
            for job, take in shares:
                tokens[job.slot, :take] = job.request.prompt_ids[
                    job.pos : job.pos + take
                ]
                start[job.slot] = job.pos
                last_rel[job.slot] = take - 1
                row_active[job.slot] = True
            if n_spec:
                row_logit_idx = np.minimum(
                    np.arange(k_ + 1)[None, :], last_rel[:, None]
                ).astype(np.int32)
                plan["row_logit_idx"] = row_logit_idx
            else:
                plan["row_logit_idx"] = None
            for job in self._prefill_jobs:
                if not row_active[job.slot]:
                    # budget-starved job rows still get their garbage chunk
                    # window WRITTEN (the dense layer loop writes every
                    # row): pin it to job.pos so it lands where the job's
                    # next chunk overwrites it before any read — at the
                    # default start=0 it would clobber already-written
                    # prompt KV. (In-order whole-budget serving currently
                    # implies a starved job has pos == 0, but correctness
                    # must not hang on that scheduling subtlety.)
                    start[job.slot] = job.pos
            plan.update(
                tokens=tokens, start=start, last_rel=last_rel,
                row_active=row_active, chunk=c,
            )
        if faults.active():
            # yield-point seam parity with _prepare_dispatch: snapshot
            # complete, worker not yet started
            faults.fire("engine.dispatch.prepare", requests=plan["requests"])
        return plan

    def _ragged_drop_row(self, plan: dict, slot: int) -> None:
        """Worker-side removal of a row whose page extension failed: its
        tokens become pads (null-page writes, masked compute); the retire
        stage fails the decode request / admission job it carried."""
        s, n = plan["spans"].pop(slot)
        plan["tokens"][s : s + n] = 0
        plan["tok_pos"][s : s + n] = 0
        plan["tok_row"][s : s + n] = 0
        plan["tok_valid"][s : s + n] = False
        plan["row_lens"][slot] = 0
        plan["span_lens"][slot] = 0
        plan["kv_lens"][slot] = plan["pre_lens"][slot]
        plan["row_last"][slot] = 0
        plan["row_steps"][slot] = 0
        plan["spec_mask"][slot] = False
        plan["sspec_mask"][slot] = False
        if plan["chain_mask"] is not None:
            plan["chain_mask"][:, slot] = False
        if plan["row_logit_idx"] is not None:
            plan["row_logit_idx"][slot] = 0
        if plan.get("tree_anc") is not None:
            # the dropped verify row's pad tokens revert to plain-causal
            # sentinels (they are never live queries, but the mask arrays
            # must not carry a freed row's topology into the launch)
            plan["tree_anc"][s : s + n] = -1
            plan["tree_anc"][s : s + n, 0] = -2
        if plan["decode_mask"][slot]:
            plan["decode_mask"][slot] = False
            plan["exhausted"].append(slot)
        else:
            job = next(j for j, _ in plan["shares"] if j.slot == slot)
            plan["failed_jobs"].append((
                job,
                MemoryError("kv page pool exhausted during ragged admission"),
            ))

    def _dispatch_ragged_device(self, plan: dict) -> dict:
        """Worker-thread half of a ragged step: page allocation for every
        row's chunk plus the ONE device launch (donated pools/cache,
        rebound under the dispatch lock — same discipline as the legacy
        dispatch workers)."""
        with self._sentry_scope("ragged", seq=plan["seq"]):
            return self._dispatch_ragged_device_inner(plan)

    def _dispatch_ragged_device_inner(self, plan: dict) -> dict:
        t0 = time.perf_counter()
        if faults.active():
            # chaos seam, BEFORE any device work: a per-request poison
            # fails only its row's request/job, never the launch
            faults.fire("engine.decode", requests=plan["requests"])
        use_extras = plan["use_extras"]
        gtables = plan["gtables"]
        want_lp = plan["want_lp"]
        launch_steps = plan["launch_steps"]

        def _spec_arrays():
            # built AFTER any pool-exhaustion drops: _ragged_drop_row edits
            # the host masks/indices in place and the device copies must
            # see the post-drop state
            if plan["row_logit_idx"] is None:
                return None
            return (
                jnp.asarray(plan["spec_mask"].copy()),
                jnp.asarray(plan["sspec_mask"].copy()),
                jnp.asarray(plan["drafts"]),
                jnp.asarray(plan["row_logit_idx"]),
                plan["spec_rng"],
            )

        def _tree_arrays():
            # tree topology operands (docs/spec_decode_trees.md), also
            # post-drop: a dropped verify row's masks are already False
            # and its ancestor rows reverted to plain-causal sentinels
            if plan.get("tree_anc") is None or plan["row_logit_idx"] is None:
                return None
            return (
                jnp.asarray(plan["tree_tokens"]),
                jnp.asarray(plan["tree_parents"]),
                jnp.asarray(plan["tree_n"]),
                jnp.asarray(plan["tree_anc"]),
            )

        if self.cache_mode == "paged":
            pool = self.paged_cache.pool
            for slot in list(plan["spans"]):
                s, n = plan["spans"][slot]
                try:
                    # surplus rides the slot: _retire_ragged truncates to
                    # what the window kept; _ragged_recover rolls back to
                    # pre_lens on a tripped step (cross-function pairing
                    # the ownership ledger audits)
                    pool.extend(slot, n)  # tpuserve: ignore[TPU701] rolled back at retire/recover
                except MemoryError:
                    self._ragged_drop_row(plan, slot)
                    continue
                coords = pool.token_coords(
                    slot, int(plan["pre_lens"][slot]), n
                )
                for i, (page, offset) in enumerate(coords):
                    plan["write_page"][s + i] = page
                    plan["write_offset"][s + i] = offset
            chain_arrays = None
            if launch_steps > 1:
                # multi-step decode rows: the reserved span positions 1..
                # become the chained steps' per-step write coordinates —
                # the mixed pass writes only position 0 (the others go to
                # the null page there, exactly like any pad)
                chain_wp = np.zeros(
                    (launch_steps - 1, self.max_batch), np.int32
                )
                chain_wo = np.zeros(
                    (launch_steps - 1, self.max_batch), np.int32
                )
                for slot, (s, n) in plan["spans"].items():
                    if (
                        not plan["decode_mask"][slot]
                        or plan["spec_mask"][slot]
                        or plan["sspec_mask"][slot]
                        or n <= 1
                    ):
                        continue
                    for i in range(1, n):
                        chain_wp[i - 1, slot] = plan["write_page"][s + i]
                        chain_wo[i - 1, slot] = plan["write_offset"][s + i]
                        plan["write_page"][s + i] = 0
                        plan["write_offset"][s + i] = 0
                chain_arrays = (
                    plan["step_rngs"],
                    jnp.asarray(plan["chain_mask"].copy()),
                    jnp.asarray(chain_wp),
                    jnp.asarray(chain_wo),
                )
            self.paged_cache.apply_pending_cow()
            page_table = pool.page_table(self._pages_per_seq)
            with self.paged_cache.dispatch_lock:
                (
                    sampled, logits,
                    self.paged_cache.k, self.paged_cache.v,
                    new_ks, new_vs, new_counts, lp, gstate_out,
                    spec_g, spec_acc,
                ) = self._ragged_paged_jit(
                    self.params,
                    jnp.asarray(plan["tokens"]),
                    jnp.asarray(plan["tok_pos"]),
                    jnp.asarray(plan["tok_row"]),
                    jnp.asarray(plan["tok_valid"]),
                    jnp.asarray(plan["row_last"]),
                    self.paged_cache.k,
                    self.paged_cache.v,
                    self.paged_cache.k_scale,
                    self.paged_cache.v_scale,
                    jnp.asarray(page_table),
                    jnp.asarray(plan["kv_lens"]),
                    jnp.asarray(plan["row_starts"]),
                    jnp.asarray(plan["row_lens"]),
                    jnp.asarray(plan["write_page"]),
                    jnp.asarray(plan["write_offset"]),
                    plan["block_rows"],
                    plan["block_q0"],
                    jnp.asarray(plan["decode_mask"].copy()),
                    plan["sampling"],
                    plan["rng"],
                    plan["lora"],
                    plan["extras"],
                    self._counts_dev if use_extras else None,
                    self._pmask_dev if use_extras else None,
                    gtables,
                    plan["gstate"],
                    want_lp=want_lp,
                    spec=_spec_arrays(),
                    chain=chain_arrays,
                    tree=_tree_arrays(),
                )
                if self._paged_quant:
                    self.paged_cache.k_scale = new_ks
                    self.paged_cache.v_scale = new_vs
        else:
            chain_arrays = None
            if launch_steps > 1:
                chain_arrays = (
                    plan["step_rngs"],
                    jnp.asarray(plan["chain_mask"].copy()),
                )
            (
                sampled, logits, self.cache, new_counts, lp, gstate_out,
                spec_g, spec_acc,
            ) = self._ragged_dense_jit(
                self.params,
                jnp.asarray(plan["tokens"]),
                jnp.asarray(plan["start"]),
                jnp.asarray(plan["last_rel"]),
                jnp.asarray(plan["row_active"]),
                self.cache,
                jnp.asarray(plan["decode_mask"].copy()),
                plan["sampling"],
                plan["rng"],
                plan["lora"],
                plan["extras"],
                self._counts_dev if use_extras else None,
                self._pmask_dev if use_extras else None,
                gtables,
                plan["gstate"],
                want_lp=want_lp,
                spec=_spec_arrays(),
                chain=chain_arrays,
            )
        if use_extras:
            self._counts_dev = new_counts
        # finishing-row logit gather: keep only rows whose admission
        # completes this step (minus any the pool-exhaustion path dropped)
        # — the [R, vocab] matrix never crosses the device boundary
        finish = [
            s for s in plan["finish_slots"]
            if self.cache_mode != "paged" or s in plan["spans"]
        ]
        if finish:
            pad = 1 << (len(finish) - 1).bit_length()
            rows = np.zeros(pad, np.int32)
            rows[: len(finish)] = finish
            logits = self._gather_finish_jit(logits, jnp.asarray(rows))
        else:
            logits = None
        self._last_progress = time.monotonic()
        self._hist_dispatch.observe((time.perf_counter() - t0) * 1e3)
        return {
            "sampled": sampled,
            "logits": logits,
            "lp": lp,
            "gstate": gstate_out if gtables is not None else None,
            "finish_rows": finish,
            "spec_g": spec_g,
            "spec_acc": spec_acc,
        }

    async def _ragged_step(self, active_mask: np.ndarray, epoch: int) -> None:
        """One ragged scheduling iteration (docs/ragged_attention.md): ONE
        device launch carries every decode row (one token each) plus as
        many prefill-chunk rows as fit the step token budget — admissions
        no longer stall the decode loop, they share its launches. Serial
        dispatch -> sync -> emit; the pipelined in-flight queue resumes
        the moment the admission backlog drains."""
        # post-ragged decode must re-upload the host mirrors: the device
        # chains were built by the (drained) pipelined path
        self._reset_device_chains()
        plan = self._prepare_ragged(active_mask, epoch)
        if plan is None:
            return
        self._dispatching = (plan["seq"], plan["decode_mask"], time.monotonic())
        try:
            result = await asyncio.to_thread(self._dispatch_ragged_device, plan)
        except asyncio.CancelledError:
            raise
        except BaseException as ex:
            req = getattr(ex, "request", None)
            job = (
                next(
                    (j for j in self._prefill_jobs if j.request is req), None
                )
                if req is not None
                else None
            )
            if job is not None:
                # per-request fault attributed to an admission row: the
                # seam fires before any device work, so decode rows lost
                # nothing — fail only the job; next iteration re-plans
                self.counters["step_failures"] += 1
                self._fail_ragged_job(job, EngineStepError(
                    "ragged admission chunk failed for this request: "
                    "{}".format(ex)
                ))
                return
            raise
        finally:
            self._dispatching = None
        if epoch != self._recover_epoch:
            await self._ragged_recover(plan, result)
            return
        self._retire_ragged(plan, result)

    async def _ragged_recover(self, plan: dict, result: dict) -> None:
        """The watchdog tripped while this ragged step was mid-worker: the
        decode results are stale (those requests were already failed) and
        no commit may run. Wait out the device program off-thread, roll
        surviving jobs' page extensions back to their pre-step lengths
        (the next step redoes the chunk cleanly — its K/V rewrites are
        value-identical), then run the shared recovery."""

        def _wait():
            try:
                jax.block_until_ready(result["sampled"])
            except Exception:
                pass

        await asyncio.to_thread(_wait)
        if self.paged_cache is not None:
            pool = self.paged_cache.pool
            for job, _take in plan["shares"]:
                if job in self._prefill_jobs:  # identity compare
                    pool.truncate(job.slot, int(plan["pre_lens"][job.slot]))
        await self._finish_recovery()

    def _retire_ragged(self, plan: dict, result: dict) -> None:
        """Loop-thread tail of a ragged step: decode emissions re-anchor
        the host mirrors exactly like a pipelined retire — a q=N decode
        row emits its whole window in order under the MID-WINDOW EOS MASK
        (a row finishing inside its window delivers the tokens up to the
        stop and drops the surplus; the q=1 path simply stopped
        launching), a spec-verify row emits its accepted chain after the
        pool rolls its over-allocation back to what the verify kept, and
        finishing prefill jobs sample their first token (the legacy
        admission code path) and activate their slot."""
        t0 = time.perf_counter()
        sampled = np.asarray(result["sampled"])
        if sampled.ndim == 1:
            sampled = sampled[None]               # step-major [S, B]
        gstate_np = (
            np.array(result["gstate"]) if result["gstate"] is not None else None
        )
        lp_np = (
            tuple(np.asarray(a) for a in result["lp"])
            if result["lp"] is not None
            else None
        )
        if lp_np is not None and lp_np[0].ndim == 1:
            lp_np = tuple(a[None] for a in lp_np)  # step-major [S, B, ...]
        spec_acc = (
            np.asarray(result["spec_acc"])
            if result["spec_acc"] is not None
            else None
        )
        spec_g = (
            np.asarray(result["spec_g"])
            if result["spec_g"] is not None
            else None
        )
        spec_any = plan["spec_mask"] | plan["sspec_mask"]
        # the per-request retire fault on a MULTI-TOKEN row fails the
        # request with its partial window delivered (all but the last
        # token): the tokens were already sampled device-side and the
        # failure is a host-emission failure, not a compute one
        partial: Dict[int, BaseException] = {}
        if faults.active():
            try:
                faults.fire("engine.decode.retire", requests=plan["requests"])
            except faults.InjectedFault as ex:
                if ex.request is None:
                    raise  # batch-wide: loop-level step-failure handling
                self.counters["step_failures"] += 1
                handled = False
                for slot, request in enumerate(self._slot_req):
                    if request is not ex.request:
                        continue
                    window = (
                        int(spec_acc[slot]) + 1
                        if spec_acc is not None and spec_any[slot]
                        else int(plan["row_steps"][slot])
                    )
                    err = EngineStepError(
                        "retire failed for this request: {}".format(ex)
                    )
                    if plan["decode_mask"][slot] and window > 1:
                        partial[slot] = err
                    else:
                        self._fail_slot(slot, err)
                    handled = True
                    break
                if not handled:
                    job = next(
                        (
                            j for j, _ in plan["shares"]
                            if j.request is ex.request
                        ),
                        None,
                    )
                    if job is not None:
                        plan["failed_jobs"].append((job, EngineStepError(
                            "retire failed for this request: {}".format(ex)
                        )))
        for slot in plan["exhausted"]:
            self._fail_slot(
                slot, MemoryError("kv page pool exhausted for this sequence")
            )
        decode_slots = [int(s) for s in np.nonzero(plan["decode_mask"])[0]]
        plain_slots = [s for s in decode_slots if not spec_any[s]]
        spec_slots = [s for s in decode_slots if spec_any[s]]
        if spec_slots and self.cache_mode == "paged":
            # roll each verify row's over-allocation back to the tokens the
            # acceptance actually kept (pending + accepted drafts). BEFORE
            # emission: _emit frees a finishing slot's pages entirely. A
            # slot the retire fault already failed (its 1-token window made
            # the failure immediate) freed its pages wholesale — nothing
            # left to truncate
            pool = self.paged_cache.pool
            for slot in spec_slots:
                if self._slot_req[slot] is None:
                    continue
                pool.truncate(
                    slot,
                    int(plan["pre_lens"][slot]) + 1 + int(spec_acc[slot]),
                )
        emitted_decode = 0

        def _window_emit(slot, toks, lp_of_step):
            """Emit one row's window in order; the mid-window EOS mask is
            the break on a freed slot — _emit finishes the request on a
            stop token / max-token / max-seq bound and the surplus never
            reaches the stream. Returns tokens delivered."""
            nonlocal emitted_decode
            fail_err = partial.pop(slot, None)
            delivered = 0
            for i, tok in enumerate(toks):
                if fail_err is not None and i == len(toks) - 1:
                    self._fail_slot(slot, fail_err)
                    return delivered
                request = self._slot_req[slot]
                if request is None:
                    break                      # mid-window EOS mask
                if self._tokbuf is not None:
                    # speculation history stays warm through ragged phases
                    # so the n-gram proposer keeps drafting well
                    idx = request.prompt_len + request.produced
                    if idx < self._tokbuf.shape[1]:
                        self._tokbuf[slot, idx] = tok
                self._emit(slot, tok, lp_of_step(i, request))
                delivered += 1
                emitted_decode += 1
            return delivered

        for slot in plain_slots:
            n = int(plan["row_steps"][slot])
            if n <= 0:
                continue

            def _lp_entry(i, request, slot=slot):
                if lp_np is None or request.logprobs is None:
                    return None
                chosen, top_id, top_lp = lp_np
                return {
                    "id": int(sampled[i, slot]),
                    "logprob": float(chosen[i, slot]),
                    "top_ids": top_id[i, slot].tolist(),
                    "top_logprobs": top_lp[i, slot].tolist(),
                }

            _window_emit(
                slot, [int(sampled[i, slot]) for i in range(n)], _lp_entry
            )
            if self._slot_req[slot] is not None:
                # the window's last token is the next launch's pending one
                self._next_token[slot] = int(sampled[n - 1, slot])
                if gstate_np is not None:
                    self._gstate[slot] = int(gstate_np[slot])
        accept_fracs = []
        for slot in spec_slots:
            acc = int(spec_acc[slot])
            accept_fracs.append(acc / max(1, plan["spec_k"]))
            if self._spec_tree:
                # accepted PATH DEPTH per tree verify row — the headline
                # engine_spec_tree_accept_depth reads at scrape time
                self._hist_spec_tree_depth.observe(acc)
            _window_emit(
                slot,
                [int(spec_g[slot, i]) for i in range(acc + 1)],
                lambda i, request: None,
            )
            if self._slot_req[slot] is not None:
                self._next_token[slot] = int(spec_g[slot, acc])
        for slot, err in partial.items():
            # defensive: a deferred partial-window failure whose row never
            # emitted (dropped between planning and retire) still fails
            self._fail_slot(slot, err)
        failed = [j for j, _ in plan["failed_jobs"]]
        live_shares = [
            (j, t) for j, t in plan["shares"]
            if not any(j is f for f in failed)
        ]
        self.counters["ragged_steps"] += 1
        self.counters["ragged_decode_tokens"] += emitted_decode
        self._step_rows["decode"] += len(plain_slots)
        self._step_rows["spec_verify"] += len(spec_slots)
        self._step_rows["prefill"] += len(live_shares)
        if plain_slots or spec_slots:
            self._hist_launch_tokens.observe(emitted_decode)
        if accept_fracs:
            self._hist_spec_accept.observe(
                sum(accept_fracs) / len(accept_fracs)
            )
        used = (
            int(plan["row_steps"].sum())
            + (plan["spec_k"] + 1) * len(spec_slots)
            + sum(t for _, t in live_shares)
        )
        self._hist_budget.observe(used / max(1, plan["budget"]))
        for job, err in plan["failed_jobs"]:
            self._fail_ragged_job(job, err)
        logits_np = None
        for job, take in live_shares:
            if job not in self._prefill_jobs:  # failed since planning
                continue
            job.pos += take
            if job.pos < len(job.request.prompt_ids):
                # draft-ahead KV shipping: the chunk boundary just made
                # whole storable pages final — overlap the transport with
                # the remaining prefill (docs/spec_decode_trees.md)
                self._maybe_ship_draft(job)
                continue
            # final chunk landed: the row's last-token logits are the
            # prompt's prefill logits — first token + slot activation
            request = job.request
            self._prefill_jobs.remove(job)
            self._admitting.discard(job.slot)
            if request.cancelled:
                self._deref_guided_request(request)
                request.out_queue.put_nowait(_FINISHED)
                self._free_ragged_slot(job.slot)
                continue
            err = self._deadline_error_at_commit(request)
            if err is not None:
                self._deref_guided_request(request)
                request.error = err
                request.out_queue.put_nowait(_FINISHED)
                self._free_ragged_slot(job.slot)
                continue
            if logits_np is None:
                # [F, vocab]: only the finishing rows were read back
                logits_np = np.asarray(result["logits"])
                finish_index = {
                    s: i for i, s in enumerate(result["finish_rows"])
                }
            first_id, first_lp = self._first_token_from_logits(
                request, jnp.asarray(logits_np[finish_index[job.slot]][None])
            )
            if self.cache_mode == "paged" and self._prefix is not None:
                # zero-copy store, same point as the legacy commit: the
                # slot's own pages now hold the whole prompt's KV
                self._prefix.store_pages(
                    request.prompt_ids,
                    self._slot_lora(request),
                    self.paged_cache.pool.slot_pages(job.slot),
                )
                # disaggregated ship-at-commit, ragged scheduler's commit
                # point (docs/disaggregation.md)
                self._maybe_ship(request, job.slot)
            self._activate_slot(request, job.slot, first_id, first_lp)
        # retire-stage promotion reap, same rule as the pipelined retire
        self._reap_promotions()
        self._last_progress = time.monotonic()
        self._hist_retire.observe((time.perf_counter() - t0) * 1e3)

    async def _run_loop(self) -> None:
        try:
            await self._run_loop_inner()
        except BaseException as ex:
            self._fail_all(ex)
            self._drain_ready(ex)
            raise
        finally:
            if self._prefill_gate is not None:
                # no decode loop -> nothing to pace against; unblock waiters
                self._prefill_gate.set_active(False)
            # ragged scheduler: no loop means no further chunk rows — fail
            # in-progress jobs and prepped-but-unopened admissions (their
            # consumers must never hang; slot pages reclaimed below)
            exit_err = EngineUnavailableError(
                "engine stopped" if self._stopped else "engine loop exited"
            )
            if self._prefill_jobs:
                self._abort_ragged_jobs(exit_err)
            self._drain_ragged_ready(exit_err)
            if self._stopped:
                # catch requests admitted while stop() was racing the loop
                # (popped from _pending before stop drained it)
                self._fail_all(EngineUnavailableError("engine stopped"))
                self._drain_ready(EngineUnavailableError("engine stopped"))
            # loop exit: the pipeline dies with the loop — no retire will
            # ever run, so drop the queue and its deferred frees here,
            # waiting out still-executing chunks off-thread before their
            # pages recycle (skipped on hard cancellation = teardown)
            dropped = list(self._inflight)
            self._inflight.clear()
            if self._ledger is not None:
                for slot in self._quarantine:
                    lifecycle_ledger.release("slot.quarantine", key=slot,
                                             domain=self, all_of_key=True)
            self._quarantine.clear()
            self._reset_device_chains()
            if self.paged_cache is not None and dropped:
                try:
                    await asyncio.to_thread(self._wait_chunks, dropped)
                except BaseException:
                    pass
            if self.paged_cache is not None:
                # loop exit = no worker thread alive -> safe to reclaim every
                # slot whose request was failed out without freeing its pages
                for slot in range(self.max_batch):
                    if self._slot_req[slot] is None:
                        self.paged_cache.pool.free(slot)
            self._recovering = False
            if self._stopped and self._watchdog_task is not None:
                # engine shut down for good: stop the supervisor too (a
                # drained-but-live engine keeps it — cancelling here would
                # race _ensure_loop's restart check on the next request)
                self._watchdog_task.cancel()

    async def _run_loop_inner(self) -> None:
        """The continuous-batching loop: admit (overlapped) -> decode -> emit.

        Admission prefills run as background tasks in worker threads, so
        decode chunks keep dispatching while long prompts prefill; only the
        cheap cache-insert commit synchronizes with the loop (chunk
        boundaries). TTFT no longer serializes behind other admissions, and
        decode throughput does not stall during admission (VERDICT r1 #6)."""
        self._wake = asyncio.Event()
        while not self._stopped:
            # deadline sweep: queued requests expire where they wait
            self._expire_pending()
            # host-tier promotions that completed since the last boundary
            # (docs/kv_tiering.md): cheap no-op without in-flight DMAs
            self._reap_promotions()
            # SLO scheduling (docs/slo_scheduling.md): refresh the brownout
            # stage from the pressure signals, then — under slot pressure
            # with interactive work queued — preempt one batch-lane slot at
            # this chunk boundary before admissions run
            self._update_brownout()
            self._maybe_preempt()
            # launch admissions for pending requests into reserved free slots
            # (quarantined slots stay unavailable: an in-flight chunk still
            # decodes their previous occupant — docs/pipelined_decode.md)
            free = [
                i
                for i, r in enumerate(self._slot_req)
                if r is None
                and i not in self._admitting
                and i not in self._quarantine
            ]
            while free and not self._pending.empty():
                request = self._pending.get_nowait()
                if request.cancelled:
                    self._release_resume_pin(request)
                    request.out_queue.put_nowait(_FINISHED)
                    continue
                slot = free.pop(0)
                self._admitting.add(slot)
                # hold a strong ref: the loop keeps only weak refs to tasks,
                # so an unreferenced admission could be GC'd mid-flight,
                # leaving the slot stuck in _admitting forever. Ragged mode
                # routes to the chunk-row admission (no prefill dispatch).
                task = asyncio.get_running_loop().create_task(
                    self._ragged_admission_task(request, slot)
                    if self._ragged
                    else self._admission_task(request, slot)
                )
                self._admission_tasks.add(task)
                task.add_done_callback(self._admission_tasks.discard)
            # commit finished prefills (loop thread; between decode chunks).
            # Interactive commits land first: a commit IS the first token,
            # so class order holds at this boundary too, not just at the
            # queue pop (docs/slo_scheduling.md)
            ready_batch = []
            while not self._ready.empty():
                ready_batch.append(self._ready.get_nowait())
            if len(ready_batch) > 1:
                ready_batch.sort(
                    key=lambda item: _CLASS_RANK.get(item[0].priority, 0)
                )
            for request, slot, first_id, mini_cache, first_lp in ready_batch:
                self._admitting.discard(slot)
                if request.cancelled:
                    self._deref_guided_request(request)
                    self._release_prefix_hit(request)
                    request.out_queue.put_nowait(_FINISHED)
                    continue
                err = self._deadline_error_at_commit(request)
                if err is not None:
                    # prefill outlived the request's ttft/total budget:
                    # structured 408 instead of a pointless slot commit
                    self._deref_guided_request(request)
                    self._release_prefix_hit(request)
                    request.error = err
                    request.out_queue.put_nowait(_FINISHED)
                    continue
                self._commit_admission(request, slot, first_id, mini_cache, first_lp)
                self._last_progress = time.monotonic()
            # ragged scheduler: open jobs for prepped admissions — their
            # prompts start riding this loop's launches as chunk rows
            while not self._ragged_ready.empty():
                request, slot = self._ragged_ready.get_nowait()
                if request.cancelled or request.error is not None:
                    self._release_resume_pin(request)
                    self._deref_guided_request(request)
                    request.out_queue.put_nowait(_FINISHED)
                    self._admitting.discard(slot)
                    continue
                job = self._start_ragged_job(request, slot)
                if job is not None:
                    self._prefill_jobs.append(job)
                    self._last_progress = time.monotonic()
            active_mask = np.array([r is not None for r in self._slot_req])
            if self._prefill_gate is not None:
                # open the gate while decode idles; pace prefills while active
                self._prefill_gate.set_active(
                    bool(active_mask.any() or self._inflight)
                )
            if (
                not active_mask.any()
                and not self._inflight
                and not self._prefill_jobs
            ):
                if (
                    self._pending.empty()
                    and self._ready.empty()
                    and not self._admitting
                ):
                    # drained: nothing owns pages but the prefix cache —
                    # anything else is a leak the sanitizer names by id
                    if faults.active():
                        # yield-point seam: the drained boundary, before
                        # the leak audit
                        faults.fire("engine.drain")
                    # straggler promotion DMAs must settle before the
                    # drained audit (and before the loop parks)
                    self._reap_promotions(force=True)
                    self._sanitize("drain", drained=True)
                    return  # drained; a new generate() restarts the loop
                # idle but admissions in flight: sleep until a prefill lands
                # or a new request arrives (no busy-spin)
                await self._wake.wait()
                self._wake.clear()
                continue
            # pipelined decode over the whole slot batch, supervised: a
            # dispatch/retire exception fails only the affected request(s)
            # and a watchdog trip (epoch bump) discards the whole in-flight
            # queue — the loop itself survives both and keeps serving
            step_epoch = self._recover_epoch
            try:
                if self._prefill_jobs or self._ragged_spec_wanted(active_mask):
                    # ragged scheduling phase (docs/ragged_attention.md):
                    # drain the pipelined queue first (host mirrors must be
                    # current — same rule the legacy spec step used), then
                    # each iteration is ONE mixed launch of every decode
                    # row (multi-step windows), spec-verify row, and
                    # budget-bounded prefill-chunk row. With speculation
                    # on, spec rows ride these launches — the serial
                    # pipeline-draining spec scan never runs here.
                    if self._inflight:
                        await self._retire_oldest()
                    else:
                        await self._ragged_step(active_mask, step_epoch)
                else:
                    await self._decode_step(active_mask, step_epoch)
            except asyncio.CancelledError:
                raise
            except Exception as ex:
                await self._handle_step_failure(ex, step_epoch)
            # armed sanitizer: audit page accounting after every step —
            # including steps that just went through failure recovery, which
            # is exactly where reclamation bugs hide. A violation raises out
            # of the loop (fail loud beats serving corrupted KV).
            self._sanitize("decode-step")
            await asyncio.sleep(0)  # let HTTP handlers interleave


    # -- pipelined decode: dispatch / retire ----------------------------------

    async def _decode_step(self, active_mask: np.ndarray, epoch: int) -> None:
        """One pipelined scheduling step. The in-flight queue fills to
        ``pipeline_depth - 1`` chunks, then every iteration OVERLAPS the
        oldest chunk's retirement (device->host readback + token emission,
        host work) with the next chunk's dispatch, which runs in a worker
        thread — on backends whose dispatch is asynchronous (TPU) the
        worker only enqueues; on backends that execute inline (current
        XLA:CPU) the worker carries the device compute itself. Either way
        chunk N's emission and chunk N+1's compute proceed concurrently,
        and the cross-chunk token dependency stays device-resident. At
        depth 1 this degenerates to the historical serial
        dispatch->sync->emit loop.

        Speculative chunks already amortize dispatch over k+1 verify
        positions and stay serial; they drain the pipeline first so the
        host-side token history they feed from is fully retired."""
        spec_masks = (
            self._spec_eligible_mask(active_mask)
            if self._speculation
            and active_mask.any()
            # the ragged scheduler never takes the serial spec scan: spec
            # rides its mixed launches as q=k+1 verify rows instead
            # (_ragged_spec_wanted routes those phases to _ragged_step)
            and not self._ragged
            # brownout stage 1+ parks speculation: the verify slack's page
            # over-allocation and the k wasted positions per reject are
            # exactly the headroom an overloaded engine no longer has
            and (self._brownout is None or self._brownout.stage < 1)
            else None
        )
        if spec_masks is not None and bool(
            spec_masks[0].any() or spec_masks[1].any()
        ):
            if self._inflight:
                # drain one chunk per step; commits keep landing between
                # steps at the loop top, as at any retire boundary
                await self._retire_oldest()
                return
            self._reset_device_chains()
            await self._spec_step(active_mask, spec_masks, epoch)
            return
        # fill: depth-1 keeps exactly one dispatch outstanding; deeper
        # pipelines keep depth-1 chunks queued ahead of the retire stage
        fill_target = max(1, self.pipeline_depth - 1)
        dispatch_mask = self._dispatchable_mask(active_mask)
        while dispatch_mask.any() and len(self._inflight) < fill_target:
            await self._dispatch_or_recover(dispatch_mask.copy(), epoch)
            # a dispatch can fail slots host-side (paged pool exhaustion):
            # drop them from the mask before topping up further
            active_mask &= np.array([r is not None for r in self._slot_req])
            dispatch_mask = self._dispatchable_mask(active_mask)
        if not self._inflight:
            return
        # the retiring chunk stays in the deque until its emissions land:
        # the concurrent dispatch's prep must still count its undelivered
        # steps (seeded-sampling counters, predictable-finish masking)
        entry = self._inflight[0]
        if dispatch_mask.any() and len(self._inflight) < self.pipeline_depth:
            # steady state: dispatch chunk N+1 (worker thread) while chunk
            # N retires (loop thread + readback worker) — the overlap that
            # hides the per-chunk host work behind device compute
            dispatch_res, retire_res = await asyncio.gather(
                self._dispatch_async(dispatch_mask.copy(), epoch),
                self._retire_chunk(entry),
                return_exceptions=True,
            )
            if self._inflight and self._inflight[0] is entry:
                self._inflight.popleft()
            # surface failures AFTER both stages settled (no orphaned
            # worker mutating engine state during recovery). A retire
            # failure reaching here is batch-wide (per-request retire
            # faults are isolated inside _retire_chunk) and outranks a
            # dispatch error: chunk N's tokens are lost for EVERY stream,
            # so the batch-wide reset must run even when the dispatch also
            # failed — raising only the dispatch error would silently skip
            # decode_steps tokens for the surviving requests.
            if isinstance(retire_res, BaseException):
                raise retire_res
            if isinstance(dispatch_res, BaseException):
                await self._recover_failed_dispatch()
                raise dispatch_res
        else:
            await self._retire_oldest()

    async def _dispatch_or_recover(self, mask: np.ndarray, epoch: int) -> None:
        """Dispatch with failure recovery, for call sites where no retire
        runs concurrently (the gather branch recovers after both settle)."""
        try:
            await self._dispatch_async(mask, epoch)
        except asyncio.CancelledError:
            raise
        except BaseException:
            await self._recover_failed_dispatch()
            raise

    async def _recover_failed_dispatch(self) -> None:
        """A dispatch raised after its prep consumed the commit overrides
        and advanced the RNG, but no chunk landed: retire whatever is still
        in flight (their results are valid — the failure happened before or
        instead of a new device program) so the host mirrors are current,
        then forget the device chains so the next dispatch re-uploads from
        them. Without this, a poisoned dispatch would leave an innocent
        freshly-committed slot chaining a stale token."""
        while self._inflight:
            await self._retire_oldest()
        self._reset_device_chains()

    async def _retire_oldest(self) -> None:
        """Retire the oldest in-flight chunk; it leaves the queue only once
        its emissions landed (recovery may clear the queue mid-retire)."""
        entry = self._inflight[0]
        await self._retire_chunk(entry)
        if self._inflight and self._inflight[0] is entry:
            self._inflight.popleft()

    def _dispatchable_mask(self, active_mask: np.ndarray) -> np.ndarray:
        """Slots worth including in the NEXT chunk: active, and not already
        guaranteed to finish inside the chunks in flight. A request whose
        remaining max_new_tokens budget is covered by undelivered in-flight
        steps will be freed at an earlier retire — dispatching more compute
        for it is certain waste (stop-token finishes stay unpredictable and
        may still overshoot by design; their surplus tokens are dropped)."""
        if not self._inflight and self._dispatching is None:
            return active_mask
        pending_steps = np.zeros(self.max_batch, np.int64)
        for entry in self._inflight:
            pending_steps += entry.active_mask * self.decode_steps
        if self._dispatching is not None:
            pending_steps += self._dispatching[1] * self.decode_steps
        mask = active_mask.copy()
        for slot in np.nonzero(active_mask)[0]:
            request = self._slot_req[slot]
            if request is not None and (
                request.produced + pending_steps[slot]
                >= self._effective_max_new(request)
            ):
                mask[slot] = False
        return mask

    async def _dispatch_async(self, active_mask: np.ndarray, epoch: int) -> None:
        """Dispatch one chunk: shared host state is snapshotted on the loop
        thread (_prepare_dispatch), then the device call runs in a worker
        thread, possibly concurrently with the previous chunk's retirement.
        Appends the in-flight entry and fails pool-exhausted slots."""
        prep = self._prepare_dispatch(active_mask, epoch)
        # barrier visibility: a slot freed by the concurrent retire stage
        # must see this chunk before its entry lands in the queue. The
        # timestamp bounds the watchdog's compile-tolerance grace.
        self._dispatching = (prep["seq"], active_mask, time.monotonic())
        try:
            entry = await asyncio.to_thread(self._dispatch_device, prep)
        finally:
            self._dispatching = None
        if entry.epoch != self._recover_epoch:
            # the watchdog tripped while this chunk was being dispatched:
            # it was failed wholesale. Queue the entry so the discard path
            # waits out ITS device writes too, then reclaim.
            self._inflight.append(entry)
            await self._finish_recovery()
            return
        self._inflight.append(entry)
        for slot in entry.exhausted:
            self._fail_slot(
                slot, MemoryError("kv page pool exhausted for this sequence")
            )

    def _prepare_dispatch(self, active_mask: np.ndarray, epoch: int) -> dict:
        """Loop-thread half of a dispatch: snapshot every piece of shared
        host state the device call needs (slot table reads, device-constant
        caches, the chained token/DFA inputs, the RNG draw) so the worker
        thread never races the concurrently-running retire stage."""
        self._last_progress = time.monotonic()
        want_lp = any(
            self._slot_req[s] is not None
            and self._slot_req[s].logprobs is not None
            for s in np.nonzero(active_mask)[0]
        )
        use_extras = self._extras_active(active_mask)
        use_guided = bool(np.any(self._gstate[active_mask] >= 0))
        gtables = self._guided_device_tables() if use_guided else None
        tokens = self._chain_input(self._next_token_dev, self._next_token)
        gstate_in = (
            self._chain_input(self._gstate_dev, self._gstate)
            if gtables is not None
            else None
        )
        self._slot_overrides[:] = False
        self._dispatch_seq += 1
        if faults.active():
            # yield-point seam (docs/static_analysis.md, interleaving
            # explorer): the loop-thread snapshot is complete, the
            # worker-thread device call has not started — the boundary the
            # PR-4 host-buffer aliasing race lived on
            faults.fire(
                "engine.dispatch.prepare",
                requests=[r for r in self._slot_req if r is not None],
            )
        return {
            "seq": self._dispatch_seq,
            "epoch": epoch,
            "active_mask": active_mask,
            # copy: paged pool exhaustion mutates active_mask after this
            # (a zero-copy alias would flip the device value under the jit)
            "active_dev": jnp.asarray(active_mask.copy()),
            "want_lp": want_lp,
            "use_extras": use_extras,
            "sampling": self._batch_sampling(),
            "extras": self._batch_extras() if use_extras else None,
            "gtables": gtables,
            "gstate_in": gstate_in,
            "tokens": tokens,
            "rng": self._next_rng(),
            "lora": (
                jnp.asarray(self._lora_slots.copy())
                if self._lora_enabled
                else None
            ),
            "requests": [r for r in self._slot_req if r is not None],
        }

    def _dispatch_device(self, prep: dict) -> "_InFlightChunk":
        """Worker-thread half of a dispatch: the device program call (plus,
        on the paged backend, the host page allocation it needs). Only
        touches state the retire stage never reads: the cache/pool handles,
        the device-resident chains, and the dispatch histogram."""
        with self._sentry_scope("decode", seq=prep["seq"]):
            return self._dispatch_device_inner(prep)

    def _dispatch_device_inner(self, prep: dict) -> "_InFlightChunk":
        t0 = time.perf_counter()
        if faults.active():
            # chaos seam (BEFORE any device dispatch, so a per-request
            # poison never corrupts innocent slots' cache state)
            faults.fire("engine.decode", requests=prep["requests"])
        active_mask = prep["active_mask"]
        use_extras = prep["use_extras"]
        gtables = prep["gtables"]
        want_lp = prep["want_lp"]
        exhausted: List[int] = []
        if self.cache_mode == "paged":
            chunk, lp, gstate_out = self._dispatch_paged(prep, exhausted)
        else:
            chunk, self.cache, new_counts, lp, gstate_out = (
                self._decode_chunk_jit(
                    self.params,
                    prep["tokens"],
                    self.cache,
                    prep["active_dev"],
                    prep["sampling"],
                    prep["rng"],
                    prep["lora"],
                    prep["extras"],
                    self._counts_dev if use_extras else None,
                    self._pmask_dev if use_extras else None,
                    gtables,
                    prep["gstate_in"],
                    want_lp=want_lp,
                )
            )
            if use_extras:
                self._counts_dev = new_counts
        # device-resident chaining: the NEXT dispatch reads these without
        # any host sync (chunk[:, -1] is a lazy slice of the pending output)
        self._next_token_dev = chunk[:, -1]
        self._gstate_dev = gstate_out if gtables is not None else None
        self._last_progress = time.monotonic()
        self._hist_dispatch.observe((time.perf_counter() - t0) * 1e3)
        return _InFlightChunk(
            seq=prep["seq"],
            epoch=prep["epoch"],
            active_mask=active_mask,
            chunk=chunk,
            gstate=gstate_out if gtables is not None else None,
            lp=lp,
            want_lp=want_lp,
            dispatched_at=t0,
            exhausted=exhausted,
        )

    def _dispatch_paged(self, prep: dict, exhausted: List[int]):
        """Paged half of a chunk dispatch (worker thread). Pre-allocates
        each active slot's pages for the whole chunk host-side and hands
        the per-step write coordinates to the scan. Slots whose allocation
        fails are dropped from the chunk (their device rows write the null
        page; their tokens are discarded at retire) and reported through
        ``exhausted`` for the loop thread to fail — one sequence hitting
        pool capacity must not take the engine down."""
        active_mask = prep["active_mask"]
        pool = self.paged_cache.pool
        n = self.decode_steps
        lengths0 = pool.lengths().copy()          # pre-extension lengths
        write_pages = np.zeros((self.max_batch, n), np.int32)   # null page 0
        write_offsets = np.zeros((self.max_batch, n), np.int32)
        for slot in np.nonzero(active_mask)[0]:
            slot = int(slot)
            start = pool.slot_length(slot)
            try:
                # the chunk's decode_steps tokens land in these pages at
                # retire; a failed step frees them with the slot in the
                # loop's recovery (cross-function pairing the ownership
                # ledger audits)
                pool.extend(slot, n)  # tpuserve: ignore[TPU701] consumed by the chunk; recovery frees the slot
            except MemoryError:
                active_mask[slot] = False
                exhausted.append(slot)
                continue
            for i, (page, offset) in enumerate(pool.token_coords(slot, start, n)):
                write_pages[slot, i] = page
                write_offsets[slot, i] = offset
        # copy-on-write: extends may have swapped a shared tail page for a
        # private one; its contents must be duplicated before this chunk's
        # writes land in it (the copy consumes the in-flight chunk's output
        # pool handle, so ordering holds by data dependency)
        self.paged_cache.apply_pending_cow()
        page_table = pool.page_table(self._pages_per_seq)
        use_extras = prep["use_extras"]
        # dispatch under the pool lock: admission workers concurrently
        # enqueue prefix-page gathers against the same (here donated) pools
        with self.paged_cache.dispatch_lock:
            (
                chunk,
                self.paged_cache.k,
                self.paged_cache.v,
                new_k_scale,
                new_v_scale,
                new_counts,
                lp,
                gstate_out,
            ) = self._decode_paged_chunk_jit(
                self.params,
                prep["tokens"],
                self.paged_cache.k,
                self.paged_cache.v,
                self.paged_cache.k_scale,
                self.paged_cache.v_scale,
                jnp.asarray(page_table),
                jnp.asarray(lengths0),
                jnp.asarray(write_pages),
                jnp.asarray(write_offsets),
                prep["sampling"],
                prep["rng"],
                prep["lora"],
                prep["extras"],
                self._counts_dev if use_extras else None,
                self._pmask_dev if use_extras else None,
                prep["gtables"],
                prep["gstate_in"],
                want_lp=prep["want_lp"],
            )
            if self._paged_quant:
                self.paged_cache.k_scale = new_k_scale
                self.paged_cache.v_scale = new_v_scale
        if use_extras:
            self._counts_dev = new_counts
        return chunk, lp, gstate_out

    def _chain_input(self, dev, host_vec):
        """Next chunk's [B] input vector: chained from the previous chunk's
        device output when possible (no host->device upload), with host
        overrides (slots committed since the last dispatch) merged in.

        Host buffers are snapshot-COPIED before upload: jnp.asarray of a
        suitably-aligned numpy array is zero-copy on CPU, and these buffers
        are mutated in place (retire writebacks, commits) while the
        async-dispatched merge may not have read them yet — an alias there
        is a rare wrong-token race, observed in the A/B harness."""
        if dev is None:
            return jnp.asarray(host_vec.copy())
        if self._slot_overrides.any():
            return self._merge_rows_jit(
                dev,
                jnp.asarray(host_vec.copy()),
                jnp.asarray(self._slot_overrides.copy()),
            )
        return dev

    async def _retire_chunk(self, entry: "_InFlightChunk") -> None:
        """Device->host readback + token emission for the OLDEST in-flight
        chunk, running while the next chunk computes. Every anchor point of
        the old serial loop re-lands here: slot frees / EOS handling,
        prefill-gate deposits, the watchdog-epoch check, the quarantine
        release, and (via the caller) the sanitizer audit — admission
        commits follow at the next loop top."""

        def _sync():
            if faults.active():
                # worker-thread stall seam: wedges THIS retire without
                # blocking the event loop, so the watchdog can observe it
                faults.fire(
                    "engine.decode.stall",
                    requests=[r for r in self._slot_req if r is not None],
                )
            chunk_np = np.asarray(entry.chunk)
            # np.array (copy): asarray would alias the immutable device
            # buffer and commit/release paths write rows in place
            gstate_np = (
                np.array(entry.gstate) if entry.gstate is not None else None
            )
            lp_np = (
                tuple(np.asarray(a) for a in entry.lp)
                if entry.lp is not None
                else None
            )
            return chunk_np, gstate_np, lp_np

        t0 = time.perf_counter()
        ready = getattr(entry.chunk, "is_ready", None)
        if not faults.active() and ready is not None and ready():
            # chunk already landed (device ran ahead): the copies are
            # microseconds — skip the worker-thread hop entirely
            chunk_np, gstate_np, lp_np = _sync()
        else:
            chunk_np, gstate_np, lp_np = await asyncio.to_thread(_sync)
        if entry.epoch != self._recover_epoch:
            # the watchdog failed this batch while the pipeline was in
            # flight: every queued chunk is stale — discard them all and
            # reclaim (epoch bump covers the whole in-flight queue).
            # _finish_recovery defers itself while the concurrent dispatch
            # leg is mid-worker; that leg completes recovery on landing.
            await self._finish_recovery()
            return
        if faults.active():
            try:
                # chaos seam: a retire-stage failure (host emission path)
                # with younger chunks possibly still in flight
                faults.fire(
                    "engine.decode.retire",
                    requests=[r for r in self._slot_req if r is not None],
                )
            except faults.InjectedFault as ex:
                if ex.request is None:
                    raise  # batch-wide: loop-level step-failure handling
                self.counters["step_failures"] += 1
                for slot, request in enumerate(self._slot_req):
                    if request is ex.request:
                        self._fail_slot(
                            slot,
                            EngineStepError(
                                "retire failed for this request: {}".format(ex)
                            ),
                        )
                        break
                # fall through: the rest of the chunk still emits
        slots = [int(s) for s in np.nonzero(entry.active_mask)[0]]
        for slot in slots:
            # host mirrors re-anchor at retire (the device chain moved on
            # at dispatch); slots committed after this chunk's dispatch are
            # not in its mask, so fresh state is never clobbered
            self._next_token[slot] = int(chunk_np[slot, -1])
            if gstate_np is not None:
                self._gstate[slot] = int(gstate_np[slot])
        if self._prefill_gate is not None:
            # decode chunk done: grant the next prefill-dispatch budget
            self._prefill_gate.deposit()
        for slot in slots:
            for i, token_id in enumerate(chunk_np[slot]):
                # _emit frees the slot on finish; the rest of the chunk for
                # that slot is dropped by the None check inside _emit
                lp_entry = None
                if lp_np is not None:
                    chosen, top_id, top_lp = lp_np
                    lp_entry = {
                        "id": int(token_id),
                        "logprob": float(chosen[slot, i]),
                        "top_ids": top_id[slot, i].tolist(),
                        "top_logprobs": top_lp[slot, i].tolist(),
                    }
                self._emit(slot, int(token_id), lp_entry)
        self._release_quarantine(entry.seq)
        # promotion completion is a retire-stage event (docs/kv_tiering.md):
        # a DMA that finished while this chunk computed cost the loop nothing
        self._reap_promotions()
        self._last_progress = time.monotonic()
        self._hist_retire.observe((time.perf_counter() - t0) * 1e3)

    async def _spec_step(self, active_mask: np.ndarray, spec_masks,
                         epoch: int) -> None:
        """Serial speculative step (draft-and-verify rounds); the pipeline
        is already drained when this runs. Unchanged semantics from the
        pre-pipelining loop."""
        spec_mask, sspec_mask = spec_masks
        want_lp = any(
            self._slot_req[s] is not None
            and self._slot_req[s].logprobs is not None
            for s in np.nonzero(active_mask)[0]
        )
        sampling = self._batch_sampling()
        # draft-and-verify rounds: device work off-loop, emission on
        # the loop thread like the plain path
        if self.cache_mode == "paged":
            res = await asyncio.to_thread(
                self._dispatch_spec_paged_chunk,
                active_mask, spec_mask, sspec_mask, sampling,
                want_lp,
            )
        else:
            res = await asyncio.to_thread(
                self._dispatch_spec_chunk,
                active_mask, spec_mask, sspec_mask, sampling,
                want_lp,
            )
        if epoch != self._recover_epoch:
            await self._finish_recovery()
            return
        if res is not None:
            gs, accs, pending, lp_np = res
            for r in range(gs.shape[0]):
                for slot in np.nonzero(active_mask)[0]:
                    slot = int(slot)
                    for i in range(int(accs[r, slot]) + 1):
                        entry = None
                        if (
                            lp_np is not None
                            and i == 0
                            and not spec_mask[slot]
                            and not sspec_mask[slot]
                        ):
                            chosen, top_id, top_lp = lp_np
                            entry = {
                                "id": int(gs[r, slot, 0]),
                                "logprob": float(chosen[r, slot]),
                                "top_ids": top_id[r, slot].tolist(),
                                "top_logprobs": top_lp[r, slot].tolist(),
                            }
                        self._emit(slot, int(gs[r, slot, i]), entry)
            for slot in np.nonzero(active_mask)[0]:
                self._next_token[slot] = int(pending[slot])
            if self._prefill_gate is not None:
                self._prefill_gate.deposit()
            self._last_progress = time.monotonic()
            return
        # paged pool couldn't hold the speculative over-allocation: run one
        # plain (serial) chunk for this iteration instead
        await self._dispatch_or_recover(active_mask.copy(), epoch)
        if self._inflight:
            await self._retire_oldest()
